"""Cluster lifecycle + status controllers and the NoExecute taint manager.

Ref:
- cluster-status-controller (pkg/controllers/status/cluster_status_controller.go):
  per-cluster heartbeat — health probe, threshold-adjusted Ready condition
  (:197-206), k8s version + API enablements (:242-258), node/pod informers ->
  ResourceSummary (:260-284).
- cluster-controller (pkg/controllers/cluster/cluster_controller.go:64-93):
  condition->taint conversion (NotReady/Unreachable taint templates).
- taint-manager (pkg/controllers/cluster/taint_manager.go): NoExecute taints
  evict bindings that don't tolerate them (into graceful-eviction tasks when
  the GracefulEviction feature is on).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.cluster import (
    NO_EXECUTE,
    NO_SCHEDULE,
    TAINT_CLUSTER_NOT_READY,
    TAINT_CLUSTER_UNREACHABLE,
    Cluster,
    ResourceSummary,
    Taint,
)
from ..api.core import Condition, set_condition
from ..api.work import (
    EVICTION_PRODUCER_TAINT_MANAGER,
    EVICTION_REASON_TAINT_UNTOLERATED,
    GracefulEvictionTask,
    TargetCluster,
)
from ..utils import DONE, Runtime, Store
from ..utils.features import FAILOVER, GRACEFUL_EVICTION, feature_gate
from ..utils.member import MemberClientRegistry

NOT_READY_TAINT = Taint(key=TAINT_CLUSTER_NOT_READY, effect=NO_SCHEDULE)
NOT_READY_EXECUTE_TAINT = Taint(key=TAINT_CLUSTER_NOT_READY, effect=NO_EXECUTE)
UNREACHABLE_EXECUTE_TAINT = Taint(key=TAINT_CLUSTER_UNREACHABLE, effect=NO_EXECUTE)


class ClusterStatusController:
    """Periodic member heartbeat -> Cluster.Status (run as a runtime ticker)."""

    #: how stale an agent lease may be before a Pull cluster degrades
    #: (ClusterLeaseDuration x renew fraction analogue)
    LEASE_GRACE_SECONDS = 120.0

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members: MemberClientRegistry,
        clock=None,
        lease_grace_seconds: float = LEASE_GRACE_SECONDS,
    ) -> None:
        self.store = store
        self.members = members
        self.clock = clock or time.time
        self.lease_grace = lease_grace_seconds
        runtime.add_ticker(self.collect_all)
        # a lease renewal re-judges its cluster immediately — tickers run in
        # registration order, and the agent's renewal ticker registers after
        # this controller, so without this a recovered agent would stay
        # NotReady for a full extra settle pass
        store.watch("Lease", self._on_lease)

    def _on_lease(self, event) -> None:
        cluster = self.store.get("Cluster", event.obj.meta.name)
        if cluster is not None:
            self.collect(cluster)

    def collect_all(self) -> None:
        for cluster in self.store.list("Cluster"):
            self.collect(cluster)

    def collect(self, cluster: Cluster) -> None:
        from ..utils.faultinject import fault_point

        member = self.members.get(cluster.name)
        # chaos seam (ISSUE 7): an armed `cluster.health=down` rule flips
        # this member NotReady for the firing judgement — the same
        # condition->taint->NoExecute-eviction machinery a real outage
        # drives, replayable from the fault seed
        rule = fault_point("cluster.health", cluster.name)
        forced_down = rule is not None and rule.action == "down"
        if cluster.spec.sync_mode == "Pull":
            # the plane cannot probe Pull members; Ready is lease freshness
            # ALONE (monitorClusterHealth over the agent-renewed Lease) — a
            # dead agent degrades only after the grace period, by design
            lease = self.store.get("Lease", cluster.name)
            ready = (
                lease is not None
                and self.clock() - lease.renew_time < self.lease_grace
                and not forced_down
            )
            reason = "AgentLeaseRenewed" if ready else "AgentLeaseExpired"
        else:
            ready = member is not None and member.reachable and not forced_down
            reason = "ClusterReady" if ready else "ClusterNotReachable"
        # status collection still needs a live client regardless of how
        # Ready was judged
        reachable = (
            member is not None and member.reachable and not forced_down
        )
        changed = set_condition(
            cluster.status.conditions,
            Condition(type="Ready", status=ready, reason=reason),
        )
        if reachable:
            summary_alloc = member.summary_allocatable()
            summary_used = member.summary_allocated()
            new_summary = ResourceSummary(
                allocatable=summary_alloc,
                allocated=summary_used,
                allocatable_modelings=cluster.status.resource_summary.allocatable_modelings,
            )
            if (
                new_summary.allocatable != cluster.status.resource_summary.allocatable
                or new_summary.allocated != cluster.status.resource_summary.allocated
            ):
                cluster.status.resource_summary = new_summary
                changed = True
            if cluster.status.api_enablements != member.api_enablements:
                cluster.status.api_enablements = list(member.api_enablements)
                changed = True
            if cluster.status.kubernetes_version != member.kubernetes_version:
                cluster.status.kubernetes_version = member.kubernetes_version
                changed = True
        if changed:
            self.store.apply(cluster)


class ClusterController:
    """Condition->taint conversion + finalizer-style cleanup."""

    def __init__(self, store: Store, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.new_worker("cluster", self._reconcile)
        store.watch("Cluster", lambda e: self.worker.enqueue(e.key))

    def _reconcile(self, key: str) -> Optional[str]:
        cluster = self.store.get("Cluster", key)
        if cluster is None:
            return DONE
        ready = any(
            c.type == "Ready" and c.status for c in cluster.status.conditions
        )
        taints = [
            t
            for t in cluster.spec.taints
            if t.key not in (TAINT_CLUSTER_NOT_READY, TAINT_CLUSTER_UNREACHABLE)
        ]
        if not ready:
            # UpdateStatusCondition -> taint templates
            # (cluster_controller.go:64-93): NoSchedule immediately; NoExecute
            # drives eviction when cluster Failover is enabled
            taints.append(NOT_READY_TAINT)
            if feature_gate.enabled(FAILOVER):
                taints.append(NOT_READY_EXECUTE_TAINT)
        if taints != cluster.spec.taints:
            cluster.spec.taints = taints
            self.store.apply(cluster)
        return DONE


class TaintManager:
    """NoExecute taints -> evict intolerant bindings
    (cluster/taint_manager.go). With GracefulEviction on, eviction goes
    through spec.gracefulEvictionTasks; otherwise the cluster entry is
    dropped immediately."""

    def __init__(self, store: Store, runtime: Runtime, clock=None) -> None:
        self.store = store
        self.clock = clock or time.time
        self.worker = runtime.new_worker("taint-manager", self._reconcile)
        store.watch("Cluster", lambda e: self.worker.enqueue(e.key))

    def _reconcile(self, key: str) -> Optional[str]:
        cluster = self.store.get("Cluster", key)
        if cluster is None:
            return DONE
        no_execute = [t for t in cluster.spec.taints if t.effect == NO_EXECUTE]
        if not no_execute:
            return DONE
        if not feature_gate.enabled(FAILOVER):
            return DONE
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
          for rb in self.store.list(kind):
            if not any(tc.name == cluster.name for tc in rb.spec.clusters):
                continue
            tolerations = (
                rb.spec.placement.cluster_tolerations if rb.spec.placement else []
            )
            untolerated = [
                t
                for t in no_execute
                if not any(tol.tolerates(t) for tol in tolerations)
            ]
            if not untolerated:
                continue
            evict_binding(
                rb,
                cluster.name,
                reason=EVICTION_REASON_TAINT_UNTOLERATED,
                producer=EVICTION_PRODUCER_TAINT_MANAGER,
                message=f"cluster {cluster.name} has NoExecute taint "
                f"{untolerated[0].key}",
                # the injected clock must stamp eviction tasks, or the
                # timeout-drain math mixes fake and wall time
                now=self.clock(),
            )
            self.store.apply(rb)
        return DONE


def evict_binding(
    rb,
    cluster_name: str,
    *,
    reason: str,
    producer: str,
    message: str = "",
    purge_mode: str = "Graciously",
    grace_period_seconds=None,
    preserved_label_state: Optional[dict] = None,
    now: Optional[float] = None,
) -> None:
    """Move a cluster from spec.clusters into graceful-eviction tasks
    (binding_types_helper GracefulEvictCluster semantics). Without the
    GracefulEviction feature the cluster is dropped outright."""
    target = next((tc for tc in rb.spec.clusters if tc.name == cluster_name), None)
    if target is None:
        return
    rb.spec.clusters = [tc for tc in rb.spec.clusters if tc.name != cluster_name]
    if feature_gate.enabled(GRACEFUL_EVICTION):
        if not any(
            t.from_cluster == cluster_name for t in rb.spec.graceful_eviction_tasks
        ):
            rb.spec.graceful_eviction_tasks.append(
                GracefulEvictionTask(
                    from_cluster=cluster_name,
                    replicas=target.replicas,
                    reason=reason,
                    message=message,
                    producer=producer,
                    purge_mode=purge_mode,
                    grace_period_seconds=grace_period_seconds,
                    creation_timestamp=now if now is not None else time.time(),
                    preserved_label_state=dict(preserved_label_state or {}),
                    clusters_before_failover=[tc.name for tc in rb.spec.clusters]
                    + [cluster_name],
                )
            )
    rb.meta.generation += 1  # spec changed -> scheduler re-runs
