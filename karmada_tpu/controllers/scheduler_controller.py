"""Scheduler process: binding events -> TensorScheduler -> spec.clusters.

Ref: pkg/scheduler/scheduler.go — the event-driven loop (:295-333), the
should-we-schedule gate (doScheduleBinding :346-414: placement changed /
replicas changed / reschedule triggered / not yet scheduled), result patching
(:598-660) and Scheduled conditions (:827-919).

The batched kernel engine (karmada_tpu.scheduler) does the actual work; this
controller packs ResourceBindings into BindingProblems, maintains snapshot
freshness (cluster events invalidate), and writes results + conditions back.
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.core import Condition, set_condition
from ..api.work import SCHEDULED, ResourceBinding, TargetCluster
from ..scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from ..utils import DONE, Runtime, Store

DEFAULT_SCHEDULER = "default-scheduler"

def _takes_dirty_keys(engine) -> bool:
    """Whether ``engine.schedule`` is the genuine tensor-engine method
    (which grew the ``dirty_keys`` kwarg) rather than a sidecar proxy or
    a patched-in double with the narrower legacy signature."""
    return (
        isinstance(engine, TensorScheduler)
        and "schedule" not in vars(engine)
        and type(engine).schedule is _TENSOR_SCHEDULE
    )


_TENSOR_SCHEDULE = TensorScheduler.schedule


def _is_transport_error(exc: Exception) -> bool:
    """Solver-channel failures that trigger the in-proc fallback (grpc is
    imported lazily so in-proc-only deployments never pay for it)."""
    from ..utils.backoff import CircuitBreakerOpen, DeadlineExceeded
    from ..utils.faultinject import FaultError

    if isinstance(
        exc, (CircuitBreakerOpen, DeadlineExceeded, FaultError,
              ConnectionError, TimeoutError)
    ):
        return True
    try:
        import grpc
    except ImportError:  # pragma: no cover — grpc ships in the image
        return False
    return isinstance(exc, grpc.RpcError)


class SchedulerController:
    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        scheduler_name: str = DEFAULT_SCHEDULER,
        extra_estimators=(),
        disabled_plugins=(),
        custom_filters=(),
        clock=None,
        solver=None,
        estimator_registry=None,
    ) -> None:
        self.store = store
        self.runtime = runtime
        self.scheduler_name = scheduler_name
        # the plane's EstimatorRegistry (when accurate estimators feed
        # extra_estimators): cluster events invalidate its memoized
        # estimates so the gRPC path re-queries live member state
        self.estimator_registry = estimator_registry
        # out-of-process solver sidecar (karmada_tpu.solver.RemoteSolver):
        # when set, scheduling goes over its gRPC channel instead of the
        # in-proc engine, with cluster state pushed on cluster events
        self.solver = solver
        self._solver_synced = False
        if solver is not None:
            solver._cluster_source = self._sorted_clusters
        # last_scheduled_time is compared against rescheduleTriggeredAt,
        # which other controllers stamp from the plane clock — both sides
        # must share one time base or Fresh triggers silently degrade
        self.clock = clock or time.time
        self.extra_estimators = list(extra_estimators)
        # --plugins enable/disable list + out-of-tree filter registry
        # (scheduler.go:243-247, framework/runtime/registry.go); both reach
        # the engine on every (re)build so flags apply live
        self.disabled_plugins = tuple(disabled_plugins)
        self.custom_filters = list(custom_filters)
        self._snapshot: Optional[ClusterSnapshot] = None
        self._engine: Optional[TensorScheduler] = None
        # id()s of binding objects whose writeback WE are applying right
        # now: the in-proc store delivers the echo synchronously with the
        # very same object, so identity marks it (one re-gate queue wave
        # per storm saved). Cleared after the batch; a bus-replica's
        # decoded echo has a different identity and just re-gates cheaply.
        self._pending_writeback: set[int] = set()
        # the batch cap bounds ONE engine pass; the device-resident fleet
        # path amortizes per-pass dispatch+fetch costs over the whole batch,
        # so a storm should drain in as few passes as possible
        # quota plane: FRQ events bump the quota generation (the engine's
        # batch-identity replay and the denied-binding retry gate both key
        # on it) and re-enqueue ONLY the denied bindings of the touched
        # namespace — a quota raise clears QuotaExceeded without a full
        # re-pack of the fleet
        self._quota_gen = 0
        self._quota_snapshot = None
        self._quota_snap_gen = -1  # generation the cached snapshot is for
        self._quota_denied: dict[tuple, int] = {}  # (kind, key) -> gen
        # dirty-set plumbing (ISSUE 20): _problem_for answers the CACHED
        # problem object when the rebuilt content is equal, so a steady
        # binding keeps one identity across waves and the engine's
        # batch-identity/delta paths can diff a wave by id(). Keys whose
        # content DID move accumulate per wave in _dirty_problem_keys —
        # the dirty-row set threaded into TensorScheduler.schedule()
        # beside the identity token. Pruned on binding delete.
        self._problem_cache: dict[str, BindingProblem] = {}
        self._dirty_problem_keys: set[str] = set()
        # once-per-transition counter gate (ISSUE 13 satellite): the
        # SHARED dedup behind quota_denied_total AND unschedulable_total
        # — a parked binding re-enqueued across passes within one
        # generation must never double-increment either family
        from ..utils.reasons import TransitionDedup

        self._reason_dedup = TransitionDedup()
        self.worker = runtime.new_worker(
            "scheduler", self._reconcile,
            reconcile_batch=self._reconcile_batch, batch_size=131072,
        )
        store.watch("ResourceBinding", self._on_binding_event)
        store.watch("ClusterResourceBinding", self._on_binding_event)
        store.watch("Cluster", self._on_cluster_event)
        store.watch("FederatedResourceQuota", self._on_quota_event)

    # -- events ------------------------------------------------------------

    def _on_binding_event(self, event) -> None:
        if event.type == "Deleted":
            return
        rb = event.obj
        if rb.spec.scheduler_name != self.scheduler_name:
            return  # scheduler-name filter (event_handler.go:93-113)
        if id(rb) in self._pending_writeback:
            return  # our own writeback echo
        self.worker.enqueue((event.kind, event.key))

    def _on_quota_event(self, event) -> None:
        self._quota_gen += 1
        self._quota_snap_gen = -1  # rebuild the packed snapshot lazily
        ns = event.obj.meta.namespace if event.obj is not None else ""
        for (kind, key), _gen in list(self._quota_denied.items()):
            if not ns or key.split("/", 1)[0] == ns:
                self.worker.enqueue((kind, key))

    def _on_cluster_event(self, event) -> None:
        self._snapshot = None  # invalidate; rebuild lazily
        self._solver_synced = False  # sidecar re-sync before next schedule
        # quota caps pack against the cluster columns: rebuild the quota
        # snapshot against the refreshed cluster snapshot too
        self._quota_snap_gen = -1
        if self.estimator_registry is not None:
            # member state moved: memoized accurate estimates are stale
            # (EstimatorRegistry.invalidate staleness contract)
            self.estimator_registry.invalidate()
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                if rb.spec.scheduler_name == self.scheduler_name:
                    self.worker.enqueue((kind, rb.meta.namespaced_name))

    # -- engine ------------------------------------------------------------

    def _sorted_clusters(self):
        return sorted(self.store.list("Cluster"), key=lambda c: c.name)

    def _get_engine(self):
        if self.solver is not None:
            if not self._solver_synced:
                self.solver.sync_clusters(self._sorted_clusters())
                self._solver_synced = True
            return self.solver
        return self._inproc_engine()

    @staticmethod
    def _quota_enforcement_enabled() -> bool:
        import os

        return os.environ.get(
            "KARMADA_TPU_QUOTA_ENFORCEMENT", "1"
        ).lower() not in ("0", "false", "")

    @staticmethod
    def _preemption_enabled() -> bool:
        """Scarcity-plane kill switch (ISSUE 14): read live per pass so
        flipping KARMADA_TPU_PREEMPTION=0 disarms without a restart."""
        import os

        return os.environ.get(
            "KARMADA_TPU_PREEMPTION", "1"
        ).lower() not in ("0", "false", "")

    def _quota_namespaces(self) -> set:
        """Namespaces carrying an FRQ when enforcement is on (empty =
        the quota plane is inert for routing purposes)."""
        if not self._quota_enforcement_enabled():
            return set()
        return {
            frq.meta.namespace
            for frq in self.store.list("FederatedResourceQuota")
        }

    def _route_engine_for_quota(self, engine, problems=()):
        """The solver sidecar has no quota channel: a wave that must
        enforce quota falls back to the in-proc engine (the same
        degraded-mode seam transport failures use) instead of silently
        scheduling quota'd bindings unbounded. Scoped to the WAVE: only
        waves that actually contain bindings in quota'd namespaces
        reroute — one team's FRQ must not cost every other namespace the
        sidecar."""
        if hasattr(engine, "set_quota"):
            return engine
        quota_ns = self._quota_namespaces()
        if not quota_ns or not any(
            p.namespace in quota_ns for p in problems
        ):
            return engine
        if not getattr(self, "_quota_solver_warned", False):
            self._quota_solver_warned = True
            print(
                "# scheduler: FederatedResourceQuota enforcement is not "
                "supported over the solver sidecar; quota waves take the "
                "in-proc engine (set KARMADA_TPU_QUOTA_ENFORCEMENT=0 to "
                "route them to the sidecar unenforced)",
                flush=True,
            )
        return self._inproc_engine()

    def _route_engine_for_scarcity(self, engine, problems=()):
        """The solver sidecar has no preemption channel either: a wave
        carrying priority>0 bindings reroutes in-proc while preemption is
        armed, scoped exactly like the quota reroute (a priority-free
        wave never costs the sidecar)."""
        if hasattr(engine, "set_preemption"):
            return engine
        if not self._preemption_enabled() or not any(
            getattr(p, "priority", 0) > 0 for p in problems
        ):
            return engine
        if not getattr(self, "_preempt_solver_warned", False):
            self._preempt_solver_warned = True
            print(
                "# scheduler: priority preemption is not supported over "
                "the solver sidecar; priority waves take the in-proc "
                "engine (set KARMADA_TPU_PREEMPTION=0 to route them to "
                "the sidecar without preemption)",
                flush=True,
            )
        return self._inproc_engine()

    def _victim_problems(self, exclude_keys):
        """The resident victim pool the engine's preemption pass selects
        from: every BOUND binding of this scheduler (assigned replicas
        on at least one cluster) that is NOT in the current wave — a
        binding being rescheduled this pass has its capacity in flux and
        is never victimized in the same pass. Kind is remembered so the
        eviction writer can find the object again."""
        out = []
        self._victim_kinds = {}
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                key = rb.meta.namespaced_name
                if (
                    rb.spec.scheduler_name != self.scheduler_name
                    or key in exclude_keys
                    or not rb.spec.clusters
                ):
                    continue
                self._victim_kinds[key] = kind
                out.append(self._problem_for(key, rb, False))
        return out

    def _ensure_engine_quota(self, engine) -> None:
        """Hand the engine a current QuotaSnapshot (None = no FRQs or
        enforcement disabled). In-proc engines only: the solver sidecar
        has no quota channel — _route_engine_for_quota sends quota waves
        to the in-proc path before this runs."""
        if not hasattr(engine, "set_quota"):
            return
        if not self._quota_enforcement_enabled():
            # live kill switch: the engine's quota hook disarms this pass
            # (the packed snapshot cache survives for a re-enable)
            engine.set_quota(None)
            return
        if self._quota_snap_gen != self._quota_gen:
            from ..scheduler.quota import build_quota_snapshot

            qsnap = None
            frqs = self.store.list("FederatedResourceQuota")
            if frqs:
                qsnap = build_quota_snapshot(
                    frqs, engine.snapshot, self._quota_gen,
                    store=self.store,
                )
            self._quota_snapshot = qsnap
            self._quota_snap_gen = self._quota_gen
        engine.set_quota(self._quota_snapshot)

    def _inproc_engine(self):
        """The snapshot-backed in-process engine — the default when no
        sidecar is configured, and the degraded-mode fallback when the
        sidecar channel is down (its breaker open or the RPC failing):
        scheduling never stalls on a dead solver."""
        if self._snapshot is None:
            clusters = self._sorted_clusters()
            snap = ClusterSnapshot(clusters)
            # same cluster set: swap the snapshot in place so the engine's
            # device-resident binding table survives status heartbeats
            # (the informer-cache delta case); rebuild only on join/leave
            if self._engine is not None and self._engine.update_snapshot(snap):
                self._snapshot = snap
            else:
                self._snapshot = snap
                self._engine = TensorScheduler(
                    self._snapshot,
                    extra_estimators=self.extra_estimators,
                    disabled_plugins=self.disabled_plugins,
                    custom_filters=self.custom_filters,
                )
        return self._engine

    # -- reconcile ---------------------------------------------------------

    def _needs_scheduling(self, rb: ResourceBinding) -> tuple[bool, bool]:
        """(should_schedule, fresh). Mirrors doScheduleBinding
        (scheduler.go:346-414)."""
        fresh = False
        if (
            rb.spec.reschedule_triggered_at is not None
            and (
                rb.status.last_scheduled_time is None
                or rb.spec.reschedule_triggered_at > rb.status.last_scheduled_time
            )
        ):
            return True, True
        if rb.status.scheduler_observed_generation != rb.meta.generation:
            return True, False
        sched = next(
            (c for c in rb.status.conditions if c.type == SCHEDULED), None
        )
        if sched is None:
            return True, False  # never attempted
        if not sched.status:
            # unschedulable bindings retry on every re-enqueue (the
            # reference's unschedulable-queue semantics): cluster events
            # re-enqueue the whole plane, so freed capacity — a
            # completed preemption eviction, a scale-down, a node join —
            # re-places a parked victim without any spec change. Quota
            # denials are intercepted BEFORE this gate by the
            # generation-gated _quota_denied park, so a denied binding
            # still retries only on quota movement.
            return True, False
        divided = (
            rb.spec.placement is not None
            and rb.spec.placement.replica_scheduling_type() == "Divided"
        )
        # Duplicated (and non-workload) bindings are always (re)scheduled so
        # cluster-set changes take effect (scheduler.go:393-401); the result
        # write-back below is change-detected, so this stays quiescent.
        if rb.spec.replicas == 0 or not divided:
            return True, False
        # replicas drift vs assignment (scale scheduling)
        assigned = sum(tc.replicas for tc in rb.spec.clusters)
        if rb.spec.clusters and assigned != rb.spec.replicas:
            return True, False
        return False, False

    def _reconcile(self, kind_key) -> Optional[str]:
        results = self._reconcile_batch([kind_key])
        return results.get(kind_key, DONE)

    def _reconcile_batch(self, kind_keys) -> dict:
        """Vectorized drain: gate every queued binding, run ONE engine pass
        over all that need scheduling, write each back. A 100k-binding
        storm becomes chunked kernel batches instead of 100k single-item
        engine invocations (the batch axis is the whole point of the
        tensor scheduler)."""
        from ..utils.metrics import (
            e2e_scheduling_duration,
            schedule_attempts,
            scheduler_pass_seconds,
        )
        from ..utils.tracing import tracer

        out: dict = {}
        todo: list[tuple] = []  # (kind_key, rb, problem, fresh)
        for kind_key in kind_keys:
            kind, key = kind_key
            rb = self.store.get(kind, key)
            if rb is None:
                self._quota_denied.pop(kind_key, None)
                # deleted binding: drop its cached problem so the key's
                # identity cannot alias a later re-creation
                self._problem_cache.pop(key, None)
                self._dirty_problem_keys.discard(key)
                out[kind_key] = DONE
                continue
            should, fresh = self._needs_scheduling(rb)
            # quota-denied retry gate: a denied binding re-schedules on
            # the NEXT quota generation (FRQ spec/usage moved), not every
            # queue wave — and it MUST re-schedule then, even when the
            # generic gate sees nothing to do (a never-placed denied
            # binding has empty spec.clusters and an up-to-date observed
            # generation). An explicit Fresh trigger bypasses the gate.
            denied_at = self._quota_denied.get(kind_key)
            if denied_at is not None and not fresh:
                if (
                    denied_at == self._quota_gen
                    and rb.status.scheduler_observed_generation
                    == rb.meta.generation
                ):
                    # same quota generation AND unchanged binding spec:
                    # stay parked. A spec change (e.g. scaled down to fit)
                    # bumps the generation and must retry immediately —
                    # its own usage is unchanged, so no quota event would
                    # ever unpark it otherwise.
                    out[kind_key] = DONE
                    continue
                should = True  # quota or the binding moved: retry now
            if not should:
                out[kind_key] = DONE
                continue
            todo.append((kind_key, rb, self._problem_for(key, rb, fresh), fresh))
        if not todo:
            return out
        # priority-descending wave ordering (ISSUE 14): higher priority
        # classes solve — and hit batched FIFO quota admission — first;
        # the sort is STABLE, so arrival order (queue order) is preserved
        # inside each class. Priority-free waves (all 0) keep their exact
        # pre-scarcity order, bit-for-bit.
        if any(getattr(p, "priority", 0) for _, _, p, _ in todo):
            todo.sort(
                key=lambda item: -getattr(item[2], "priority", 0)
            )
        start = time.perf_counter()
        # one engine pass = one scheduler.pass span; the fleet/kernel
        # spans (pack/dispatch/device/fetch) nest under it, so a storm
        # wave's solve time decomposes without per-binding bookkeeping
        with tracer.span("scheduler.pass") as sp:
            problems = [p for _, _, p, _ in todo]
            # the wave's dirty-row set: keys whose problem content moved
            # since their cached build (watch-bus spec changes, quota
            # re-enqueues, eviction displacements all land here through
            # _problem_for). Handed to the engine beside the identity
            # token; reset so the next wave reports only ITS churn.
            wave_dirty = self._dirty_problem_keys
            self._dirty_problem_keys = set()
            sp.attrs["dirty_rows"] = len(wave_dirty)

            def _eng_schedule(engine):
                # dirty keys ride only the in-proc tensor engine; a
                # solver-sidecar proxy (or a patched-in test double)
                # keeps its existing contract
                if _takes_dirty_keys(engine):
                    return engine.schedule(problems, dirty_keys=wave_dirty)
                return engine.schedule(problems)

            def _solve_on(engine):
                """One engine pass with the scarcity plane armed for its
                duration only (dry solves and other callers of the same
                engine must never inherit an armed victim source)."""
                self._ensure_engine_quota(engine)
                armed = (
                    hasattr(engine, "set_preemption")
                    and self._preemption_enabled()
                    and any(
                        getattr(p, "priority", 0) > 0 for p in problems
                    )
                )
                if not armed:
                    return _eng_schedule(engine), None
                engine.set_preemption(self._victim_problems)
                try:
                    results = _eng_schedule(engine)
                    return results, getattr(engine, "last_preemption", None)
                finally:
                    engine.set_preemption(None)

            try:
                engine = self._route_engine_for_scarcity(
                    self._route_engine_for_quota(
                        self._get_engine(), problems
                    ),
                    problems,
                )
                results, preemption = _solve_on(engine)
            except Exception as exc:  # noqa: BLE001 — transport triage below
                if self.solver is None or not _is_transport_error(exc):
                    raise
                # degraded mode (unified-resilience contract): a broken
                # solver sidecar fails over to the in-proc engine for this
                # pass — the breaker's half-open probe re-admits the
                # sidecar without operator action, and _solver_synced
                # stays False so recovery re-pushes the snapshot first
                from ..utils.metrics import degraded_passes

                degraded_passes.inc(channel="solver")
                self._solver_synced = False
                sp.attrs["degraded"] = "solver-fallback"
                print(
                    "# scheduler: solver sidecar unavailable "
                    f"({type(exc).__name__}); in-proc solve for this pass",
                    flush=True,
                )
                # the fallback engine may retain a QuotaSnapshot from an
                # earlier quota wave: _solve_on refreshes (or clears) it
                # before solving
                results, preemption = _solve_on(self._inproc_engine())
            sp.attrs["bindings"] = len(todo)
            if preemption is not None and preemption.victims:
                sp.attrs["preempted"] = len(preemption.victims)
        scheduler_pass_seconds.observe(sp.duration)
        per_item = (time.perf_counter() - start) / len(todo)
        # leadership check at the write barrier: a batched engine pass can
        # outlast a lease (first-compile stalls), and the heartbeat seam
        # only fires BETWEEN work items — one storm batch is one item. A
        # plane deposed during the pass must discard its results unwritten
        # (the standby owns the storm now); the keys park for the next
        # leadership. In-proc planes have no heartbeat and skip this.
        hb = getattr(self.runtime, "heartbeat", None)
        if hb is not None and hb() is False:
            for kind_key, _, _, _ in todo:
                self.worker.enqueue(kind_key)
                out[kind_key] = DONE
            return out
        from ..scheduler.quota import QUOTA_EXCEEDED_ERROR

        changed_rbs = []
        for (kind_key, rb, _, fresh), result in zip(todo, results):
            if result.error == QUOTA_EXCEEDED_ERROR:
                self._quota_denied[kind_key] = self._quota_gen
            else:
                self._quota_denied.pop(kind_key, None)
            if self._write_back(rb, result, fresh):
                changed_rbs.append(rb)
            e2e_scheduling_duration.observe(per_item)
            schedule_attempts.inc(
                result="success" if result.success else "error",
                schedule_type="FreshSchedule" if fresh else "ReconcileSchedule",
            )
            out[kind_key] = DONE
        # batched writeback: one locked sweep + one delivery sweep instead
        # of len(changed) apply calls (storm hot path); over a bus facade
        # the same call ships ONE ApplyBatch RPC per KARMADA_TPU_BUS_BATCH
        # bindings (ISSUE 11) instead of len(changed) round-trips
        self._pending_writeback = {id(rb) for rb in changed_rbs}
        try:
            apply_many = getattr(self.store, "apply_many", None)
            if apply_many is not None:
                for rb, err in apply_many(changed_rbs):
                    # per-object admission rejection: surface it (an engine
                    # result the webhook refuses is a bug worth seeing),
                    # the rest of the wave committed
                    print(
                        f"# scheduler writeback rejected for "
                        f"{rb.meta.namespaced_name}: {err}",
                        flush=True,
                    )
            else:
                for rb in changed_rbs:
                    self.store.apply(rb)
        finally:
            self._pending_writeback.clear()
        if preemption is not None and preemption.victims:
            self._evict_preemption_victims(preemption)
        return out

    def _evict_preemption_victims(self, preemption) -> None:
        """Route the pass's selected victims through PR 7's graceful-
        eviction machinery: each assigned cluster becomes a
        ``PreemptedByHigherPriority`` eviction task (preserved-state
        labels ride the task exactly like a failover eviction), the
        victim gets a ``Preempted`` condition naming its displacer, and
        ``karmada_tpu_preemptions_total`` counts once per displacement
        episode (TransitionDedup — a twice-enqueued victim within one
        episode never double-counts; a fresh displacement after a
        successful re-placement counts anew). The spec bump re-enqueues
        the victim, which then reschedules via the existing ranked
        failover path with the evicted clusters excluded."""
        from ..api.work import (
            EVICTION_PRODUCER_PREEMPTION,
            EVICTION_REASON_PREEMPTED,
            PREEMPTED,
        )
        from ..utils.metrics import preemptions_total
        from .cluster import evict_binding

        displacer = next(
            iter(preemption.placed or preemption.still_unschedulable), ""
        )
        now = self.clock()
        changed = []
        for key, placement, _prio in preemption.victims:
            kind = getattr(self, "_victim_kinds", {}).get(
                key, "ResourceBinding"
            )
            rb = self.store.get(kind, key)
            if rb is None or not rb.spec.clusters:
                continue  # vanished or already displaced: nothing to free
            for cluster in list(placement):
                evict_binding(
                    rb,
                    cluster,
                    reason=EVICTION_REASON_PREEMPTED,
                    producer=EVICTION_PRODUCER_PREEMPTION,
                    message=f"preempted by higher-priority {displacer}",
                    now=now,
                )
            set_condition(
                rb.status.conditions,
                Condition(
                    type=PREEMPTED,
                    status=True,
                    reason=EVICTION_REASON_PREEMPTED,
                    message=f"preempted by higher-priority {displacer}",
                ),
            )
            if self._reason_dedup.observe(
                ("preempt", key), EVICTION_REASON_PREEMPTED, None
            ):
                preemptions_total.inc(reason=EVICTION_REASON_PREEMPTED)
            changed.append(rb)
        if not changed:
            return
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            for rb, err in apply_many(changed):
                print(
                    f"# scheduler: preemption eviction rejected for "
                    f"{rb.meta.namespaced_name}: {err}",
                    flush=True,
                )
        else:
            for rb in changed:
                self.store.apply(rb)

    def dry_solve(self, problems, dirty_keys=None) -> list:
        """One engine pass with NO store writes and NO scarcity arming —
        the continuous descheduler's scoring seam (the engine still
        enforces quota, so a drift score can never recommend a placement
        admission would deny). A dry pass must leave NO trace on the
        live plane: the quota working ``remaining`` is restored (a
        scoring pass never debits budget real bindings need) and the
        provenance store is disarmed for its duration (a hypothetical
        fresh-solve capture must not overwrite a binding's real
        decision chain in /debug/explain). ``dirty_keys`` threads the
        caller's known-churn set into the engine's delta path — the
        descheduler's whole-plane scoring rounds replay untouched rows
        from the resident mirrors instead of re-packing the plane."""
        engine = self._route_engine_for_quota(self._get_engine(), problems)
        self._ensure_engine_quota(engine)
        q = getattr(engine, "quota", None)
        saved_remaining = q.remaining.copy() if q is not None else None
        saved_explain = getattr(engine, "explain", None)
        if hasattr(engine, "set_explain"):
            engine.set_explain(None)
        try:
            if _takes_dirty_keys(engine):
                return engine.schedule(problems, dirty_keys=dirty_keys)
            return engine.schedule(problems)
        finally:
            if hasattr(engine, "set_explain"):
                engine.set_explain(saved_explain)
            if q is not None:
                q.remaining = saved_remaining

    def _problem_for(self, key: str, rb: ResourceBinding, fresh: bool) -> BindingProblem:
        """Build the engine problem for ``rb`` — answering the CACHED
        object when the rebuilt content is equal (identity ⇔ content, the
        delta plumbing's contract: the engine diffs waves by id(), so an
        unchanged binding must keep ONE problem object across waves). A
        content move replaces the cache entry and marks the key dirty
        for the wave's dirty-row set."""
        p = self._build_problem(key, rb, fresh)
        cached = self._problem_cache.get(key)
        if cached is not None and cached == p:
            return cached
        self._problem_cache[key] = p
        self._dirty_problem_keys.add(key)
        return p

    def _build_problem(self, key: str, rb: ResourceBinding, fresh: bool) -> BindingProblem:
        return BindingProblem(
            key=key,
            placement=rb.spec.placement,
            replicas=rb.spec.replicas,
            requests=(
                rb.spec.replica_requirements.resource_request
                if rb.spec.replica_requirements
                else {}
            ),
            gvk=rb.spec.resource.gvk,
            prev={tc.name: tc.replicas for tc in rb.spec.clusters},
            evict_clusters=tuple(
                t.from_cluster for t in rb.spec.graceful_eviction_tasks
            ),
            fresh=fresh,
            namespace=rb.meta.namespace or "",
            # getattr: checkpoints written by a pre-scarcity build
            # unpickle without the field (default-0 back-compat)
            priority=getattr(rb.spec, "priority", 0),
            preempt_clusters=tuple(
                t.from_cluster
                for t in rb.spec.graceful_eviction_tasks
                if t.reason == "PreemptedByHigherPriority"
            ),
        )

    def _write_back(self, rb: ResourceBinding, result, fresh: bool = False) -> bool:
        """Mutate ``rb`` from the schedule result; returns whether it
        changed (the batch caller owns the store write). Scheduled=False
        conditions carry a REASONS-taxonomy code (the classified
        unschedulability reason, not free text), and every (binding,
        reason, generation) transition increments
        ``karmada_tpu_unschedulable_total{reason}`` exactly once."""
        before = [(tc.name, tc.replicas) for tc in rb.spec.clusters]
        changed = rb.status.scheduler_observed_generation != rb.meta.generation
        if result.success and fresh and (
            rb.status.last_scheduled_time is None
            or (
                rb.spec.reschedule_triggered_at is not None
                and rb.spec.reschedule_triggered_at
                > rb.status.last_scheduled_time
            )
        ):
            # consume the served Fresh trigger even when the result is
            # unchanged (scheduler.go patches lastScheduledTime on every
            # successful run): a lingering trigger re-marks every later
            # pass Fresh, so e.g. an eviction-displaced binding would
            # re-DIVIDE from scratch instead of scale-up-rescheduling
            # with its surviving placements credited
            rb.status.last_scheduled_time = self.clock()
            changed = True
        if result.success:
            if rb.spec.replicas > 0:
                rb.spec.clusters = [
                    TargetCluster(name=n, replicas=r)
                    for n, r in sorted(result.clusters.items())
                ]
            else:
                # non-workload: all feasible clusters, no replica counts
                rb.spec.clusters = [
                    TargetCluster(name=n) for n in sorted(result.feasible)
                ]
            if [(tc.name, tc.replicas) for tc in rb.spec.clusters] != before:
                changed = True
                rb.status.last_scheduled_time = self.clock()
            rb.status.scheduler_observed_generation = rb.meta.generation
            if rb.status.scheduler_observed_affinity_name != result.affinity_name:
                rb.status.scheduler_observed_affinity_name = result.affinity_name
                changed = True
            if rb.status.last_scheduled_time is None:
                rb.status.last_scheduled_time = self.clock()
                changed = True
            if set_condition(
                rb.status.conditions,
                Condition(type=SCHEDULED, status=True, reason="Success"),
            ):
                changed = True
            # a later denial after a successful schedule is a NEW
            # transition and must count again
            self._reason_dedup.forget(("sched", rb.meta.namespaced_name))
            # a successful (re-)placement closes the displacement
            # episode: the next preemption of this binding counts anew,
            # and the Preempted condition resolves
            self._reason_dedup.forget(("preempt", rb.meta.namespaced_name))
            from ..api.work import PREEMPTED

            for cond in rb.status.conditions:
                if cond.type == PREEMPTED and cond.status:
                    if set_condition(
                        rb.status.conditions,
                        Condition(
                            type=PREEMPTED,
                            status=False,
                            reason="Success",
                            message="re-placed after displacement",
                        ),
                    ):
                        changed = True
                    break
        else:
            from ..scheduler.quota import QUOTA_EXCEEDED_ERROR
            from ..utils.reasons import classify_error

            rb.status.scheduler_observed_generation = rb.meta.generation
            quota_hit = result.error == QUOTA_EXCEEDED_ERROR
            reason = classify_error(result.error)
            if set_condition(
                rb.status.conditions,
                Condition(
                    type=SCHEDULED,
                    status=False,
                    reason=reason,
                    message=result.error,
                ),
            ):
                changed = True
            # counter transitions dedup independently of the condition
            # write: re-classifying message drift must not re-count, and
            # a parked binding re-enqueued across passes within one
            # generation of ITS OWN spec increments exactly once — a
            # quota event that re-denies an unchanged binding is the
            # same ongoing denial, not a new one (the old condition-
            # transition semantics, minus its success-bounce hole)
            if self._reason_dedup.observe(
                ("sched", rb.meta.namespaced_name),
                reason,
                rb.meta.generation,
            ):
                from ..utils.metrics import unschedulable_total

                unschedulable_total.inc(reason=reason)
                if quota_hit:
                    from ..utils.metrics import quota_denied

                    quota_denied.inc(namespace=rb.meta.namespace or "")
        return changed
