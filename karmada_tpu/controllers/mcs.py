"""Multi-cluster service controllers: endpoint collection + dispatch.

Ref:
- mcs ServiceExport controller (pkg/controllers/mcs/service_export_controller.go):
  collect EndpointSlices of exported services from member clusters into the
  control plane (as Works-shadowed EndpointSlice resources labeled with the
  source cluster).
- MultiClusterService controllers (pkg/controllers/multiclusterservice/,
  1,601 LoC): for an MCS CR, ensure the backing service runs in provider
  clusters, then distribute a derived service + collected EndpointSlices to
  consumer clusters (endpointslice-collect + endpointslice-dispatch).
- ServiceImport -> derived service (pkg/controllers/mcs/
  service_import_controller.go): "derived-<name>" service in importing
  clusters backed by the collected slices.
"""

from __future__ import annotations

from typing import Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import Work, WorkSpec
from ..utils import DONE, Runtime, Store
from ..utils.member import MemberClientRegistry, UnreachableError
from .propagation import execution_namespace

SOURCE_CLUSTER_LABEL = "endpointslice.karmada.io/source-cluster"
SERVICE_LABEL = "kubernetes.io/service-name"


def derived_service_name(name: str) -> str:
    return f"derived-{name}"


class ServiceExportController:
    """Collect member EndpointSlices for exported services onto the control
    plane."""

    def __init__(
        self, store: Store, runtime: Runtime, members: MemberClientRegistry
    ) -> None:
        self.store = store
        self.members = members
        self.worker = runtime.new_worker("service-export", self._reconcile)
        store.watch("ServiceExport", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        for se in self.store.list("ServiceExport"):
            self.worker.enqueue(se.meta.namespaced_name)

    def _reconcile(self, key: str) -> Optional[str]:
        se = self.store.get("ServiceExport", key)
        ns, _, name = key.rpartition("/")
        if se is None:
            self._cleanup(ns, name)
            return DONE
        for cluster_name in self.members.names():
            member = self.members.get(cluster_name)
            if member is None or not member.reachable:
                continue
            try:
                slices = [
                    s
                    for s in member.list("discovery.k8s.io/v1/EndpointSlice")
                    if s.meta.namespace == ns
                    and s.meta.labels.get(SERVICE_LABEL) == name
                ]
            except UnreachableError:
                continue
            for s in slices:
                collected = Resource(
                    api_version=s.api_version,
                    kind=s.kind,
                    meta=ObjectMeta(
                        name=f"{cluster_name}-{s.meta.name}",
                        namespace=ns,
                        labels={
                            SERVICE_LABEL: name,
                            SOURCE_CLUSTER_LABEL: cluster_name,
                        },
                    ),
                    spec=dict(s.spec),
                )
                existing = self.store.get(
                    "Resource", f"{ns}/{collected.meta.name}"
                )
                if existing is None or existing.spec != collected.spec:
                    self.store.apply(collected)
        return DONE

    def _cleanup(self, ns: str, name: str) -> None:
        for res in self.store.list("Resource", ns):
            if (
                res.kind == "EndpointSlice"
                and res.meta.labels.get(SERVICE_LABEL) == name
                and SOURCE_CLUSTER_LABEL in res.meta.labels
            ):
                self.store.delete("Resource", res.meta.namespaced_name)


class MultiClusterServiceController:
    """MCS CR -> derived service + endpoint slices into consumer clusters."""

    def __init__(
        self, store: Store, runtime: Runtime, members: MemberClientRegistry
    ) -> None:
        self.store = store
        self.members = members
        self.worker = runtime.new_worker("multiclusterservice", self._reconcile)
        store.watch("MultiClusterService", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        for mcs in self.store.list("MultiClusterService"):
            self.worker.enqueue(mcs.meta.namespaced_name)

    def _reconcile(self, key: str) -> Optional[str]:
        mcs = self.store.get("MultiClusterService", key)
        ns, _, name = key.rpartition("/")
        if mcs is None:
            return DONE
        providers = mcs.provider_names() or list(self.members.names())
        consumers = mcs.consumer_names() or list(self.members.names())

        # 1. collect endpoint slices from provider clusters
        slices: list[Resource] = []
        for cluster_name in providers:
            member = self.members.get(cluster_name)
            if member is None or not member.reachable:
                continue
            try:
                found = [
                    s
                    for s in member.list("discovery.k8s.io/v1/EndpointSlice")
                    if s.meta.namespace == ns
                    and s.meta.labels.get(SERVICE_LABEL) == name
                ]
            except UnreachableError:
                continue
            for s in found:
                slices.append((cluster_name, s))

        # 2. derive the service spec from any provider's service
        svc_spec = {"ports": mcs.spec.ports}
        for cluster_name in providers:
            member = self.members.get(cluster_name)
            if member is None or not member.reachable:
                continue
            svc = member.get("v1/Service", ns, name)
            if svc is not None:
                svc_spec = {**svc.spec, "clusterIP": None}
                break

        # 3. dispatch derived service + slices into consumer clusters
        derived = derived_service_name(name)
        for cluster_name in consumers:
            work_ns = execution_namespace(cluster_name)
            workloads = [
                Resource(
                    api_version="v1",
                    kind="Service",
                    meta=ObjectMeta(name=derived, namespace=ns),
                    spec=dict(svc_spec),
                )
            ]
            for src, s in slices:
                if src == cluster_name:
                    continue  # a cluster doesn't need its own slices back
                workloads.append(
                    Resource(
                        api_version=s.api_version,
                        kind=s.kind,
                        meta=ObjectMeta(
                            name=f"{src}-{s.meta.name}",
                            namespace=ns,
                            labels={
                                SERVICE_LABEL: derived,
                                SOURCE_CLUSTER_LABEL: src,
                            },
                        ),
                        spec=dict(s.spec),
                    )
                )
            wkey = f"{work_ns}/mcs-{ns}.{name}"
            existing = self.store.get("Work", wkey)
            sig = [(w.kind, w.meta.name, w.spec) for w in workloads]
            if existing is not None and [
                (w.kind, w.meta.name, w.spec) for w in existing.spec.workload
            ] == sig:
                continue
            self.store.apply(
                Work(
                    meta=ObjectMeta(name=f"mcs-{ns}.{name}", namespace=work_ns),
                    spec=WorkSpec(workload=workloads),
                )
            )
        return DONE
