"""Per-wave telemetry history: the plane's memory ACROSS waves.

The tracing plane (ISSUE 6 + 10) can explain any single wave; this module
is the third observability layer — history. At every ``end_wave()`` a
sampler captures ONE structured wave row from surfaces that already
exist: the wave's per-phase self seconds from ``wave_summary()``
(stitched across ``KARMADA_TPU_TRACE_PEERS`` when peers are registered),
engine pass stats off the wave's span attributes (rows packed vs
replayed, batched solves, upload/fetch megabytes — the churn-attribution
series the incremental-1M work regresses against), per-channel RPC
counts off the span taxonomy, and compile/queue-depth/device-byte levels
off the metrics registry. Rows live in a lock-disciplined ring
(``KARMADA_TPU_HISTORY_CAP``, default 512 waves; 0 disables sampling
entirely), served as ``/debug/history`` by every ``MetricsServer`` and
aggregated plane-wide by ``karmadactl-tpu top [--watch]``.

Every row field that is a time series is DECLARED in ``HISTORY_SERIES``
with the surface that backs it (``span:<name>`` — a SPAN_NAMES taxonomy
entry — or ``metric:<family>`` — a registered metric family). graftlint
GL009 machine-checks those references and the generated wave-row schema
table in docs/OPERATIONS.md is rendered from the same registry
(``tools/docs_from_bench.py check_history_schema`` fails every doc regen
on drift), so a series can never silently detach from the surface it
claims to read.

Sliding-window digests: the ring IS the window — ``digests(window=N)``
computes p50/p95/p99 per numeric series over the last N rows on demand
(bucket-free: exact quantiles over at most ``cap`` scalars). The
slow-wave flight recorder attaches the breaching wave's row plus the
recent-window digests to its record (``breach_context``), so
``karmadactl-tpu trace analyze`` renders breach-vs-recent-baseline in
one view, offline.

Thread-safety: a row is built COMPLETELY before it enters the ring, and
ring append/eviction/read all run under one lock — a reader can never
observe a torn row, and evictions are counted, never silent (the
tracer-ring discipline). Sampling is telemetry: any failure is logged
and the wave closes normally.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger("karmada_tpu.history")

#: env knobs (registered in utils.flags ENV_FLAGS)
HISTORY_CAP_ENV = "KARMADA_TPU_HISTORY_CAP"
HISTORY_STITCH_ENV = "KARMADA_TPU_HISTORY_STITCH"

_DEFAULT_CAP = 512


@dataclass(frozen=True)
class HistorySeries:
    """One declared wave-row series: ``source`` names the surface the
    value is derived from — ``span:<name>`` (a SPAN_NAMES taxonomy entry;
    the value sums that span family's durations, counts or attributes
    within the wave) or ``metric:<family>`` (a registered metric family;
    the value is a level or per-wave delta of its samples). graftlint
    GL009 validates every reference."""

    name: str
    #: "gauge" = a per-wave level, "counter" = a per-wave count/delta
    kind: str
    source: str
    description: str


#: THE wave-row series registry: every time-series field of a history row
#: must be declared here (identity fields — wave, trace_id, at, proc,
#: stitched — are row keys, not series). The docs wave-row schema table
#: and graftlint GL009 both key on this dict.
HISTORY_SERIES: dict[str, HistorySeries] = {
    s.name: s
    for s in (
        HistorySeries(
            "wall_s", "gauge", "span:settle",
            "wave wall seconds: summed root (settle) span durations — "
            "wave_summary total_s",
        ),
        HistorySeries(
            "coverage", "gauge", "span:settle",
            "fraction of the wave wall attributed to named spans",
        ),
        HistorySeries(
            "coverage_degraded", "gauge", "span:settle",
            "1 when ring evictions dropped spans of this wave (coverage "
            "undercounts; raise KARMADA_TPU_TRACE_CAPACITY)",
        ),
        HistorySeries(
            "spans", "counter", "span:settle",
            "spans recorded for the wave (stitched: across processes)",
        ),
        HistorySeries(
            "dropped", "counter",
            "metric:karmada_tpu_trace_spans_dropped_total",
            "spans of this wave evicted off the tracer ring",
        ),
        HistorySeries(
            "bindings", "counter", "span:scheduler.pass",
            "bindings scheduled: summed `bindings` attrs over the wave's "
            "scheduler.pass spans",
        ),
        HistorySeries(
            "bindings_s", "gauge", "span:scheduler.pass",
            "bindings / wall_s — the wave's scheduling throughput",
        ),
        HistorySeries(
            "solve_batches", "counter", "span:scheduler.solve",
            "batched fleet solves dispatched (scheduler.solve spans + "
            "host-path chunk spans)",
        ),
        HistorySeries(
            "rows_packed", "counter", "span:scheduler.solve",
            "fleet-table rows (re)packed this wave — the churn-"
            "attribution series (summed rows_packed attrs)",
        ),
        HistorySeries(
            "rows_replayed", "counter", "span:scheduler.solve",
            "fleet-table rows served without re-packing (row fingerprint "
            "or batch-identity replay)",
        ),
        HistorySeries(
            "dirty_rows", "counter", "span:scheduler.solve",
            "rows the wave's delta passes dispatched as dirty (summed "
            "dirty_rows attrs; 0 = every pass was full or pure replay)",
        ),
        HistorySeries(
            "upload_mb", "counter", "span:kernel.host",
            "host->device megabytes shipped (state scatter/upload + row "
            "indices; summed upload_mb attrs)",
        ),
        HistorySeries(
            "fetch_mb", "counter", "span:kernel.fetch",
            "device->host megabytes fetched (summed fetch_mb attrs)",
        ),
        HistorySeries(
            "device_s", "gauge", "span:kernel.device",
            "fenced on-device execute seconds within the wave",
        ),
        HistorySeries(
            "compile_s", "gauge", "span:kernel.device",
            "seconds of compile-flagged spans (fresh XLA traces)",
        ),
        HistorySeries(
            "kernel_compiles", "counter",
            "metric:karmada_tpu_kernel_compiles_total",
            "fresh XLA trace signatures dispatched since the previous "
            "sampled wave",
        ),
        HistorySeries(
            "rpc_estimator", "counter", "span:estimator.rpc",
            "estimator-channel client RPCs issued during the wave",
        ),
        HistorySeries(
            "rpc_solver", "counter", "span:solver.rpc",
            "solver-channel client RPCs issued during the wave",
        ),
        HistorySeries(
            "rpc_bus", "counter", "span:bus.rpc",
            "bus-channel client RPC attempts issued during the wave",
        ),
        HistorySeries(
            "queue_depth", "gauge",
            "metric:karmada_tpu_worker_queue_depth",
            "deepest per-worker queue at wave close (work left behind)",
        ),
        HistorySeries(
            "device_bytes", "gauge", "metric:karmada_tpu_device_bytes",
            "resident device bytes at wave close, summed over every "
            "{kind,bucket} ledger sample",
        ),
        HistorySeries(
            "quota_denied", "counter",
            "metric:karmada_tpu_quota_denied_total",
            "bindings newly denied by quota admission since the previous "
            "sampled wave",
        ),
        HistorySeries(
            "unschedulable", "counter",
            "metric:karmada_tpu_unschedulable_total",
            "bindings transitioning to Scheduled=False (any REASONS "
            "code) since the previous sampled wave — the `top` "
            "unschedulable/denied column",
        ),
        HistorySeries(
            "preemptions", "counter",
            "metric:karmada_tpu_preemptions_total",
            "bindings displaced by the scarcity plane since the "
            "previous sampled wave (victims of the preemption kernel + "
            "descheduler drift triggers) — the `top` preempt column",
        ),
        HistorySeries(
            "disruption_budget", "gauge",
            "metric:karmada_tpu_desched_disruption_budget",
            "the continuous descheduler's per-round trigger cap at wave "
            "close (0 = tier disabled)",
        ),
        HistorySeries(
            "disruption_used", "gauge",
            "metric:karmada_tpu_desched_disruption_used",
            "bindings the last drift-rebalance round re-placed (always "
            "<= disruption_budget)",
        ),
        HistorySeries(
            "phases", "gauge", "span:settle",
            "per-phase SELF seconds dict — keys are SPAN_NAMES entries "
            "(digested as phases.<name> sub-series)",
        ),
        HistorySeries(
            "device_bytes_kinds", "gauge",
            "metric:karmada_tpu_device_bytes",
            "resident device bytes by ledger kind dict (slot tables, "
            "packed grid, donated residents, quota caps, ...)",
        ),
    )
}

#: row keys that are identity/context, not series (rendered first in the
#: schema table)
ROW_IDENTITY_FIELDS: tuple = (
    ("wave", "the closed wave id the row describes"),
    ("trace_id", "the wave's plane-unique trace id"),
    ("at", "unix time the row was sampled (wave close)"),
    ("proc", "the sampling process's name (plane/solver/estimator/bus)"),
    ("stitched", "true when the row's phases came from the cross-process "
                 "stitched summary with more than one process actually "
                 "contributing (peers registered AND reachable)"),
)


def _env_cap() -> int:
    raw = os.environ.get(HISTORY_CAP_ENV, "").strip()
    if not raw:
        return _DEFAULT_CAP
    try:
        return max(int(raw), 0)
    except ValueError:
        log.warning("bad %s=%r; using %d", HISTORY_CAP_ENV, raw,
                    _DEFAULT_CAP)
        return _DEFAULT_CAP


def _stitch_enabled() -> bool:
    """Stitched sampling (default on): when peers are registered, each
    wave row's phases come from the cross-process stitched summary —
    one narrowed ``/debug/traces?wave=N`` fetch per peer per wave close.
    ``KARMADA_TPU_HISTORY_STITCH=0`` keeps sampling local-only."""
    return os.environ.get(HISTORY_STITCH_ENV, "1").strip() not in (
        "0", "false", "no",
    )


def _quantile(sorted_vals: list, q: float) -> float:
    """Exact linear-interpolation quantile over a sorted list."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_vals[0])
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class WaveHistory:
    """Ring-capped per-wave telemetry store. One instance rides each
    ``WaveTracer`` (``tracer.history``); the process-wide tracer's
    instance is what ``/debug/history`` and ``karmadactl-tpu top``
    read."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = _env_cap() if cap is None else cap
        self._lock = threading.Lock()
        self._rows: deque = deque()
        self._evicted = 0
        self._sampled = 0
        # cumulative metric totals at the previous sample — counter
        # series sourced from metric families delta against these
        self._last_counters: dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return self.cap > 0

    # -- sampling ----------------------------------------------------------

    def sample(self, tracer_obj, wave: int) -> Optional[dict]:
        """The ``end_wave()`` hook: build one wave row and append it.
        Telemetry discipline: any failure logs and returns None — the
        wave close must never be aborted by its own history."""
        if not self.enabled:
            return None
        try:
            row = self._build_row(tracer_obj, wave)
        except Exception as exc:  # noqa: BLE001 — telemetry never kills
            # a settle; a broken sampler loses the row, not the wave
            log.warning("history sample of wave %s failed: %s", wave, exc)
            return None
        with self._lock:
            self._rows.append(row)
            self._sampled += 1
            while len(self._rows) > self.cap:
                self._rows.popleft()
                self._evicted += 1
        return row

    def _build_row(self, tr, wave: int) -> dict:
        from .metrics import (
            desched_disruption_budget,
            desched_disruption_used,
            device_bytes as device_bytes_gauge,
            kernel_compiles,
            preemptions_total,
            quota_denied,
            trace_spans_dropped,
            unschedulable_total,
            worker_queue_depth,
        )

        summary = None
        stitched = False
        from .tracing import peers

        if peers() and _stitch_enabled():
            try:
                # falls back to the LOCAL summary internally when the
                # stitch comes back empty — either way the returned
                # summary is usable, never recomputed here. The row's
                # stitched flag demands actual cross-process content
                # (>1 contributing process), not merely the stitched
                # SHAPE: peers all down/skipped must read local-only.
                summary = tr.wave_summary(wave, stitched=True)
                stitched = bool(summary.get("stitched")) and (
                    len(summary.get("procs", [])) > 1
                )
            except Exception as exc:  # noqa: BLE001 — peers unreachable:
                # the local summary still makes an honest row
                log.debug("stitched history sample failed: %s", exc)
        if summary is None:
            summary = tr.wave_summary(wave)

        # span-attribute aggregation over the LOCAL ring (engine pass
        # stats ride local span attrs; remote handler spans carry none)
        packed = replayed = bindings = dirty = 0
        upload_mb = fetch_mb = 0.0
        for sp in tr.spans_for(wave):
            if sp.name == "scheduler.pass":
                bindings += int(sp.attrs.get("bindings", 0) or 0)
            elif sp.name == "scheduler.solve":
                packed += int(sp.attrs.get("rows_packed", 0) or 0)
                replayed += int(sp.attrs.get("rows_replayed", 0) or 0)
                dirty += int(sp.attrs.get("dirty_rows", 0) or 0)
            elif sp.name == "kernel.host":
                upload_mb += float(sp.attrs.get("upload_mb", 0.0) or 0.0)
            elif sp.name == "kernel.fetch":
                fetch_mb += float(sp.attrs.get("fetch_mb", 0.0) or 0.0)

        counts = summary.get("span_counts", {})
        wall = float(summary.get("total_s", 0.0))

        def _counter_delta(name: str, metric) -> float:
            # the FIRST observation seeds the baseline and answers 0:
            # process-lifetime totals accrued before sampling started
            # (prewarm compiles, pre-clear() counts) must not land on
            # one row and skew every digest it feeds
            total = sum(metric.samples().values())
            with self._lock:
                prev = self._last_counters.get(name)
                self._last_counters[name] = total
            return max(total - prev, 0.0) if prev is not None else 0.0

        dev_samples = device_bytes_gauge.samples()
        by_kind: dict[str, float] = {}
        for key, v in dev_samples.items():
            kind = dict(key).get("kind", "?")
            by_kind[kind] = by_kind.get(kind, 0.0) + v
        depth_samples = worker_queue_depth.samples()

        row = {
            "wave": wave,
            "trace_id": summary.get("trace_id", ""),
            "at": time.time(),
            "proc": tr.proc,
            "stitched": stitched,
            "wall_s": round(wall, 6),
            "coverage": summary.get("coverage", 0.0),
            "coverage_degraded": bool(summary.get("coverage_degraded")),
            "spans": int(summary.get("spans", 0)),
            "dropped": int(summary.get("dropped", 0) or 0),
            "bindings": bindings,
            "bindings_s": round(bindings / wall, 1) if wall else 0.0,
            "solve_batches": int(
                counts.get("scheduler.solve", 0)
                + counts.get("scheduler.host", 0)
            ),
            "rows_packed": packed,
            "rows_replayed": replayed,
            "dirty_rows": dirty,
            "upload_mb": round(upload_mb, 6),
            "fetch_mb": round(fetch_mb, 6),
            "device_s": float(summary.get("device_s", 0.0)),
            "compile_s": float(summary.get("compile_s", 0.0)),
            "kernel_compiles": int(
                _counter_delta("kernel_compiles", kernel_compiles)
            ),
            "rpc_estimator": int(counts.get("estimator.rpc", 0)),
            "rpc_solver": int(counts.get("solver.rpc", 0)),
            "rpc_bus": int(counts.get("bus.rpc", 0)),
            "queue_depth": int(max(depth_samples.values(), default=0)),
            "device_bytes": int(sum(dev_samples.values())),
            "device_bytes_kinds": {
                k: int(v) for k, v in sorted(by_kind.items())
            },
            "quota_denied": int(
                _counter_delta("quota_denied", quota_denied)
            ),
            "unschedulable": int(
                _counter_delta("unschedulable", unschedulable_total)
            ),
            "preemptions": int(
                _counter_delta("preemptions", preemptions_total)
            ),
            "disruption_budget": int(
                sum(desched_disruption_budget.samples().values())
            ),
            "disruption_used": int(
                sum(desched_disruption_used.samples().values())
            ),
            "phases": dict(summary.get("phases", {})),
        }
        # keep the dropped counter's cumulative bookkeeping moving even
        # though the row carries the per-wave figure from the summary
        _counter_delta("trace_spans_dropped", trace_spans_dropped)
        return row

    # -- reads -------------------------------------------------------------

    def rows(
        self, window: Optional[int] = None, wave: Optional[int] = None
    ) -> list[dict]:
        """Snapshot of the last ``window`` rows (None = all), newest
        last; ``wave`` narrows to one wave id."""
        with self._lock:
            rows = list(self._rows)
        if wave is not None:
            rows = [r for r in rows if r.get("wave") == wave]
        if window is not None and window >= 0:
            rows = rows[-window:] if window else []
        return [dict(r) for r in rows]

    def row_for(self, wave: int) -> Optional[dict]:
        with self._lock:
            for r in reversed(self._rows):
                if r.get("wave") == wave:
                    return dict(r)
        return None

    @property
    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    @property
    def sampled(self) -> int:
        with self._lock:
            return self._sampled

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._evicted = 0
            self._sampled = 0
            self._last_counters.clear()

    def digests(
        self,
        window: Optional[int] = None,
        *,
        rows: Optional[list] = None,
    ) -> dict:
        """p50/p95/p99 per numeric series over the last ``window`` rows
        (the ring is the sliding window — exact quantiles over at most
        ``cap`` scalars, no buckets). ``phases`` digests as
        ``phases.<name>`` sub-series. ``rows`` overrides the window (the
        breach context digests the window EXCLUDING the breaching
        row)."""
        if rows is None:
            rows = self.rows(window)
        values: dict[str, list] = {}
        for r in rows:
            for name, spec in HISTORY_SERIES.items():
                v = r.get(name)
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    values.setdefault(name, []).append(float(v))
            for ph, v in (r.get("phases") or {}).items():
                values.setdefault(f"phases.{ph}", []).append(float(v))
        out: dict[str, dict] = {}
        for name, vals in sorted(values.items()):
            vals.sort()
            out[name] = {
                "n": len(vals),
                "p50": round(_quantile(vals, 0.50), 6),
                "p95": round(_quantile(vals, 0.95), 6),
                "p99": round(_quantile(vals, 0.99), 6),
            }
        return {"window": len(rows), "series": out}

    # -- documents ---------------------------------------------------------

    def debug_doc(
        self,
        window: Optional[int] = None,
        wave: Optional[int] = None,
        *,
        with_digests: bool = True,
        proc: str = "",
    ) -> dict:
        """THE ``/debug/history`` document (one builder so the HTTP
        endpoint, the CLI and the bench can never drift on shape).
        ``?window=N`` paginates to the last N rows; digests cover the
        same window."""
        from .tracing import peers

        rows = self.rows(window=window, wave=wave)
        doc = {
            "proc": proc,
            "cap": self.cap,
            "sampled": self.sampled,
            "evicted": self.evicted,
            # peer endpoints ride along so `top` pointed at ONE process
            # can discover the rest of the plane
            "peers": peers(),
            "rows": rows,
        }
        if with_digests:
            doc["digests"] = self.digests(rows=rows)
        return doc

    def breach_context(self, wave: int) -> Optional[dict]:
        """The flight recorder's history attachment: the breaching
        wave's row plus digests over the recent window EXCLUDING it —
        breach-vs-recent-baseline in one object."""
        row = self.row_for(wave)
        if row is None:
            return None
        recent = [r for r in self.rows() if r.get("wave") != wave]
        return {
            "row": row,
            "recent": self.digests(rows=recent),
        }


def history_for(tracer_obj=None) -> WaveHistory:
    """The history ring of ``tracer_obj`` (default: the process-wide
    tracer) — the instance ``/debug/history`` serves."""
    if tracer_obj is None:
        from .tracing import tracer as tracer_obj
    return tracer_obj.history


# --------------------------------------------------------------------------
# rendering (karmadactl-tpu top, trace analyze, the bench table)
# --------------------------------------------------------------------------


def render_history_table(rows: list[dict], proc: str = "") -> str:
    """The per-wave table ``karmadactl-tpu top`` and the observability
    bench print (the JSON row stays the machine surface)."""
    head = (
        f"{'proc':<10} {'wave':>5} {'wall_s':>8} {'cover':>6} "
        f"{'bind/s':>8} {'packed':>7} {'replay':>7} {'dirty':>7} "
        f"{'cmpl':>4} "
        f"{'up/fetch MB':>12} {'rpc e/s/b':>11} {'devMB':>8} "
        f"{'uns/den':>8} {'pre':>4} {'dis u/b':>8} {'q':>4}"
    )
    lines = [head]
    for r in rows:
        cov = f"{r.get('coverage', 0.0) * 100:.1f}"
        if r.get("coverage_degraded"):
            cov += "!"
        lines.append(
            f"{(r.get('proc') or proc):<10} {r.get('wave', 0):>5} "
            f"{r.get('wall_s', 0.0):>8.3f} {cov:>6} "
            f"{r.get('bindings_s', 0.0):>8.1f} "
            f"{r.get('rows_packed', 0):>7} {r.get('rows_replayed', 0):>7} "
            f"{r.get('dirty_rows', 0):>7} "
            f"{r.get('kernel_compiles', 0):>4} "
            f"{r.get('upload_mb', 0.0):>5.1f}/{r.get('fetch_mb', 0.0):<6.1f} "
            f"{r.get('rpc_estimator', 0)}/{r.get('rpc_solver', 0)}"
            f"/{r.get('rpc_bus', 0):<5} "
            f"{r.get('device_bytes', 0) / 1e6:>8.2f} "
            f"{r.get('unschedulable', 0)}/{r.get('quota_denied', 0):<4} "
            f"{r.get('preemptions', 0):>4} "
            f"{r.get('disruption_used', 0)}/{r.get('disruption_budget', 0):<4} "
            f"{r.get('queue_depth', 0):>4}"
        )
    return "\n".join(lines)


#: the breach table's headline series (phases are appended dynamically)
_BREACH_SERIES = (
    "wall_s", "bindings_s", "coverage", "kernel_compiles", "upload_mb",
    "fetch_mb", "device_bytes", "rpc_bus", "rpc_estimator", "rpc_solver",
)


def render_breach_table(ctx: dict) -> str:
    """Breach-vs-recent-baseline: the breaching wave's row against the
    recent window's p50/p95 — what ``trace analyze`` appends under the
    attribution table when the flight record carries history context."""
    row = ctx.get("row") or {}
    recent = (ctx.get("recent") or {}).get("series", {})
    window = (ctx.get("recent") or {}).get("window", 0)
    lines = [
        f"history: wave {row.get('wave')} vs last {window} wave(s)",
        f"{'series':<28} {'breach':>12} {'p50':>12} {'p95':>12}",
    ]
    phases = sorted(
        (row.get("phases") or {}).items(), key=lambda kv: -kv[1]
    )[:5]
    names = list(_BREACH_SERIES) + [f"phases.{k}" for k, _ in phases]
    for name in names:
        if name.startswith("phases."):
            val = (row.get("phases") or {}).get(name[len("phases."):], 0.0)
        else:
            val = row.get(name, 0.0)
        if isinstance(val, bool):
            val = int(val)
        if not isinstance(val, (int, float)):
            continue
        d = recent.get(name, {})
        lines.append(
            f"{name:<28} {val:>12.3f} {d.get('p50', 0.0):>12.3f} "
            f"{d.get('p95', 0.0):>12.3f}"
        )
    return "\n".join(lines)


def render_history_schema_table() -> str:
    """The docs/OPERATIONS.md wave-row schema table, generated from
    ``ROW_IDENTITY_FIELDS`` + ``HISTORY_SERIES`` so prose can never drift
    from the sampler (tools/docs_from_bench.py writes it between the
    historyschema markers and fails loudly on drift — the env-table
    pattern; graftlint GL009 keeps the ``source`` references honest)."""
    lines = [
        "| field | kind | source | what it carries |",
        "|---|---|---|---|",
    ]
    for name, desc in ROW_IDENTITY_FIELDS:
        lines.append(f"| `{name}` | identity | — | {desc} |")
    for name in sorted(HISTORY_SERIES):
        s = HISTORY_SERIES[name]
        lines.append(
            f"| `{name}` | {s.kind} | `{s.source}` | {s.description} |"
        )
    return "\n".join(lines)
