"""Slow-operation tracing + event recording.

Ref: k8s.io/utils/trace usage (estimator server/estimate.go:37-54 logs
"Estimating" traces over 100ms) and the EventRecorder pattern
(scheduler.go:921-967 — events recorded on both binding and template).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("karmada_tpu.trace")


@dataclass
class Step:
    name: str
    at: float


class Trace:
    """utiltrace.Trace: named steps, logged when total exceeds threshold."""

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[Step] = []

    def step(self, name: str) -> None:
        self.steps.append(Step(name, time.perf_counter()))

    def log_if_long(self, threshold_seconds: float = 0.1) -> Optional[str]:
        total = time.perf_counter() - self.start
        if total < threshold_seconds:
            return None
        parts = [f'"{self.name}" total={total * 1e3:.1f}ms']
        last = self.start
        for s in self.steps:
            parts.append(f"{s.name}={(s.at - last) * 1e3:.1f}ms")
            last = s.at
        msg = " ".join(parts) + (
            " " + " ".join(f"{k}={v}" for k, v in self.fields.items())
            if self.fields
            else ""
        )
        log.info(msg)
        return msg


@dataclass
class Event:
    object_ref: str  # "<kind>/<key>"
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)


class EventRecorder:
    """In-memory event sink (kube EventRecorder seam). Bounded ring."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self.events: list[Event] = []

    def event(self, object_ref: str, type_: str, reason: str, message: str) -> None:
        self.events.append(Event(object_ref, type_, reason, message))
        if len(self.events) > self.capacity:
            self.events = self.events[-self.capacity :]

    def for_object(self, object_ref: str) -> list[Event]:
        return [e for e in self.events if e.object_ref == object_ref]


# shared recorder (cmd binaries each had one; in-proc a single sink suffices)
recorder = EventRecorder()
