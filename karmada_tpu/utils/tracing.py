"""Slow-operation tracing, wave-scoped span tracing, event recording.

Ref: k8s.io/utils/trace usage (estimator server/estimate.go:37-54 logs
"Estimating" traces over 100ms) and the EventRecorder pattern
(scheduler.go:921-967 — events recorded on both binding and template).

The wave tracer (ISSUE 6 tentpole) is the plane-wide form of utiltrace:
a monotonic WAVE id is stamped when new work enters the plane (the
detector's template events, or any settle that finds work queued), and
every instrumented region — controller drains, scheduler passes, fleet
kernel phases, estimator refreshes — records a ``Span`` carrying that
wave id plus a parent span id, so one storm wave reconstructs as a single
tree attributing pack/solve/dispatch/render/status time. Spans live in a
bounded ring (deque), are exported as JSON by ``MetricsServer``'s
``/debug/traces`` endpoint and ``karmadactl-tpu trace dump``, and are
summarized per-phase by ``wave_summary`` (the bench observability tier's
record format).

Thread-safety: the completed-span ring, wave bookkeeping and summaries
mutate/read under one lock; the OPEN-span parent chain is thread-local
(each thread nests its own spans — a span never migrates threads).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("karmada_tpu.trace")


@dataclass
class Step:
    name: str
    at: float


class Trace:
    """utiltrace.Trace: named steps, logged when total exceeds threshold."""

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[Step] = []

    def step(self, name: str) -> None:
        self.steps.append(Step(name, time.perf_counter()))

    def log_if_long(self, threshold_seconds: float = 0.1) -> Optional[str]:
        total = time.perf_counter() - self.start
        if total < threshold_seconds:
            return None
        parts = [f'"{self.name}" total={total * 1e3:.1f}ms']
        last = self.start
        for s in self.steps:
            parts.append(f"{s.name}={(s.at - last) * 1e3:.1f}ms")
            last = s.at
        msg = " ".join(parts) + (
            " " + " ".join(f"{k}={v}" for k, v in self.fields.items())
            if self.fields
            else ""
        )
        log.info(msg)
        return msg


# --------------------------------------------------------------------------
# wave-scoped span tracing
# --------------------------------------------------------------------------


@dataclass
class Span:
    """One timed region of one wave. ``attrs`` may be filled while the
    span is open (the fleet path stamps device/compile attribution onto
    its kernel spans); everything is frozen into the ring at close."""

    name: str
    wave: int
    span_id: int
    parent_id: Optional[int]
    start: float  # perf_counter
    wall: float  # time.time at open (absolute anchor for exports)
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.perf_counter()) - self.start

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "wave": self.wave,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": round(self.start, 6),
            "wall": round(self.wall, 6),
            "duration_s": round(self.duration, 6),
            "attrs": dict(self.attrs),
        }


class WaveTracer:
    """Ring-buffered, thread-safe, nestable span recorder keyed by wave.

    Wave lifecycle: ``ensure_wave(reason)`` opens a wave if none is open
    (the detector stamps one per user-event burst; ``run_until_settled``
    stamps one for any other work source) and ``end_wave()`` closes it
    when the plane reaches quiescence — so one storm, however triggered,
    is one wave id across every controller it touches."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._wave_seq = itertools.count(1)
        self._span_seq = itertools.count(1)
        self._local = threading.local()
        self.current_wave = 0
        self._wave_open = False
        self._wave_reason = ""
        self._wave_started = 0.0

    # -- waves -------------------------------------------------------------

    # called-with-lock-held helper (the *_locked naming convention):
    # begin_wave/ensure_wave hold self._lock around it
    def _begin_wave_locked(self, reason: str) -> int:  # graftlint: disable=GL004
        self.current_wave = next(self._wave_seq)
        self._wave_open = True
        self._wave_reason = reason
        self._wave_started = time.perf_counter()
        return self.current_wave

    def begin_wave(self, reason: str = "") -> int:
        with self._lock:
            return self._begin_wave_locked(reason)

    def ensure_wave(self, reason: str = "") -> int:
        # ONE critical section for check-and-open: two threads racing
        # (detector event on the bus watch thread vs the serve loop's
        # settle) must agree on a single wave id for one burst
        with self._lock:
            if self._wave_open:
                return self.current_wave
            return self._begin_wave_locked(reason)

    def end_wave(self) -> None:
        with self._lock:
            self._wave_open = False

    # -- spans -------------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    @contextmanager
    def span(self, name: str, **attrs):
        """Record a span under the current wave, nested under this
        thread's innermost open span. Yields the ``Span`` so callers can
        stamp attrs (``kind="device"``, ``compile=True``) mid-flight."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(
            name=name,
            wave=self.current_wave,
            span_id=next(self._span_seq),
            parent_id=parent,
            start=time.perf_counter(),
            wall=time.time(),
            attrs=dict(attrs),
        )
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = time.perf_counter()
            stack.pop()
            # a span the caller marked _discard never reaches the ring
            # (speculative spans around drains that turned out empty)
            if not sp.attrs.pop("_discard", False):
                with self._lock:
                    self._spans.append(sp)

    def record(self, name: str, duration: float, **attrs) -> Span:
        """Append an already-measured region as a COMPLETED span (ending
        now), nested under this thread's innermost open span — for code
        that times its phases with perf_counter deltas (the fleet pass
        breakdown) rather than nesting context managers."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        now = time.perf_counter()
        sp = Span(
            name=name,
            wave=self.current_wave,
            span_id=next(self._span_seq),
            parent_id=parent,
            start=now - duration,
            wall=time.time() - duration,
            end=now,
            attrs=dict(attrs),
        )
        with self._lock:
            self._spans.append(sp)
        return sp

    # -- export ------------------------------------------------------------

    def dump(self, wave: Optional[int] = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if wave is not None:
            spans = [s for s in spans if s.wave == wave]
        return [s.to_json() for s in spans]

    def waves(self) -> list[int]:
        with self._lock:
            return sorted({s.wave for s in self._spans})

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._wave_open = False

    def wave_summary(self, wave: Optional[int] = None) -> dict:
        """Per-phase attribution of one wave (default: the latest one
        with spans): ``total_s`` sums the wave's ROOT spans (parentless —
        the settle drains), ``phases`` maps span name -> summed SELF time
        (duration minus direct children), and ``coverage`` is attributed/
        total (1.0 by construction unless spans fell off the ring). The
        bench observability tier compares ``total_s`` against the
        externally measured wave wall clock for the >=95% criterion."""
        with self._lock:
            spans = list(self._spans)
        if wave is None:
            wave = max((s.wave for s in spans), default=0)
        spans = [s for s in spans if s.wave == wave and s.end is not None]
        by_id = {s.span_id: s for s in spans}
        child_time: dict[int, float] = {}
        for s in spans:
            if s.parent_id is not None and s.parent_id in by_id:
                child_time[s.parent_id] = (
                    child_time.get(s.parent_id, 0.0) + s.duration
                )
        roots = [
            s for s in spans
            if s.parent_id is None or s.parent_id not in by_id
        ]
        total = sum(s.duration for s in roots)
        phases: dict[str, float] = {}
        counts: dict[str, int] = {}
        device = compile_s = 0.0
        for s in spans:
            self_time = max(s.duration - child_time.get(s.span_id, 0.0), 0.0)
            phases[s.name] = phases.get(s.name, 0.0) + self_time
            counts[s.name] = counts.get(s.name, 0) + 1
            if s.attrs.get("kind") == "device":
                device += s.duration
            # compile attribution is a FLAG, not a kind: a synchronous
            # backend compiles inside the dispatch window, an async
            # tunnel inside the device fence — the fleet marks both spans
            # of a fresh-trace pass, so compile_s upper-bounds the
            # compile-bearing time on either backend
            if s.attrs.get("compile"):
                compile_s += s.duration
        attributed = sum(phases.values())
        return {
            "wave": wave,
            "total_s": round(total, 6),
            "coverage": round(attributed / total, 4) if total else 0.0,
            "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
            "span_counts": dict(sorted(counts.items())),
            "device_s": round(device, 6),
            "compile_s": round(compile_s, 6),
            "host_s": round(max(attributed - device, 0.0), 6),
            "spans": len(spans),
        }

    def wave_summaries(self, last: int = 8) -> list[dict]:
        return [self.wave_summary(w) for w in self.waves()[-last:]]


#: the process-wide tracer (one ring per process, like the metrics
#: registry; MetricsServer and the CLI dump read THIS instance)
tracer = WaveTracer()


# --------------------------------------------------------------------------
# events
# --------------------------------------------------------------------------


@dataclass
class Event:
    object_ref: str  # "<kind>/<key>"
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)


class EventRecorder:
    """In-memory event sink (kube EventRecorder seam). Bounded ring —
    ``deque(maxlen=...)`` so append-at-capacity is O(1) and atomic, with
    a lock over append/snapshot: the shared global ``recorder`` is written
    by every controller thread and read by status surfaces concurrently."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    @property
    def events(self) -> list[Event]:
        """Snapshot (consumers iterate/filter freely; the historical
        attribute was a mutable list — a snapshot keeps that read
        contract race-free)."""
        with self._lock:
            return list(self._events)

    def event(self, object_ref: str, type_: str, reason: str, message: str) -> None:
        with self._lock:
            self._events.append(Event(object_ref, type_, reason, message))

    def for_object(self, object_ref: str) -> list[Event]:
        with self._lock:
            return [e for e in self._events if e.object_ref == object_ref]


# shared recorder (cmd binaries each had one; in-proc a single sink suffices)
recorder = EventRecorder()
