"""Slow-operation tracing, wave-scoped span tracing, event recording.

Ref: k8s.io/utils/trace usage (estimator server/estimate.go:37-54 logs
"Estimating" traces over 100ms) and the EventRecorder pattern
(scheduler.go:921-967 — events recorded on both binding and template).

The wave tracer (ISSUE 6 tentpole) is the plane-wide form of utiltrace:
a monotonic WAVE id is stamped when new work enters the plane (the
detector's template events, or any settle that finds work queued), and
every instrumented region — controller drains, scheduler passes, fleet
kernel phases, estimator refreshes — records a ``Span`` carrying that
wave id plus a parent span id, so one storm wave reconstructs as a single
tree attributing pack/solve/dispatch/render/status time. Spans live in a
bounded ring, are exported as JSON by ``MetricsServer``'s
``/debug/traces`` endpoint and ``karmadactl-tpu trace dump``, and are
summarized per-phase by ``wave_summary`` (the bench observability tier's
record format).

Cross-process propagation (ISSUE 10 tentpole): every wave mints a
plane-unique ``trace_id``; the three transport seams (estimator, solver,
bus) stamp ``(wave, trace_id, client span id, caller process)`` into gRPC
metadata on each RPC, and the serving process records its handler spans
(``estimator.serve``, ``solver.solve``, ``bus.apply``...) under the
CALLER's wave/trace with the caller's span id as ``remote_parent`` — so a
storm wave's trace no longer dies at a process boundary. The stitcher
(``stitch_dumps`` / ``karmadactl-tpu trace dump --stitch``) pulls
``/debug/traces`` from every registered peer's metrics port, merges by
``(trace_id, wave)``, re-parents each remote root under its originating
client span, and computes per-process and per-channel self-time columns —
``client span − remote root`` per RPC is the network/serialization time
no single-process view can produce.

The slow-wave flight recorder rides ``end_wave()``: armed by
``KARMADA_TPU_TRACE_SLO_SECONDS``, a closing wave whose wall exceeds the
SLO — or during which a breaker transition, degraded pass or QuotaExceeded
denial fired — persists the full stitched trace + a metrics-registry delta
+ the fired fault-injection log as one JSONL record under
``KARMADA_TPU_FLIGHT_DIR`` (ring-capped on disk);
``karmadactl-tpu trace analyze`` re-renders the attribution offline.

Thread-safety: the completed-span ring, wave bookkeeping and summaries
mutate/read under one lock; the OPEN-span parent chain is thread-local
(each thread nests its own spans — a span never migrates threads), and an
*ambient* thread-local context carries the wave/trace/parent triple onto
executor threads (fan-out pools) and into server handlers.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("karmada_tpu.trace")

#: env knobs (registered in utils.flags ENV_FLAGS)
TRACE_CAPACITY_ENV = "KARMADA_TPU_TRACE_CAPACITY"
TRACE_SLO_ENV = "KARMADA_TPU_TRACE_SLO_SECONDS"
FLIGHT_DIR_ENV = "KARMADA_TPU_FLIGHT_DIR"
FLIGHT_CAP_ENV = "KARMADA_TPU_FLIGHT_CAP"
TRACE_PEERS_ENV = "KARMADA_TPU_TRACE_PEERS"

_DEFAULT_CAPACITY = 8192
_DEFAULT_FLIGHT_CAP = 64


# --------------------------------------------------------------------------
# span-name registry (graftlint GL008 + the docs span-taxonomy table)
# --------------------------------------------------------------------------

#: THE span taxonomy: every span name recorded anywhere in the import
#: graph must appear here (graftlint GL008 enforces it — the stitcher's
#: channel attribution and the generated docs table key on these names).
#: A ``*`` suffix registers a dynamic family (``controller.<worker>``).
SPAN_NAMES: dict[str, str] = {
    "settle": "one run_until_settled drain — the wave's root span",
    "controller.*": "one contiguous drain of one controller worker",
    "scheduler.pass": "one engine pass over a queued binding batch",
    "scheduler.pack": (
        "host prologue of a pass: placement compile + spread selection + "
        "eligibility partition"
    ),
    "scheduler.host": "host-path (non-fleet) scheduling of a batch",
    "scheduler.solve": "one fleet-table solve pass",
    "scheduler.explain": (
        "armed-only provenance capture of a pass: per-stage mask "
        "composition + the batched explain dispatch (ISSUE 13)"
    ),
    "scheduler.preempt": (
        "armed-only preemption round of a pass: plane-wide victim "
        "selection + the boosted same-pass re-solve (ISSUE 14)"
    ),
    "kernel.host": "kernel host phases: pack/upsert/sync/decode",
    "kernel.dispatch": (
        "kernel dispatch window (sync backends execute inside it; "
        "compile=true on a fresh-trace pass)"
    ),
    "kernel.device": (
        "fenced on-device execute window (compile=true when the pass "
        "minted a fresh XLA trace)"
    ),
    "kernel.fetch": "post-device wire transfer + decode + entry folds",
    "estimator.refresh": (
        "one estimator-registry refresh: generation pings + grouped "
        "profile fan-out"
    ),
    "estimator.rpc": (
        "client side of one estimator-channel RPC (remote=true; "
        "peer/method attrs)"
    ),
    "estimator.serve": (
        "server side of one estimator RPC, recorded in the estimator "
        "process under the CALLER's wave"
    ),
    "solver.rpc": "client side of one solver-sidecar RPC (remote=true)",
    "solver.solve": (
        "server side of ScoreAndAssign, recorded in the sidecar under "
        "the caller's wave"
    ),
    "solver.sync": (
        "server side of SyncClusters, recorded in the sidecar under the "
        "caller's wave"
    ),
    "bus.rpc": (
        "client side of one store-bus write-through RPC attempt (batched "
        "calls carry a batch=N attribute — the channel table's "
        "events-per-message column)"
    ),
    "bus.apply": (
        "server side of one bus Apply, recorded in the bus process under "
        "the caller's wave"
    ),
    "bus.apply_batch": (
        "server side of one bus ApplyBatch (ops=N write set committed as "
        "one batched store sweep)"
    ),
    "bus.delete": "server side of one bus Delete",
    "bus.watch": (
        "server side of one Watch replay (list-then-watch initial sync), "
        "up to the bookmark"
    ),
    "channel.breaker": (
        "a circuit-breaker state transition (zero-duration marker span)"
    ),
}


def span_name_registered(name: str) -> bool:
    """True when ``name`` is in the taxonomy, directly or via a ``*``
    family (``controller.scheduler`` matches ``controller.*``)."""
    if name in SPAN_NAMES:
        return True
    return any(
        name.startswith(k[:-1])
        for k in SPAN_NAMES
        if k.endswith("*")
    )


def render_span_table() -> str:
    """The docs/OPERATIONS.md span-taxonomy table, generated from
    ``SPAN_NAMES`` so prose can never drift from the registry the linter
    and the stitcher enforce (tools/docs_from_bench.py writes it between
    the spantaxonomy markers and fails loudly on drift)."""
    lines = [
        "| span | what it times |",
        "|---|---|",
    ]
    for name in sorted(SPAN_NAMES):
        lines.append(f"| `{name}` | {SPAN_NAMES[name]} |")
    return "\n".join(lines)


@dataclass
class Step:
    name: str
    at: float


class Trace:
    """utiltrace.Trace: named steps, logged when total exceeds threshold."""

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[Step] = []

    def step(self, name: str) -> None:
        self.steps.append(Step(name, time.perf_counter()))

    def log_if_long(self, threshold_seconds: float = 0.1) -> Optional[str]:
        total = time.perf_counter() - self.start
        if total < threshold_seconds:
            return None
        parts = [f'"{self.name}" total={total * 1e3:.1f}ms']
        last = self.start
        for s in self.steps:
            parts.append(f"{s.name}={(s.at - last) * 1e3:.1f}ms")
            last = s.at
        msg = " ".join(parts) + (
            " " + " ".join(f"{k}={v}" for k, v in self.fields.items())
            if self.fields
            else ""
        )
        log.info(msg)
        return msg


# --------------------------------------------------------------------------
# trace context + wire metadata
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class TraceContext:
    """The propagated triple + the caller's process name: what crosses a
    channel so the remote ``WaveTracer`` records under the caller's wave."""

    wave: int
    trace_id: str
    span_id: Optional[int]
    proc: str


#: gRPC metadata keys carrying the context (lowercase per gRPC rules)
MD_WAVE = "karmada-tpu-wave"
MD_TRACE = "karmada-tpu-trace"
MD_SPAN = "karmada-tpu-span"
MD_PROC = "karmada-tpu-proc"


def trace_metadata(ctx: Optional[TraceContext]) -> tuple:
    """``ctx`` as gRPC invocation metadata pairs (empty when no context —
    callers splice this into the stub call unconditionally)."""
    if ctx is None or not ctx.trace_id:
        return ()
    return (
        (MD_WAVE, str(ctx.wave)),
        (MD_TRACE, ctx.trace_id),
        (MD_SPAN, "" if ctx.span_id is None else str(ctx.span_id)),
        (MD_PROC, ctx.proc),
    )


def decode_trace_metadata(pairs) -> Optional[TraceContext]:
    """Decode a server handler's invocation metadata back to a context.
    Tolerant: absent or malformed values answer None (an untraced caller
    must never fail the RPC)."""
    if not pairs:
        return None
    md = {}
    try:
        for k, v in pairs:
            md[str(k).lower()] = v
    except (TypeError, ValueError):
        return None
    trace_id = md.get(MD_TRACE, "")
    if not trace_id:
        return None
    try:
        wave = int(md.get(MD_WAVE, "0") or 0)
    except ValueError:
        return None
    raw_span = md.get(MD_SPAN, "")
    span_id: Optional[int] = None
    if raw_span:
        try:
            span_id = int(raw_span)
        except ValueError:
            return None
    return TraceContext(
        wave=wave, trace_id=str(trace_id), span_id=span_id,
        proc=str(md.get(MD_PROC, "") or "peer"),
    )


# --------------------------------------------------------------------------
# wave-scoped span tracing
# --------------------------------------------------------------------------


@dataclass
class Span:
    """One timed region of one wave. ``attrs`` may be filled while the
    span is open (the fleet path stamps device/compile attribution onto
    its kernel spans); everything is frozen into the ring at close."""

    name: str
    wave: int
    span_id: int
    parent_id: Optional[int]
    start: float  # perf_counter
    wall: float  # time.time at open (absolute anchor for exports)
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)
    trace_id: str = ""

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.perf_counter()) - self.start

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "wave": self.wave,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start": round(self.start, 6),
            "wall": round(self.wall, 6),
            "duration_s": round(self.duration, 6),
            "attrs": dict(self.attrs),
        }


def _env_capacity() -> int:
    raw = os.environ.get(TRACE_CAPACITY_ENV, "").strip()
    if not raw:
        return _DEFAULT_CAPACITY
    try:
        return max(int(raw), 16)
    except ValueError:
        log.warning("bad %s=%r; using %d", TRACE_CAPACITY_ENV, raw,
                    _DEFAULT_CAPACITY)
        return _DEFAULT_CAPACITY


class WaveTracer:
    """Ring-buffered, thread-safe, nestable span recorder keyed by wave.

    Wave lifecycle: ``ensure_wave(reason)`` opens a wave if none is open
    (the detector stamps one per user-event burst; ``run_until_settled``
    stamps one for any other work source) and ``end_wave()`` closes it
    when the plane reaches quiescence — so one storm, however triggered,
    is one wave id across every controller it touches. Every wave mints a
    plane-unique ``trace_id``; spans stamp (wave, trace_id) ONCE at open,
    under the lock — a span opened before ``end_wave()`` but closed after
    stays attributed to the wave it opened under, never to a since-reused
    id."""

    def __init__(self, capacity: Optional[int] = None):
        # capacity: explicit argument wins; else KARMADA_TPU_TRACE_CAPACITY
        # (the 1M-tier storms outgrow the 8192 default — evictions are
        # counted, never silent)
        self.capacity = _env_capacity() if capacity is None else capacity
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque()
        self._wave_seq = itertools.count(1)
        self._span_seq = itertools.count(1)
        self._local = threading.local()
        self.current_wave = 0
        self._wave_open = False
        self._wave_reason = ""
        self._wave_started = 0.0
        #: process name stamped on exports + propagated in metadata (the
        #: stitcher keys processes on it); entrypoints override via
        #: set_process ("solver", "estimator", "bus", "agent")
        self.proc = "plane"
        # wave -> trace_id (bounded: old waves age out with the ring)
        self._trace_ids: dict[int, str] = {}
        # ring-eviction accounting (ISSUE 10 satellite): total + per-wave
        self._dropped_total = 0
        self._dropped_by_wave: dict[int, int] = {}
        self._dropped_counter = None  # lazy karmada_tpu_trace_spans_dropped
        # flight-recorder baseline captured at begin_wave when armed
        self._flight_baseline: Optional[dict] = None
        # per-tracer wave-history ring (utils.history), built lazily so
        # the tracer stays importable without the sampler
        self._history = None
        # one-shot (wave, stitched doc) handoff: the history sampler
        # stitches at wave close, and a flight record firing for the
        # SAME close consumes the result instead of re-fetching every
        # peer — a breaching wave pays the stitch once
        self._stitch_handoff = None

    def set_process(self, name: str) -> None:
        with self._lock:
            self.proc = name

    # -- waves -------------------------------------------------------------

    # called-with-lock-held helper (the *_locked naming convention):
    # begin_wave/ensure_wave hold self._lock around it
    def _begin_wave_locked(self, reason: str) -> int:  # graftlint: disable=GL004
        self.current_wave = next(self._wave_seq)
        self._wave_open = True
        self._wave_reason = reason
        self._wave_started = time.perf_counter()
        self._trace_ids[self.current_wave] = uuid.uuid4().hex[:16]
        if len(self._trace_ids) > 512:
            for w in sorted(self._trace_ids)[:-256]:
                del self._trace_ids[w]
                self._dropped_by_wave.pop(w, None)
        return self.current_wave

    def begin_wave(self, reason: str = "") -> int:
        with self._lock:
            wave = self._begin_wave_locked(reason)
        self._flight_begin(wave)
        return wave

    def ensure_wave(self, reason: str = "") -> int:
        # ONE critical section for check-and-open: two threads racing
        # (detector event on the bus watch thread vs the serve loop's
        # settle) must agree on a single wave id for one burst
        with self._lock:
            if self._wave_open:
                return self.current_wave
            wave = self._begin_wave_locked(reason)
        self._flight_begin(wave)
        return wave

    def open_wave(self) -> Optional[int]:
        """The wave currently open, or None. Measurement harnesses use
        this to anchor a window: work they trigger joins the OPEN wave
        when a previous burst's tail kept it open, so a wave-id diff
        alone would miss it."""
        with self._lock:
            return self.current_wave if self._wave_open else None

    def end_wave(self) -> int:
        """Close the open wave and return its id — the flight recorder
        (and tests) key on the CLOSED id, not on whatever wave is current
        by the time they run. The history sampler runs FIRST so a flight
        record of the same close can attach the freshly sampled row
        (utils.history.breach_context)."""
        with self._lock:
            closed = self.current_wave
            was_open = self._wave_open
            self._wave_open = False
        if was_open:
            self.history.sample(self, closed)
            try:
                maybe_flight_record(self, closed)
            except Exception as exc:  # noqa: BLE001 — the recorder must
                # never abort a settle; a broken disk loses the record,
                # not the wave
                log.warning("flight recorder failed: %s", exc)
        return closed

    @property
    def history(self):
        """This tracer's per-wave telemetry ring (utils.history.
        WaveHistory) — the process-wide tracer's instance backs
        ``/debug/history`` and ``karmadactl-tpu top``."""
        # double-checked locking: the unlocked fast-path read is the
        # point (every span close consults the ring); the locked
        # re-check makes the one-time publication race-free
        if self._history is None:  # graftlint: disable=GL011
            from .history import WaveHistory

            fresh = WaveHistory()
            with self._lock:
                if self._history is None:
                    self._history = fresh
        return self._history  # graftlint: disable=GL011

    def wave_trace_id(self, wave: Optional[int] = None) -> str:
        with self._lock:
            if wave is None:
                wave = self.current_wave
            return self._trace_ids.get(wave, "")

    # -- flight-recorder baseline -----------------------------------------

    def _flight_begin(self, wave: int) -> None:
        """Capture the metrics/fault baseline for ``wave`` when the flight
        recorder is armed (KARMADA_TPU_TRACE_SLO_SECONDS set). Disarmed —
        the default — this is one env read per WAVE, nothing per span."""
        if flight_slo() is None:
            return
        baseline = flight_baseline(wave)
        with self._lock:
            self._flight_baseline = baseline

    def flight_baseline_for(self, wave: int) -> Optional[dict]:
        with self._lock:
            b = self._flight_baseline
        return b if (b is not None and b.get("wave") == wave) else None

    def consume_stitch_handoff(self, wave: int) -> Optional[dict]:
        """Take (one-shot) the stitched doc the history sampler built
        for ``wave`` at this close — None when sampling was local-only
        or the handoff belongs to another wave."""
        with self._lock:
            handoff = self._stitch_handoff
            self._stitch_handoff = None
        if handoff is not None and handoff[0] == wave:
            return handoff[1]
        return None

    # -- spans -------------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def _open_ctx(self) -> tuple[int, str, Optional[int]]:
        """(wave, trace_id, parent span id) for a span opening NOW on this
        thread: innermost open span wins, then the thread's ambient
        context (executor tasks / server handlers), then the process-wide
        current wave — read under the lock, stamped exactly once."""
        stack = self._stack()
        if stack:
            top = stack[-1]
            return top.wave, top.trace_id, top.span_id
        amb = getattr(self._local, "ambient", None)
        if amb is not None:
            return amb.wave, amb.trace_id, amb.span_id
        with self._lock:
            return (
                self.current_wave,
                self._trace_ids.get(self.current_wave, ""),
                None,
            )

    def current_context(self) -> TraceContext:
        """The context a CLIENT seam propagates: the innermost open span
        (or ambient context) of this thread, else the current wave."""
        wave, trace_id, parent = self._open_ctx()
        # self.proc is set once at entrypoint boot (set_process) before
        # any span flows; the client-seam read stays deliberately
        # lock-free on the span hot path
        return TraceContext(
            wave=wave, trace_id=trace_id, span_id=parent,
            proc=self.proc,  # graftlint: disable=GL011
        )

    @contextmanager
    def activate(self, ctx: Optional[TraceContext]):
        """Install ``ctx`` as this thread's ambient context: spans opened
        with no local parent nest under ``ctx.span_id``'s wave/trace.
        THE cross-thread propagation primitive — fan-out executors capture
        ``current_context()`` before submit and activate it in the task."""
        if ctx is None:
            yield
            return
        prev = getattr(self._local, "ambient", None)
        self._local.ambient = ctx
        try:
            yield
        finally:
            self._local.ambient = prev

    def _append(self, sp: Span) -> None:
        """Ring append with counted eviction (called with the lock NOT
        held)."""
        dropped: Optional[Span] = None
        with self._lock:
            if len(self._spans) >= self.capacity:
                dropped = self._spans.popleft()
                self._dropped_total += 1
                self._dropped_by_wave[dropped.wave] = (
                    self._dropped_by_wave.get(dropped.wave, 0) + 1
                )
            self._spans.append(sp)
        if dropped is not None:
            counter = self._dropped_counter
            if counter is None:
                # lazy: utils.metrics is stdlib-only but the tracer must
                # stay importable before/without the registry
                from .metrics import trace_spans_dropped as counter

                self._dropped_counter = counter
            counter.inc()

    def _new_span(
        self,
        name: str,
        wave: int,
        trace_id: str,
        parent_id: Optional[int],
        attrs: dict,
        *,
        start: Optional[float] = None,
        end: Optional[float] = None,
    ) -> Span:
        now = time.perf_counter()
        start = now if start is None else start
        return Span(
            name=name,
            wave=wave,
            span_id=next(self._span_seq),
            parent_id=parent_id,
            start=start,
            wall=time.time() - (now - start),
            end=end,
            attrs=attrs,
            trace_id=trace_id,
        )

    @contextmanager
    def span(self, name: str, **attrs):
        """Record a span under the current wave, nested under this
        thread's innermost open span (or ambient context). Yields the
        ``Span`` so callers can stamp attrs (``kind="device"``,
        ``compile=True``) mid-flight."""
        wave, trace_id, parent = self._open_ctx()
        with self._span_at(name, wave, trace_id, parent, dict(attrs)) as sp:
            yield sp

    @contextmanager
    def server_span(self, name: str, ctx: Optional[TraceContext], **attrs):
        """The SERVER half of context propagation: record a handler span
        under the CALLER's wave/trace. A remote caller's span id cannot be
        a local parent (ids are per-process), so it lands in
        ``remote_parent`` (+ ``caller``) for the stitcher to re-parent;
        an in-process caller (same ``proc``) just nests naturally."""
        # set-once proc read (see current_context), lock-free by design
        if ctx is None or ctx.proc == self.proc:  # graftlint: disable=GL011
            with self.span(name, **attrs) as sp:
                yield sp
            return
        attrs = dict(attrs)
        attrs["remote_parent"] = ctx.span_id
        attrs["caller"] = ctx.proc
        with self._span_at(name, ctx.wave, ctx.trace_id, None, attrs) as sp:
            yield sp

    @contextmanager
    def _span_at(
        self,
        name: str,
        wave: int,
        trace_id: str,
        parent: Optional[int],
        attrs: dict,
    ):
        sp = self._new_span(name, wave, trace_id, parent, attrs)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = time.perf_counter()
            stack.pop()
            # a span the caller marked _discard never reaches the ring
            # (speculative spans around drains that turned out empty)
            if not sp.attrs.pop("_discard", False):
                self._append(sp)

    def record(self, name: str, duration: float, **attrs) -> Span:
        """Append an already-measured region as a COMPLETED span (ending
        now), nested under this thread's innermost open span — for code
        that times its phases with perf_counter deltas (the fleet pass
        breakdown) rather than nesting context managers."""
        wave, trace_id, parent = self._open_ctx()
        now = time.perf_counter()
        sp = self._new_span(
            name, wave, trace_id, parent, dict(attrs),
            start=now - duration, end=now,
        )
        self._append(sp)
        return sp

    def open_manual(
        self, name: str, ctx: Optional[TraceContext] = None, **attrs
    ) -> Span:
        """Allocate an OPEN span without pushing it on this thread's
        stack — for in-flight windows that close on another thread (the
        pipelined ``call_future`` seam closes its client span from the
        grpc done callback). Close with ``close_manual``; until then the
        span is not in the ring."""
        if ctx is None:
            wave, trace_id, parent = self._open_ctx()
        else:
            wave, trace_id, parent = ctx.wave, ctx.trace_id, ctx.span_id
        return self._new_span(name, wave, trace_id, parent, dict(attrs))

    def server_open_manual(
        self, name: str, ctx: Optional[TraceContext] = None, **attrs
    ) -> Span:
        """``server_span``'s manual-close variant — the same re-parenting
        contract (a remote caller's span id lands in ``remote_parent`` +
        ``caller`` with the span parentless locally; an in-process caller
        nests naturally) for handler windows that suspend across the
        handler thread (the bus Watch replay generator). Close with
        ``close_manual``."""
        # set-once proc read (see current_context), lock-free by design
        if ctx is not None and ctx.proc != self.proc:  # graftlint: disable=GL011
            attrs = dict(attrs)
            attrs["remote_parent"] = ctx.span_id
            attrs["caller"] = ctx.proc
            return self._new_span(name, ctx.wave, ctx.trace_id, None, attrs)
        return self.open_manual(name, ctx, **attrs)

    def close_manual(self, sp: Span) -> None:
        sp.end = time.perf_counter()
        self._append(sp)

    # -- export ------------------------------------------------------------

    def dump(self, wave: Optional[int] = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if wave is not None:
            spans = [s for s in spans if s.wave == wave]
        return [s.to_json() for s in spans]

    def spans_for(self, wave: int) -> list[Span]:
        """Completed spans of one wave (ring snapshot, no JSON) — the
        history sampler aggregates engine pass stats off their attrs."""
        with self._lock:
            return [
                s for s in self._spans
                if s.wave == wave and s.end is not None
            ]

    def waves(self) -> list[int]:
        with self._lock:
            return sorted({s.wave for s in self._spans})

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return self._dropped_total

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._wave_open = False
            self._dropped_total = 0
            self._dropped_by_wave.clear()
            self._stitch_handoff = None
            hist = self._history
        if hist is not None:
            hist.clear()

    def wave_summary(
        self, wave: Optional[int] = None, *, stitched: bool = False
    ) -> dict:
        """Per-phase attribution of one wave (default: the latest one
        with spans): ``total_s`` sums the wave's ROOT spans (parentless —
        the settle drains), ``phases`` maps span name -> summed SELF time
        (duration minus direct children), and ``coverage`` is attributed/
        total. ``dropped`` counts spans of this wave evicted off the ring
        (coverage silently degrading at 1M-tier was the ISSUE 10
        satellite). ``stitched=True`` additionally pulls ``/debug/traces``
        from every registered peer and returns the cross-process summary
        (``stitch_dumps`` shape) instead of the local one."""
        if stitched:
            # narrowed both sides: the per-wave-close history sampler
            # rides this path, so the LOCAL doc must not pay the
            # full-ring JSON build either, and a black-holed peer gets
            # the flight recorder's short timeout, not urlopen's default
            local = trace_debug_doc(wave=wave, tracer_obj=self)
            peer_docs = fetch_peer_dumps(
                peers(), timeout=2.0, wave=wave, skip_unhealthy=True
            )
            doc = stitch_dumps(local, peer_docs, wave=wave)
            if wave is not None:
                with self._lock:
                    self._stitch_handoff = (wave, doc)
            waves = doc.get("waves", [])
            if not waves:
                return self.wave_summary(wave)
            return waves[-1]
        with self._lock:
            spans = list(self._spans)
            dropped_by_wave = dict(self._dropped_by_wave)
            trace_ids = dict(self._trace_ids)
        if wave is None:
            wave = max((s.wave for s in spans), default=0)
        spans = [s for s in spans if s.wave == wave and s.end is not None]
        by_id = {s.span_id: s for s in spans}
        child_time: dict[int, float] = {}
        for s in spans:
            if s.parent_id is not None and s.parent_id in by_id:
                child_time[s.parent_id] = (
                    child_time.get(s.parent_id, 0.0) + s.duration
                )
        roots = [
            s for s in spans
            if s.parent_id is None or s.parent_id not in by_id
        ]
        total = sum(s.duration for s in roots)
        phases: dict[str, float] = {}
        counts: dict[str, int] = {}
        device = compile_s = 0.0
        for s in spans:
            self_time = max(s.duration - child_time.get(s.span_id, 0.0), 0.0)
            phases[s.name] = phases.get(s.name, 0.0) + self_time
            counts[s.name] = counts.get(s.name, 0) + 1
            if s.attrs.get("kind") == "device":
                device += s.duration
            # compile attribution is a FLAG, not a kind: a synchronous
            # backend compiles inside the dispatch window, an async
            # tunnel inside the device fence — the fleet marks both spans
            # of a fresh-trace pass, so compile_s upper-bounds the
            # compile-bearing time on either backend
            if s.attrs.get("compile"):
                compile_s += s.duration
        attributed = sum(phases.values())
        trace_id = trace_ids.get(wave, "")
        if not trace_id and spans:
            trace_id = spans[0].trace_id
        dropped = dropped_by_wave.get(wave, 0)
        return {
            "wave": wave,
            "trace_id": trace_id,
            "total_s": round(total, 6),
            "coverage": round(attributed / total, 4) if total else 0.0,
            # ISSUE 12 satellite: coverage is computed against the FULL
            # wall even when ring evictions dropped this wave's spans —
            # flag the degradation instead of letting the ratio silently
            # undercount (raise KARMADA_TPU_TRACE_CAPACITY)
            "coverage_degraded": dropped > 0,
            "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
            "span_counts": dict(sorted(counts.items())),
            "device_s": round(device, 6),
            "compile_s": round(compile_s, 6),
            "host_s": round(max(attributed - device, 0.0), 6),
            "spans": len(spans),
            "dropped": dropped,
        }

    def wave_summaries(self, last: int = 8) -> list[dict]:
        return [self.wave_summary(w) for w in self.waves()[-last:]]


#: the process-wide tracer (one ring per process, like the metrics
#: registry; MetricsServer and the CLI dump read THIS instance)
tracer = WaveTracer()


class ContextPropagatingExecutor:
    """Submit-side context propagation over any executor: each task runs
    under the SUBMITTER's trace context (innermost open span at submit
    time), so fan-out RPC spans land in the wave that fanned them out
    instead of wave 0. Wraps only ``submit`` — the estimator fan-out pools
    use nothing else — and delegates the rest."""

    def __init__(self, executor, tracer_obj: Optional[WaveTracer] = None):
        self._executor = executor
        self._tracer = tracer_obj or tracer

    def submit(self, fn, *args, **kwargs):
        tr = self._tracer
        ctx = tr.current_context()

        def run():
            with tr.activate(ctx):
                return fn(*args, **kwargs)

        return self._executor.submit(run)

    def shutdown(self, wait: bool = True) -> None:
        self._executor.shutdown(wait=wait)

    def __getattr__(self, name):
        return getattr(self._executor, name)


# --------------------------------------------------------------------------
# peer registry: where the stitcher finds the other processes' rings
# --------------------------------------------------------------------------

_PEERS: dict[str, str] = {}
_PEERS_LOCK = threading.Lock()


def register_peer(name: str, address: str) -> None:
    """Register a peer process's metrics endpoint (``host:port``) for the
    stitcher. The plane registers its solver sidecar / estimator servers /
    bus at boot (localup exports KARMADA_TPU_TRACE_PEERS to the serve
    process; benches register programmatically)."""
    with _PEERS_LOCK:
        _PEERS[name] = address


def unregister_peer(name: str) -> None:
    with _PEERS_LOCK:
        _PEERS.pop(name, None)


def peers() -> dict[str, str]:
    with _PEERS_LOCK:
        return dict(_PEERS)


def clear_peers() -> None:
    with _PEERS_LOCK:
        _PEERS.clear()
        _PEER_RETRY_AT.clear()


def register_peers_from_env() -> dict[str, str]:
    """Parse ``KARMADA_TPU_TRACE_PEERS`` (``name=host:port,...``) into the
    registry — the boot hook every long-running entrypoint calls."""
    raw = os.environ.get(TRACE_PEERS_ENV, "").strip()
    if not raw:
        return {}
    added: dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, addr = part.partition("=")
        if not sep or not name.strip() or not addr.strip():
            log.warning("bad %s entry %r (want name=host:port)",
                        TRACE_PEERS_ENV, part)
            continue
        register_peer(name.strip(), addr.strip())
        added[name.strip()] = addr.strip()
    return added


# --------------------------------------------------------------------------
# the /debug/traces document (shared by MetricsServer + the CLI dump)
# --------------------------------------------------------------------------


def trace_debug_doc(
    wave: Optional[int] = None,
    *,
    summary: bool = False,
    tracer_obj: Optional[WaveTracer] = None,
) -> dict:
    """THE ``/debug/traces`` document: built in one place so the HTTP
    endpoint, ``karmadactl-tpu trace dump`` and the stitcher can never
    drift on shape. The scheduling-mesh report is sys.modules-gated: a
    process that never imported the mesh module has no mesh, and importing
    it here would drag jax into lean processes (the bus)."""
    import sys as _sys

    tr = tracer_obj or tracer
    pm = _sys.modules.get("karmada_tpu.parallel.mesh")
    doc = {
        "proc": tr.proc,
        "mesh": pm.active_mesh_shape() if pm is not None else None,
        "dropped": tr.dropped_total,
        "peers": peers(),
    }
    if wave is not None:
        # narrowed fetch (?wave=N): filter BEFORE serializing and
        # summarize only the requested wave — per-wave history sampling
        # and the flight recorder hit this path once per wave close, so
        # it must not pay the full-ring JSON build
        doc["waves"] = [
            w for w in (tr.wave_summary(wave),) if w.get("spans")
        ]
        doc["spans"] = tr.dump(wave)
    else:
        doc["waves"] = tr.wave_summaries()
        doc["spans"] = tr.dump()
    if summary:
        doc.pop("spans", None)
    return doc


#: addr -> monotonic retry-at for peers that just failed a fetch: the
#: per-wave-close sampler must not pay a full timeout per close for a
#: persistently-down peer (skip window; guarded by _PEERS_LOCK)
_PEER_RETRY_AT: dict[str, float] = {}
_PEER_SKIP_SECONDS = 30.0


def fetch_peer_dumps(
    peer_map: dict[str, str], timeout: float = 5.0,
    wave: Optional[int] = None, *, skip_unhealthy: bool = False,
) -> dict[str, dict]:
    """Pull ``/debug/traces`` from every peer's metrics port,
    CONCURRENTLY (N black-holed peers cost one timeout, not N serial
    ones — the per-wave-close history sampler rides this path).
    Unreachable peers are skipped with a warning — a stitched dump of
    the reachable plane beats no dump. ``wave`` narrows each fetch
    server-side (``?wave=N`` — peers record under the CALLER's wave id):
    at 1M-tier capacities the full ring is tens of thousands of spans
    per peer, and both stitching call sites already know which wave they
    want. ``skip_unhealthy=True`` (the frequent-caller mode: per-wave
    sampling) additionally skips any peer that failed within the last
    30s, so a down sidecar costs one timeout per skip window instead of
    one per wave close; one-shot callers (flight recorder without a
    handoff, the CLI) keep the always-try default."""
    import urllib.request

    docs: dict[str, dict] = {}
    query = "" if wave is None else f"?wave={wave}"

    def fetch_one(addr: str) -> dict:
        with urllib.request.urlopen(
            f"http://{addr}/debug/traces{query}", timeout=timeout
        ) as resp:
            return json.loads(resp.read().decode())

    if skip_unhealthy:
        now = time.monotonic()
        with _PEERS_LOCK:
            peer_map = {
                name: addr for name, addr in peer_map.items()
                if _PEER_RETRY_AT.get(addr, 0.0) <= now
            }
    if not peer_map:
        return docs
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(len(peer_map), 8)) as pool:
        futures = {
            name: pool.submit(fetch_one, addr)
            for name, addr in sorted(peer_map.items())
        }
    for name, fut in futures.items():
        try:
            docs[name] = fut.result()
        except Exception as exc:  # noqa: BLE001 — peer down: stitch the rest
            with _PEERS_LOCK:
                _PEER_RETRY_AT[peer_map[name]] = (
                    time.monotonic() + _PEER_SKIP_SECONDS
                )
            log.warning("trace peer %s (%s) unreachable: %s", name,
                        peer_map[name], type(exc).__name__)
        else:
            with _PEERS_LOCK:
                _PEER_RETRY_AT.pop(peer_map[name], None)
    return docs


# --------------------------------------------------------------------------
# the stitcher: cross-process trace trees + per-channel attribution
# --------------------------------------------------------------------------


def _span_channel(name: str) -> Optional[str]:
    """The channel a client RPC span belongs to (its name's first dotted
    component: ``estimator.rpc`` -> ``estimator``)."""
    head, sep, _ = name.partition(".")
    return head if sep else None


def stitch_spans(
    spans: list[dict], wave: int, trace_id: str, *, dropped: int = 0
) -> dict:
    """Stitch ONE wave's spans (already tagged with ``proc``, merged from
    every process) into a cross-process summary: remote handler roots
    re-parent under their originating client spans (``remote_parent`` +
    ``caller`` attrs), self-times compute across the stitched tree, and
    each channel's network/serialization time falls out as
    ``client span − remote roots`` per RPC. Durations only — process
    clocks are never compared. ``dropped`` is INPUT data (ring evictions
    of this wave, summed across the contributing processes — the raw
    spans cannot carry it): nonzero flags the stitched coverage as
    degraded, same as the local summary."""
    sel = [
        s for s in spans
        if s.get("wave") == wave
        and (not trace_id or s.get("trace_id", "") == trace_id)
    ]
    by_key = {(s.get("proc", "?"), s["span_id"]): s for s in sel}

    def parent_key(s: dict) -> Optional[tuple]:
        attrs = s.get("attrs", {})
        rp, caller = attrs.get("remote_parent"), attrs.get("caller")
        if caller is not None:
            key = (caller, rp)
            return key if key in by_key else None
        if s.get("parent_id") is not None:
            key = (s.get("proc", "?"), s["parent_id"])
            return key if key in by_key else None
        return None

    child_time: dict[tuple, float] = {}
    remote_children: dict[tuple, list] = {}
    parents: dict[tuple, Optional[tuple]] = {}
    for s in sel:
        key = (s.get("proc", "?"), s["span_id"])
        pk = parent_key(s)
        parents[key] = pk
        if pk is not None:
            child_time[pk] = child_time.get(pk, 0.0) + s["duration_s"]
            if pk[0] != key[0]:
                remote_children.setdefault(pk, []).append(s)

    # roots: unparented spans that did NOT arrive over a channel. After
    # re-parenting, a remote handler span is never a root — total_s is
    # the caller-side wall, exactly what the local summary reports; a
    # handler span whose client span fell off the ring must not inflate
    # it either (hence the ``caller`` check, not just parent resolution)
    roots = [
        s for s in sel
        if parents[(s.get("proc", "?"), s["span_id"])] is None
        and "caller" not in s.get("attrs", {})
    ]
    total = sum(s["duration_s"] for s in roots)

    phases: dict[str, float] = {}
    counts: dict[str, int] = {}
    process_s: dict[str, float] = {}
    channels: dict[str, dict] = {}
    device = compile_s = 0.0
    for s in sel:
        key = (s.get("proc", "?"), s["span_id"])
        self_time = max(s["duration_s"] - child_time.get(key, 0.0), 0.0)
        phases[s["name"]] = phases.get(s["name"], 0.0) + self_time
        counts[s["name"]] = counts.get(s["name"], 0) + 1
        proc = s.get("proc", "?")
        process_s[proc] = process_s.get(proc, 0.0) + self_time
        # device/compile attribution, the local summary's rule: kind is
        # a span attr, compile a flag — stitched history rows must not
        # read zeros for series the local rows populate
        if s.get("attrs", {}).get("kind") == "device":
            device += s["duration_s"]
        if s.get("attrs", {}).get("compile"):
            compile_s += s["duration_s"]
        # per-channel columns from CLIENT rpc spans: server time is the
        # re-parented remote roots' wall; the remainder of the client
        # span is wire + serialization — the column no single-process
        # view can produce
        if s.get("attrs", {}).get("remote"):
            ch = _span_channel(s["name"])
            if ch is not None:
                slot = channels.setdefault(
                    ch, {"rpcs": 0, "client_s": 0.0, "server_s": 0.0,
                         "network_s": 0.0, "events": 0},
                )
                server = sum(
                    c["duration_s"] for c in remote_children.get(key, [])
                )
                slot["rpcs"] += 1
                # batching factor: a batched RPC carries batch=N items
                # per message (ISSUE 11); unary calls count 1
                slot["events"] += int(s["attrs"].get("batch") or 1)
                slot["client_s"] += s["duration_s"]
                slot["server_s"] += server
                slot["network_s"] += max(s["duration_s"] - server, 0.0)
    attributed = sum(phases.values())
    return {
        "wave": wave,
        "trace_id": trace_id,
        "stitched": True,
        "total_s": round(total, 6),
        "coverage": round(attributed / total, 4) if total else 0.0,
        "coverage_degraded": dropped > 0,
        "dropped": dropped,
        "device_s": round(device, 6),
        "compile_s": round(compile_s, 6),
        "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
        "span_counts": dict(sorted(counts.items())),
        "process_s": {
            k: round(v, 6) for k, v in sorted(process_s.items())
        },
        "channels": {
            k: {
                "rpcs": v["rpcs"],
                "events": v["events"],
                "events_per_rpc": round(
                    v["events"] / v["rpcs"], 2
                ) if v["rpcs"] else 0.0,
                "client_s": round(v["client_s"], 6),
                "server_s": round(v["server_s"], 6),
                "network_s": round(v["network_s"], 6),
            }
            for k, v in sorted(channels.items())
        },
        "spans": len(sel),
        "procs": sorted({s.get("proc", "?") for s in sel}),
    }


def stitch_dumps(
    local: dict, peer_docs: dict[str, dict], wave: Optional[int] = None
) -> dict:
    """Merge the local ``/debug/traces`` doc with the peers' docs into one
    stitched document: every span tagged with its process, waves keyed by
    the LOCAL process's (trace_id, wave) and summarized across processes.
    ``wave`` restricts to one wave (default: every local wave)."""
    all_spans: list[dict] = []
    local_proc = local.get("proc", "plane")
    for s in local.get("spans", []):
        s = dict(s)
        s.setdefault("proc", local_proc)
        all_spans.append(s)
    dropped = {local_proc: local.get("dropped", 0)}
    for name, doc in sorted(peer_docs.items()):
        proc = doc.get("proc", name)
        dropped[proc] = doc.get("dropped", 0)
        for s in doc.get("spans", []):
            s = dict(s)
            s.setdefault("proc", proc)
            all_spans.append(s)
    waves = [
        w for w in local.get("waves", [])
        if wave is None or w.get("wave") == wave
    ]
    # per-wave ring evictions summed across the contributing processes
    # (each doc's wave summaries carry their own `dropped`): the stitched
    # summary must flag degraded coverage exactly like a local one
    dropped_by_wave: dict[int, int] = {}
    for doc in [local, *peer_docs.values()]:
        for w in doc.get("waves", []):
            wid = w.get("wave")
            if wid is not None:
                dropped_by_wave[wid] = (
                    dropped_by_wave.get(wid, 0) + int(w.get("dropped", 0) or 0)
                )
    stitched_waves = [
        stitch_spans(
            all_spans, w["wave"], w.get("trace_id", ""),
            dropped=dropped_by_wave.get(w["wave"], 0),
        )
        for w in waves
    ]
    return {
        "proc": local_proc,
        "procs": sorted({s.get("proc", "?") for s in all_spans}),
        "dropped": dropped,
        "waves": stitched_waves,
        "spans": all_spans,
    }


def render_attribution_table(summary: dict) -> str:
    """The stitched-wave attribution table as text (``trace analyze`` and
    the bench print this; the JSON record stays the machine surface)."""
    degraded = (
        f" DEGRADED(dropped={summary.get('dropped', 0)})"
        if summary.get("coverage_degraded")
        else ""
    )
    lines = [
        f"wave {summary.get('wave')} trace {summary.get('trace_id', '')} "
        f"total {summary.get('total_s', 0.0):.3f}s coverage "
        f"{summary.get('coverage', 0.0) * 100:.1f}%{degraded}",
        "phase                       self_s",
    ]
    for name, v in sorted(
        summary.get("phases", {}).items(), key=lambda kv: -kv[1]
    ):
        lines.append(f"{name:<27} {v:8.4f}")
    if summary.get("process_s"):
        lines.append("process                     self_s")
        for name, v in sorted(summary["process_s"].items()):
            lines.append(f"{name:<27} {v:8.4f}")
    if summary.get("channels"):
        lines.append(
            "channel      rpcs  ev/msg   client_s   server_s  network_s"
        )
        for name, v in sorted(summary["channels"].items()):
            ev_per = v.get(
                "events_per_rpc",
                (v.get("events", v["rpcs"]) / v["rpcs"]) if v["rpcs"] else 0.0,
            )
            lines.append(
                f"{name:<10} {v['rpcs']:6d} {ev_per:7.2f} "
                f"{v['client_s']:10.4f} "
                f"{v['server_s']:10.4f} {v['network_s']:10.4f}"
            )
    return "\n".join(lines)


# --------------------------------------------------------------------------
# slow-wave flight recorder
# --------------------------------------------------------------------------


def flight_slo() -> Optional[float]:
    """The armed SLO (seconds), or None when the recorder is off —
    KARMADA_TPU_TRACE_SLO_SECONDS unset/empty/unparseable means OFF, and
    the whole recorder costs one env read per wave boundary."""
    raw = os.environ.get(TRACE_SLO_ENV, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def flight_dir() -> str:
    raw = os.environ.get(FLIGHT_DIR_ENV, "").strip()
    if raw:
        return raw
    import tempfile

    return os.path.join(tempfile.gettempdir(), "karmada_tpu_flight")


def _flight_cap() -> int:
    raw = os.environ.get(FLIGHT_CAP_ENV, "").strip()
    try:
        return max(int(raw), 1) if raw else _DEFAULT_FLIGHT_CAP
    except ValueError:
        return _DEFAULT_FLIGHT_CAP


def flight_baseline(wave: int) -> dict:
    """The begin-of-wave snapshot the recorder deltas against: the full
    metrics registry + the fired-fault count."""
    from .faultinject import injector
    from .metrics import registry

    inj = injector()
    return {
        "wave": wave,
        "metrics": registry.snapshot(),
        "fault_events": len(inj.log) if inj is not None else 0,
    }


def _metrics_delta(before: Optional[dict], after: dict) -> dict:
    """Per-family sample deltas (after − before); families/samples absent
    from ``before`` delta against 0. Zero deltas are dropped — the record
    carries what MOVED during the wave."""
    out: dict = {}
    before = before or {}
    for family, samples in after.items():
        prev = before.get(family, {})
        fam_delta: dict = {}
        for key, val in samples.items():
            if isinstance(val, dict):
                pv = prev.get(key, {})
                d = {
                    k: round(val.get(k, 0) - pv.get(k, 0), 9)
                    for k in val
                    if val.get(k, 0) != pv.get(k, 0)
                }
                if d:
                    fam_delta[key] = d
            else:
                d = val - prev.get(key, 0)
                if d:
                    fam_delta[key] = round(d, 9)
        if fam_delta:
            out[family] = fam_delta
    return out


def _delta_total(delta: dict, family: str) -> float:
    vals = delta.get(family, {})
    total = 0.0
    for v in vals.values():
        if isinstance(v, dict):
            total += v.get("count", 0)
        else:
            total += v
    return total


def maybe_flight_record(tr: WaveTracer, wave: int) -> Optional[str]:
    """The ``end_wave`` hook: when the recorder is armed and the closing
    wave breached the SLO — or a breaker transition / degraded pass /
    QuotaExceeded denial fired during it — persist the stitched trace, the
    metrics delta and the fired fault log as one JSONL record. Returns the
    record path when a record was written."""
    slo = flight_slo()
    if slo is None:
        return None
    from .faultinject import injector
    from .metrics import registry

    summary = tr.wave_summary(wave)
    wall = summary.get("total_s", 0.0)
    baseline = tr.flight_baseline_for(wave) or {}
    delta = _metrics_delta(baseline.get("metrics"), registry.snapshot())
    reasons: list[str] = []
    if wall > slo:
        reasons.append(f"slo:{wall:.3f}s>{slo:.3f}s")
    if _delta_total(delta, "karmada_tpu_degraded_passes_total") > 0:
        reasons.append("degraded-pass")
    if _delta_total(delta, "karmada_tpu_quota_denied_total") > 0:
        reasons.append("quota-exceeded")
    if summary.get("span_counts", {}).get("channel.breaker"):
        reasons.append("breaker-transition")
    if not reasons:
        return None

    inj = injector()
    fault_log = []
    if inj is not None:
        start = baseline.get("fault_events", 0)
        fault_log = [
            {"seq": e.seq, "point": e.point, "action": e.action,
             "key": e.key}
            for e in inj.log[start:]
        ]
    # reuse the stitch the history sampler just built for this close
    # (the sampler runs first in end_wave) — a breaching wave pays the
    # peer fetch once; with stitched sampling off (no peers registered
    # or KARMADA_TPU_HISTORY_STITCH=0), only a RECORDED wave pays it
    stitched = tr.consume_stitch_handoff(wave)
    if stitched is None:
        local = trace_debug_doc(wave=wave, tracer_obj=tr)
        peer_docs = fetch_peer_dumps(peers(), timeout=2.0, wave=wave)
        stitched = stitch_dumps(local, peer_docs, wave=wave)
    stitched_summary = (
        stitched["waves"][-1] if stitched.get("waves") else summary
    )
    record = {
        "wave": wave,
        "trace_id": summary.get("trace_id", ""),
        "proc": tr.proc,
        "recorded_at": time.time(),
        "slo_seconds": slo,
        "wall_s": wall,
        "reasons": reasons,
        "summary": stitched_summary,
        "spans": stitched["spans"],
        "procs": stitched["procs"],
        "dropped": stitched["dropped"],
        "metrics_delta": delta,
        "fault_events": fault_log,
        # ISSUE 12: the breaching wave's history row + recent-window
        # digests (end_wave samples BEFORE recording, so the row exists)
        # — `trace analyze` renders breach-vs-recent-baseline offline
        "history": tr.history.breach_context(wave),
    }
    # ISSUE 13: the K worst (denied/unschedulable/displaced) bindings'
    # explanations, when the explain plane captured this wave — `trace
    # analyze` answers "why" offline. Lazy import: the store is
    # numpy-backed and most waves never arm it.
    try:
        from .explainstore import store as _explain_store

        explain_ctx = _explain_store().worst_context(wave)
        if explain_ctx is not None:
            record["explain"] = explain_ctx
    except Exception:  # noqa: BLE001 — provenance is attachment, not
        # the record; a broken capture never blocks the flight write
        pass
    return _flight_append(record)


def _flight_append(record: dict) -> str:
    """Append one JSONL record under KARMADA_TPU_FLIGHT_DIR, ring-capped:
    the file keeps at most KARMADA_TPU_FLIGHT_CAP records (oldest
    dropped)."""
    dir_ = flight_dir()
    os.makedirs(dir_, exist_ok=True)
    path = os.path.join(dir_, "flight.jsonl")
    line = json.dumps(record, sort_keys=True)
    cap = _flight_cap()
    lines: list[str] = []
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    lines.append(line)
    if len(lines) > cap:
        lines = lines[-cap:]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)
    log.warning(
        "flight record: wave %s (%s) -> %s",
        record["wave"], ",".join(record["reasons"]), path,
    )
    return path


def load_flight_records(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        return [
            json.loads(ln) for ln in f.read().splitlines() if ln.strip()
        ]


def analyze_record(record: dict) -> dict:
    """Re-derive a flight record's attribution from its RAW spans and
    compare against the summary stored at record time — the offline
    ``trace analyze`` surface. ``identical`` proves the stitcher is a pure
    function of the spans (the bench asserts it). The recorded `dropped`
    count is INPUT data, not derived from the spans, so it feeds back
    into the re-derivation. A record carrying history context
    additionally renders the breach-vs-recent-window table."""
    recorded = record.get("summary", {})
    recomputed = stitch_spans(
        record.get("spans", []), record.get("wave", 0),
        record.get("trace_id", ""),
        dropped=int(recorded.get("dropped", 0) or 0),
    )
    table = render_attribution_table(recomputed)
    hist = record.get("history")
    if hist and hist.get("row"):
        from .history import render_breach_table

        table += "\n" + render_breach_table(hist)
    # ISSUE 13: a record carrying worst-binding explanations renders
    # the "why" block too — the offline form of /debug/explain
    expl = record.get("explain")
    if expl and expl.get("worst"):
        from .explainstore import render_worst_table

        table += "\n" + render_worst_table(expl)
    # purity check tolerant of OLDER records: summary keys this build
    # added (coverage_degraded/dropped) are ignored when the recorded
    # summary predates them — a pre-upgrade flight record must still
    # prove the stitcher pure, not flag a schema addition
    recomputed_vs = {
        k: v for k, v in recomputed.items() if k in recorded
    }
    return {
        "wave": record.get("wave"),
        "trace_id": record.get("trace_id", ""),
        "reasons": record.get("reasons", []),
        "wall_s": record.get("wall_s"),
        "slo_seconds": record.get("slo_seconds"),
        "summary": recomputed,
        "identical": recomputed_vs == recorded,
        "metrics_delta": record.get("metrics_delta", {}),
        "fault_events": record.get("fault_events", []),
        "history": hist,
        "explain": record.get("explain"),
        "table": table,
    }


# --------------------------------------------------------------------------
# events
# --------------------------------------------------------------------------


@dataclass
class Event:
    object_ref: str  # "<kind>/<key>"
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)


class EventRecorder:
    """In-memory event sink (kube EventRecorder seam). Bounded ring —
    ``deque(maxlen=...)`` so append-at-capacity is O(1) and atomic, with
    a lock over append/snapshot: the shared global ``recorder`` is written
    by every controller thread and read by status surfaces concurrently."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    @property
    def events(self) -> list[Event]:
        """Snapshot (consumers iterate/filter freely; the historical
        attribute was a mutable list — a snapshot keeps that read
        contract race-free)."""
        with self._lock:
            return list(self._events)

    def event(self, object_ref: str, type_: str, reason: str, message: str) -> None:
        with self._lock:
            self._events.append(Event(object_ref, type_, reason, message))

    def for_object(self, object_ref: str) -> list[Event]:
        with self._lock:
            return [e for e in self._events if e.object_ref == object_ref]


# shared recorder (cmd binaries each had one; in-proc a single sink suffices)
recorder = EventRecorder()
