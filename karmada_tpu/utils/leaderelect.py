"""Leader election over a Lease resource lock.

Ref: client-go tools/leaderelection (LeasesResourceLock) as every reference
binary uses it via ``--leader-elect`` (controller-manager, scheduler,
descheduler, agent option structs; utils/flags.py carries the flag
grammar). The algorithm is tryAcquireOrRenew: read the lease, and if it is
unheld, expired, or held by us, write our claim with an
optimistic-concurrency precondition (``Store.apply(expected_rv=...)`` — the
apiserver Update-with-resourceVersion 409 contract). The CAS loser simply
observes the winner's lease.

Unlike client-go this elector is TICK-driven, not thread-driven: the owner
calls :meth:`tick` from its own loop (the agent/serve loops already run on
a cadence), which keeps it deterministic under the test runtime and free of
background threads in the cooperative control plane.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..api.cluster import Lease
from ..api.core import ObjectMeta
from .store import ConflictError

__all__ = ["LeaderElector"]


class LeaderElector:
    """CAS-based leader election on a named Lease.

    ``store`` needs get/apply with the ``expected_rv`` precondition — the
    in-proc Store, the bus StoreReplica, and the agent's facade all
    qualify, so election works identically in-process and across the DCN.

    State transitions surface via ``on_started_leading`` /
    ``on_stopped_leading``; ``is_leader`` is authoritative between ticks
    only up to ``renew_deadline`` — a leader that cannot renew within it
    must consider itself deposed (clock-skew guard, leaderelection.go's
    renewDeadline contract)."""

    def __init__(
        self,
        store,
        name: str,
        identity: str,
        *,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        clock: Callable[[], float] = time.time,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        self.store = store
        self.name = name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_renew = 0.0

    @property
    def is_leader(self) -> bool:
        return self._leading

    def tick(self) -> bool:
        """One tryAcquireOrRenew round. Returns leadership after it."""
        now = self.clock()
        lease: Optional[Lease] = self.store.get("Lease", self.name)
        held_by_other = (
            lease is not None
            and lease.holder_identity not in ("", self.identity)
            and now < lease.renew_time + lease.lease_duration_seconds
        )
        if held_by_other:
            # another candidate holds a live lease: deposed immediately
            # (unlike a transient renew failure, there is no ambiguity)
            self._step_down()
            return False

        claim = Lease(
            meta=ObjectMeta(name=self.name),
            renew_time=now,
            holder_identity=self.identity,
            lease_duration_seconds=self.lease_duration,
            acquire_time=(
                lease.acquire_time
                if lease is not None and lease.holder_identity == self.identity
                else now
            ),
            lease_transitions=(
                lease.lease_transitions
                + (1 if lease.holder_identity != self.identity else 0)
                if lease is not None
                else 0
            ),
        )
        try:
            self.store.apply(
                claim,
                expected_rv=(
                    lease.meta.resource_version if lease is not None else 0
                ),
            )
        except ConflictError:
            # raced a concurrent writer — or, over a bus replica, our own
            # previous write's echo has not landed in the mirror yet (reads
            # are async there). Defer judgment: the next tick's read shows
            # the true holder; the renew deadline bounds the coast.
            if self._leading and now - self._last_renew >= self.renew_deadline:
                self._step_down()
            return self._leading
        except Exception:
            # bus unreachable etc.: cannot renew — step down only once the
            # renew deadline passes (transient write failures must not
            # flap leadership)
            if self._leading and now - self._last_renew >= self.renew_deadline:
                self._step_down()
            return self._leading
        self._last_renew = now
        if not self._leading:
            self._leading = True
            if self.on_started_leading is not None:
                self.on_started_leading()
        return True

    def _step_down(self) -> None:
        if self._leading:
            self._leading = False
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()

    def release(self) -> None:
        """Voluntarily drop the lease (leaderelection.go's ReleaseOnCancel):
        zero the holder so a standby acquires without waiting out the
        expiry."""
        lease: Optional[Lease] = self.store.get("Lease", self.name)
        if lease is None or lease.holder_identity != self.identity:
            return
        lease.holder_identity = ""
        lease.renew_time = 0.0
        try:
            self.store.apply(
                lease, expected_rv=lease.meta.resource_version
            )
        except Exception:  # noqa: BLE001 — best-effort on shutdown
            pass
        self._step_down()
