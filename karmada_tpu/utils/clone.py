"""Fast structural clones for the propagation hot path.

The control plane copies workload manifests constantly — template -> Work,
revise-replica, override application, Retain merges, member applies — and
``copy.deepcopy`` was >60% of a 2000-binding propagation storm's wall time
(its per-node memo bookkeeping and reflective dispatch dominate for the
JSON-shaped trees API objects actually are; the reference pays the same
shape of cost in runtime.DeepCopyObject but with generated per-type
copiers, apimachinery codegen). These helpers are the generated-copier
analogue: type-dispatched, memo-free tree copies that fall back to
``copy.deepcopy`` for anything unexpected (aliased graphs are impossible in
manifests parsed from JSON-style input).
"""

from __future__ import annotations

import copy
from dataclasses import replace
from typing import Any

_SCALARS = (str, int, float, bool, type(None))


def clone_json(x: Any) -> Any:
    """Copy a JSON-shaped tree (dict/list/tuple/scalars); deepcopy
    fallback for anything else."""
    tp = type(x)
    if tp in _SCALARS:
        return x
    if tp is dict:
        return {k: clone_json(v) for k, v in x.items()}
    if tp is list:
        return [clone_json(v) for v in x]
    if tp is tuple:
        return tuple(clone_json(v) for v in x)
    return copy.deepcopy(x)


def clone_meta(meta):
    """Copy an ObjectMeta (flat fields + label/annotation dicts)."""
    return replace(
        meta,
        labels=dict(meta.labels),
        annotations=dict(meta.annotations),
        finalizers=list(meta.finalizers),
    )


def clone_resource(obj):
    """Copy a Resource (unstructured manifest): fresh meta + spec/status
    trees. The workhorse of the Work build / override / retain chain."""
    return replace(
        obj,
        meta=clone_meta(obj.meta),
        spec=clone_json(obj.spec),
        status=clone_json(obj.status),
    )
