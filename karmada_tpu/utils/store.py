"""In-memory API store with watch bus — the control-plane state hub.

Plays the role the kube-apiserver + informers play in the reference: typed
buckets keyed by (kind, namespace/name), resource-version bumping, watch
handlers, finalizer-aware deletion. Controllers subscribe and reconcile; the
whole control plane can be driven deterministically with
``Runtime.run_until_settled`` (karmada_tpu.utils.worker).

Ref analogues: client-go informers / fedinformer managers (pkg/util/fedinformer)
and the apiserver REST semantics the reference assumes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from ..api.core import ObjectMeta, new_uid

ADDED = "Added"
MODIFIED = "Modified"
DELETED = "Deleted"


class ConflictError(RuntimeError):
    """Optimistic-concurrency precondition failed (the apiserver's 409):
    the object's resource_version moved under the caller. Re-read and
    retry, or give up the claim (leader election's loss signal)."""


@dataclass(frozen=True)
class Event:
    type: str  # Added | Modified | Deleted
    kind: str
    key: str  # namespace/name or name
    obj: Any


WatchHandler = Callable[[Event], None]


def obj_key(obj: Any) -> str:
    meta: ObjectMeta = obj.meta
    return meta.namespaced_name


def obj_kind(obj: Any) -> str:
    return type(obj).KIND if hasattr(type(obj), "KIND") else type(obj).__name__


class Store:
    """Typed object store. Mutations are thread-safe; watch handlers run
    synchronously on the mutating thread, outside the lock (so handlers may
    re-enter the store). Cross-thread event *ordering* is therefore not
    guaranteed — the deterministic control-plane runtime (utils.worker) is
    single-threaded, which is the supported concurrency model; multi-threaded
    callers must tolerate reordered events, as with real informers."""

    def __init__(
        self,
        admission: Optional[Callable[[str, Any], None]] = None,
        delete_admission: Optional[Callable[[str, Any], None]] = None,
    ) -> None:
        self._lock = threading.RLock()
        self._buckets: dict[str, dict[str, Any]] = {}
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._all_watchers: list[WatchHandler] = []
        self._rv = 0
        # admission(kind, obj) raises to reject an apply (webhook seam);
        # delete_admission likewise guards Delete operations
        self._admission = admission
        self._delete_admission = delete_admission

    # -- mutation ----------------------------------------------------------

    @property
    def rv(self) -> int:
        """Current resource-version counter (public read for change-gated
        periodic checkpoints and diagnostics)."""
        with self._lock:
            return self._rv

    def advance_rv(self, rv: int) -> None:
        """Advance the resource-version counter to at least ``rv - 1`` so the
        NEXT apply stamps ``rv``. Public seam for replicas mirroring a
        primary's version stream (bus StoreReplica): the replica aligns the
        counter before each replayed apply so its objects carry the
        primary's rvs without reaching into Store internals."""
        with self._lock:
            self._rv = max(self._rv, rv - 1)

    def apply(self, obj: Any, *, expected_rv: Optional[int] = None) -> Any:
        """Create-or-update. Bumps resource_version; bumps generation when a
        spec is present and changed is not detectable (callers that mutate
        spec in place should bump generation themselves via ``bump_generation``).

        ``expected_rv`` is the apiserver's optimistic-concurrency
        precondition: the write succeeds only if the CURRENT object's
        resource_version equals it (0 = the object must not exist yet);
        otherwise ConflictError (HTTP 409). The compare-and-swap leader
        election and controllers racing on shared objects build on this."""
        kind = obj_kind(obj)
        key = obj_key(obj)
        if self._admission is not None:
            self._admission(kind, obj)
        with self._lock:
            bucket = self._buckets.setdefault(kind, {})
            existing = bucket.get(key)
            if expected_rv is not None:
                current_rv = (
                    existing.meta.resource_version
                    if existing is not None
                    else 0
                )
                if current_rv != expected_rv:
                    raise ConflictError(
                        f"{kind} {key!r}: resource_version is "
                        f"{current_rv}, precondition {expected_rv}"
                    )
            self._rv += 1
            obj.meta.resource_version = self._rv
            if not obj.meta.uid:
                obj.meta.uid = existing.meta.uid if existing else new_uid()
            if existing is None and not obj.meta.creation_timestamp:
                import time

                obj.meta.creation_timestamp = time.time()
            bucket[key] = obj
            event = Event(MODIFIED if existing is not None else ADDED, kind, key, obj)
        self._deliver(event)
        return obj

    def apply_many(self, objs: list) -> list:
        """Batched create-or-update for INDEPENDENT objects: admission runs
        per object (against pre-batch state — use only for sweeps whose
        objects don't admit against each other, like a storm writeback
        over distinct bindings), then one lock acquisition commits every
        ACCEPTED mutation, then one delivery sweep fans the events out.
        A 100k-binding writeback is 100k ``apply`` calls otherwise —
        per-call lock churn and bookkeeping were ~30% of the measured
        whole-plane wave.

        Admission rejections do NOT abort the batch: each object's write
        is independent (the reference's controller writebacks are
        per-object patches — one invalid binding must not void a storm
        wave). Rejected objects are skipped (no rv bump, no event) and
        returned as ``[(obj, exception), ...]`` for the caller to surface.
        No ``expected_rv`` support: CAS writers want the single-object
        path."""
        import time as _time

        if not objs:
            return []
        errors: list = []
        keyed = []
        for obj in objs:
            kind = obj_kind(obj)
            key = obj_key(obj)
            if self._admission is not None:
                try:
                    self._admission(kind, obj)
                except Exception as e:  # noqa: BLE001 — per-object verdict
                    errors.append((obj, e))
                    continue
            keyed.append((kind, key, obj))
        events = []
        with self._lock:
            for kind, key, obj in keyed:
                bucket = self._buckets.setdefault(kind, {})
                existing = bucket.get(key)
                self._rv += 1
                obj.meta.resource_version = self._rv
                if not obj.meta.uid:
                    obj.meta.uid = existing.meta.uid if existing else new_uid()
                if existing is None and not obj.meta.creation_timestamp:
                    obj.meta.creation_timestamp = _time.time()
                bucket[key] = obj
                events.append(
                    Event(
                        MODIFIED if existing is not None else ADDED,
                        kind, key, obj,
                    )
                )
        for ev in events:
            self._deliver(ev)
        return errors

    def bump_generation(self, obj: Any) -> None:
        obj.meta.generation += 1

    def delete(self, kind: str, key: str, *, force: bool = False) -> Optional[Any]:
        """Delete an object. With finalizers present (and not force), only
        marks deletion_timestamp and emits MODIFIED — controllers must strip
        finalizers, after which the delete completes (kube semantics).
        ``force`` is the internal finalizer-completion path and skips delete
        admission, like a direct etcd removal."""
        import time

        if not force and self._delete_admission is not None:
            existing = self.get(kind, key)
            if existing is not None:
                self._delete_admission(kind, existing)
        with self._lock:
            bucket = self._buckets.get(kind, {})
            obj = bucket.get(key)
            if obj is None:
                return None
            if obj.meta.finalizers and not force:
                if obj.meta.deletion_timestamp is None:
                    obj.meta.deletion_timestamp = time.time()
                    self._rv += 1
                    obj.meta.resource_version = self._rv
                    event = Event(MODIFIED, kind, key, obj)
                else:
                    return obj
            else:
                del bucket[key]
                event = Event(DELETED, kind, key, obj)
        self._deliver(event)
        return obj

    def finalize(self, obj: Any) -> None:
        """Re-evaluate a deleting object: if finalizers are now empty, remove
        it for real."""
        if obj.meta.deletion_timestamp is not None and not obj.meta.finalizers:
            self.delete(obj_kind(obj), obj_key(obj), force=True)
        else:
            self.apply(obj)

    # -- reads -------------------------------------------------------------

    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._buckets.get(kind, {}).get(key)

    def list(self, kind: str, namespace: Optional[str] = None) -> list[Any]:
        with self._lock:
            objs = list(self._buckets.get(kind, {}).values())
        if namespace is not None:
            objs = [o for o in objs if o.meta.namespace == namespace]
        return objs

    # -- durability (checkpoint/resume; SURVEY.md section 5) ---------------

    def checkpoint(self, path: str) -> int:
        """Serialize every object to ``path`` (the etcd-snapshot analogue:
        the store is the single source of truth, controllers and the solver
        are stateless, so a snapshot + replay IS resume). Returns the number
        of objects written."""
        import os
        import pickle

        # Serialize while holding the lock: the bucket copies are shallow
        # and delete()/finalize mutate stored objects' meta IN PLACE under
        # the lock (store.py delete path), including from bus gRPC worker
        # threads — pickling after release could tear the snapshot
        # (tests/test_concurrency_torture.py pins this). The stall is
        # bounded by callers checkpointing only when the rv moved.
        with self._lock:
            payload = {
                kind: dict(bucket) for kind, bucket in self._buckets.items()
            }
            blob = pickle.dumps({"rv": self._rv, "buckets": payload})
        # atomic replace: a crash (or SIGKILL) mid-write must never leave a
        # truncated snapshot that bricks the next restore
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return sum(len(b) for b in payload.values())

    def restore(self, path: str) -> int:
        """Load a checkpoint into this (fresh) store, replaying every object
        through the watch bus as Added so already-registered controllers
        rebuild their working state — the reconcile-from-listing pattern the
        reference relies on after an apiserver restart. Admission is NOT
        re-run: the snapshot was admitted when it was written."""
        import pickle

        with open(path, "rb") as f:
            snap = pickle.load(f)
        events = []
        with self._lock:
            self._rv = max(self._rv, snap["rv"])
            for kind, bucket in snap["buckets"].items():
                dst = self._buckets.setdefault(kind, {})
                for key, obj in bucket.items():
                    dst[key] = obj
                    events.append(Event(ADDED, kind, key, obj))
        for event in events:
            self._deliver(event)
        return len(events)

    def kinds(self) -> Iterable[str]:
        with self._lock:
            return list(self._buckets.keys())

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler, *, replay: bool = True) -> None:
        """Subscribe to events for one kind. With replay, synthesizes ADDED
        events for existing objects (informer initial-list semantics)."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)
            existing = list(self._buckets.get(kind, {}).items()) if replay else []
        for key, obj in existing:
            handler(Event(ADDED, kind, key, obj))

    def watch_all(self, handler: WatchHandler) -> None:
        with self._lock:
            self._all_watchers.append(handler)

    def unwatch_all(self, handler: WatchHandler) -> None:
        """Unregister a watch_all handler (long-lived stores outlive bus
        servers; a dead server's handler must not stay on the write path)."""
        with self._lock:
            self._all_watchers = [h for h in self._all_watchers if h is not handler]

    def _deliver(self, event: Event) -> None:
        # snapshot the handler lists under the lock, call OUTSIDE it — a
        # handler mutating watchers mid-delivery must not tear the
        # iteration, and delivery under the lock would hold it across
        # arbitrary handler code (the lock is an RLock, but handlers can
        # block on other threads that need the store)
        with self._lock:
            handlers = list(self._watchers.get(event.kind, ()))
            handlers += list(self._all_watchers)
        for handler in handlers:
            handler(event)
