"""ExplainStore: ring-capped per-wave placement-provenance captures.

The engine's armed-only explain dispatch (ops/explain.py via
``TensorScheduler``) answers, for every binding x cluster of a pass, a
packed EXCLUSION BITMASK — one bit per decision stage, in
``utils.reasons.STAGE_REASONS`` order — plus a per-binding top-k
candidate summary (availability, credited prev, final assignment) and
the selected affinity-group rank. This module is where those captures
live: a lock-disciplined, ring-capped store (the ``utils/history.py``
discipline — a capture enters the ring complete, evictions are counted,
never silent), served as ``/debug/explain?binding=|?wave=`` by every
``MetricsServer`` and rendered by ``karmadactl-tpu explain <ns>/<name>``
as a decision-chain view. The slow-wave flight recorder attaches the K
worst (denied/unschedulable/displaced) bindings' explanations to a
breaching wave's record, so ``trace analyze`` answers "why" offline.

Mask rows are interned (np.unique over the [B, C] byte matrix): storms
carry few unique placements, so a 100k-binding capture stores U unique
rows + one int32 index instead of the dense grid.

Arming: ``KARMADA_TPU_EXPLAIN=1`` arms every engine in the process
(disarmed = one ``is None`` check per pass, the PR 7/8 pattern);
``KARMADA_TPU_EXPLAIN_CAP`` bounds the ring in WAVES (0 disables the
store even when armed). numpy-only — no jax; lean processes import this
lazily from the debug endpoint.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from .reasons import STAGE_REASONS, classify_error

EXPLAIN_ENV = "KARMADA_TPU_EXPLAIN"
EXPLAIN_CAP_ENV = "KARMADA_TPU_EXPLAIN_CAP"

_DEFAULT_CAP = 8

#: clusters listed per stage in a decoded explanation (the full count is
#: always reported; the name list is a sample, not the set)
_STAGE_NAME_CAP = 16


def explain_armed() -> bool:
    """The process-wide arm switch (read once per engine construction —
    the hot path costs one ``is None`` check, not an env read)."""
    return os.environ.get(EXPLAIN_ENV, "").strip().lower() in (
        "1", "true", "yes",
    )


def _env_cap() -> int:
    raw = os.environ.get(EXPLAIN_CAP_ENV, "").strip()
    if not raw:
        return _DEFAULT_CAP
    try:
        return max(int(raw), 0)
    except ValueError:
        return _DEFAULT_CAP


class ExplainCapture:
    """One engine pass's provenance: interned exclusion-mask rows + the
    top-k candidate summary. Built COMPLETELY before entering the ring."""

    __slots__ = (
        "wave", "at", "names", "keys", "index", "uniq_masks", "mask_inv",
        "topk", "group_rank", "reasons", "errors",
        "asg_rows", "asg_cols", "asg_vals",
    )

    def __init__(
        self,
        *,
        wave: int,
        names: tuple,
        keys: list,
        masks: np.ndarray,  # uint8[B, C] packed stage-exclusion bits
        topk: np.ndarray,  # int32[B, K, 5]: cluster, avail, prev, assigned, mask
        group_rank: np.ndarray,  # int32[B] selected affinity-group index
        errors: list,  # per-binding ScheduleResult.error ("" = scheduled)
        assignment: np.ndarray,  # int32[B, C] the pass's final assignment
    ):
        b = len(keys)
        assert masks.shape[0] == b and topk.shape[0] == b
        assert assignment.shape[0] == b
        self.wave = int(wave)
        self.at = time.time()
        self.names = tuple(names)
        self.keys = list(keys)
        self.index = {k: i for i, k in enumerate(keys)}
        # intern mask rows: storms repeat placements, so U << B
        self.uniq_masks, self.mask_inv = np.unique(
            np.ascontiguousarray(masks, dtype=np.uint8),
            axis=0, return_inverse=True,
        )
        self.mask_inv = self.mask_inv.astype(np.int32)
        self.topk = np.ascontiguousarray(topk, dtype=np.int32)
        self.group_rank = np.ascontiguousarray(group_rank, dtype=np.int32)
        self.errors = list(errors)
        self.reasons = [classify_error(e) for e in errors]
        # the FULL assignment, stored sparse (CSR-ish: np.nonzero answers
        # row-major order, so asg_rows is sorted): the top-k summary caps
        # at k candidates, but a wide placement (Duplicated over hundreds
        # of clusters) must still decode its complete final assignment
        rows, cols = np.nonzero(np.asarray(assignment) > 0)
        self.asg_rows = rows.astype(np.int32)
        self.asg_cols = cols.astype(np.int32)
        self.asg_vals = np.asarray(assignment)[rows, cols].astype(np.int32)

    @property
    def bindings(self) -> int:
        return len(self.keys)

    def nbytes(self) -> int:
        return int(
            self.uniq_masks.nbytes + self.mask_inv.nbytes
            + self.topk.nbytes + self.group_rank.nbytes
            + self.asg_rows.nbytes + self.asg_cols.nbytes
            + self.asg_vals.nbytes
        )

    def mask_row(self, row: int) -> np.ndarray:
        return self.uniq_masks[self.mask_inv[row]]

    def decode(self, row: int) -> dict:
        """One binding's decision chain: per-stage excluded clusters,
        the top-k candidate table, the selected group, and the final
        verdict (classified reason + assignment)."""
        mask = self.mask_row(row)
        stages: dict[str, dict] = {}
        for bit, code in enumerate(STAGE_REASONS):
            hit = np.flatnonzero((mask >> np.uint8(bit)) & np.uint8(1))
            if hit.size:
                stages[code] = {
                    "clusters": [
                        self.names[j] for j in hit[:_STAGE_NAME_CAP]
                    ],
                    "count": int(hit.size),
                }
        candidates = []
        for j, avail, prev, assigned, m in self.topk[row].tolist():
            if j < 0:
                continue
            candidates.append({
                "cluster": self.names[j],
                "available": int(avail),
                "prev": int(prev),
                "assigned": int(assigned),
                "excluded_by": [
                    code for bit, code in enumerate(STAGE_REASONS)
                    if (int(m) >> bit) & 1
                ],
            })
        # the COMPLETE assignment off the sparse store — never the top-k
        # slice (a wide placement assigns more clusters than k)
        lo = int(np.searchsorted(self.asg_rows, row))
        hi = int(np.searchsorted(self.asg_rows, row + 1))
        assignment = {
            self.names[int(j)]: int(v)
            for j, v in zip(self.asg_cols[lo:hi], self.asg_vals[lo:hi])
        }
        feasible = int((mask == 0).sum())
        return {
            "binding": self.keys[row],
            "wave": self.wave,
            "at": self.at,
            "reason": self.reasons[row],
            "error": self.errors[row],
            "scheduled": not self.errors[row],
            "group_rank": int(self.group_rank[row]),
            "clusters_total": len(self.names),
            "clusters_feasible": feasible,
            "stages": stages,
            "candidates": candidates,
            "assignment": assignment,
        }


class ExplainStore:
    """PER-WAVE ring of ``ExplainCapture``s — the process-wide
    provenance memory behind ``/debug/explain`` (the history-ring
    discipline: complete rows, one lock, counted evictions). A pass is
    captured as one capture per engine chunk; the cap counts WAVES, so
    a many-chunk storm pass can never evict its own early chunks."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = _env_cap() if cap is None else cap
        self._lock = threading.Lock()
        self._captures: deque = deque()
        self._evicted = 0
        self._added = 0

    @property
    def enabled(self) -> bool:
        return self.cap > 0

    def add(self, capture: ExplainCapture) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._captures.append(capture)
            self._added += 1
            waves: list = []
            for c in self._captures:
                if c.wave not in waves:
                    waves.append(c.wave)
            while len(waves) > self.cap:
                drop = waves.pop(0)
                while self._captures and self._captures[0].wave == drop:
                    self._captures.popleft()
                    self._evicted += 1

    def captures(self, wave: Optional[int] = None) -> list:
        with self._lock:
            caps = list(self._captures)
        if wave is not None:
            caps = [c for c in caps if c.wave == wave]
        return caps

    @property
    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    @property
    def added(self) -> int:
        with self._lock:
            return self._added

    def clear(self) -> None:
        with self._lock:
            self._captures.clear()
            self._evicted = 0
            self._added = 0

    # -- queries -----------------------------------------------------------

    def explain_binding(
        self, key: str, wave: Optional[int] = None
    ) -> Optional[dict]:
        """Newest explanation for ``key`` (optionally pinned to one
        wave). Accepts both the engine's problem key and a bare
        ``ns/name``."""
        for cap in reversed(self.captures(wave)):
            row = cap.index.get(key)
            if row is None and "/" in key:
                # problem keys are namespaced names already; tolerate a
                # kind-prefixed form (``ResourceBinding/ns/name``)
                for k, r in cap.index.items():
                    if k == key or k.endswith("/" + key):
                        row = r
                        break
            if row is not None:
                return cap.decode(row)
        return None

    def wave_summary(self, wave: Optional[int] = None) -> dict:
        """Per-reason verdict counts + per-stage exclusion totals over
        one wave's captures (default: the newest captured wave)."""
        caps = self.captures(wave)
        if wave is None and caps:
            wave = caps[-1].wave
            caps = [c for c in caps if c.wave == wave]
        verdicts: dict[str, int] = {}
        stage_excluded: dict[str, int] = {}
        bindings = 0
        for cap in caps:
            bindings += cap.bindings
            for r in cap.reasons:
                verdicts[r] = verdicts.get(r, 0) + 1
            counts = np.bincount(
                cap.mask_inv, minlength=len(cap.uniq_masks)
            )
            for bit, code in enumerate(STAGE_REASONS):
                rows = (
                    (cap.uniq_masks >> np.uint8(bit)) & np.uint8(1)
                ).sum(axis=1)
                total = int((rows * counts).sum())
                if total:
                    stage_excluded[code] = (
                        stage_excluded.get(code, 0) + total
                    )
        return {
            "wave": wave,
            "captures": len(caps),
            "bindings": bindings,
            "verdicts": dict(sorted(verdicts.items())),
            "stage_excluded_cells": dict(sorted(stage_excluded.items())),
        }

    def worst(self, wave: Optional[int] = None, k: int = 8) -> list[dict]:
        """The K worst bindings of a wave, decoded: denied/unschedulable
        rows first (newest capture wins a key), then displaced rows that
        fell back to a later affinity group. The flight recorder
        attaches exactly this to a breaching wave's record."""
        caps = self.captures(wave)
        if wave is None and caps:
            caps = [c for c in caps if c.wave == caps[-1].wave]
        seen: set = set()
        ranked: list[tuple] = []
        for cap in reversed(caps):
            for row, key in enumerate(cap.keys):
                if key in seen:
                    continue
                # newest capture wins the key UNCONDITIONALLY: a binding
                # denied in an early pass but scheduled by a later pass
                # of the same wave must not surface its stale denial
                seen.add(key)
                if cap.errors[row]:
                    badness = 0
                elif int(cap.group_rank[row]) > 0:
                    badness = 1  # displaced onto a fallback group
                else:
                    continue
                ranked.append((badness, len(ranked), cap, row))
        ranked.sort(key=lambda t: (t[0], t[1]))
        return [cap.decode(row) for _, _, cap, row in ranked[:k]]

    def worst_context(
        self, wave: Optional[int] = None, k: int = 8
    ) -> Optional[dict]:
        """The flight recorder's attachment: worst-binding explanations
        plus the wave's verdict summary (None when nothing captured —
        the record stays explain-free rather than carrying an empty
        shell)."""
        worst = self.worst(wave, k)
        if not worst:
            return None
        return {"summary": self.wave_summary(wave), "worst": worst}

    # -- documents ---------------------------------------------------------

    def debug_doc(
        self,
        binding: Optional[str] = None,
        wave: Optional[int] = None,
        proc: str = "",
    ) -> dict:
        """THE ``/debug/explain`` document (one builder so the HTTP
        endpoint, the CLI and the flight recorder can never drift on
        shape)."""
        doc: dict = {
            "proc": proc,
            "cap": self.cap,
            "added": self.added,
            "evicted": self.evicted,
            "waves": sorted({c.wave for c in self.captures()}),
        }
        if binding is not None:
            doc["binding"] = self.explain_binding(binding, wave)
        else:
            doc["summary"] = self.wave_summary(wave)
            doc["worst"] = self.worst(wave)
        return doc


_STORE: Optional[ExplainStore] = None
_STORE_LOCK = threading.Lock()


def store() -> ExplainStore:
    """The process-wide store (the tracer/registry pattern): armed
    engines write it, ``/debug/explain`` and the flight recorder read
    it."""
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = ExplainStore()
    return _STORE


def reset_store() -> None:
    """Test/bench hook: drop the singleton so the next ``store()`` call
    re-reads the env cap."""
    global _STORE
    with _STORE_LOCK:
        _STORE = None


# --------------------------------------------------------------------------
# rendering (karmadactl-tpu explain, trace analyze)
# --------------------------------------------------------------------------


def render_explanation(doc: dict) -> str:
    """One binding's decision chain as text (the CLI view; the JSON doc
    stays the machine surface)."""
    if doc is None:
        return "(no explanation captured)"
    lines = [
        f"binding {doc.get('binding')} wave {doc.get('wave')} -> "
        + (
            "SCHEDULED" if doc.get("scheduled")
            else f"{doc.get('reason')} ({doc.get('error')})"
        ),
        f"affinity group rank {doc.get('group_rank', 0)}; "
        f"{doc.get('clusters_feasible', 0)}/{doc.get('clusters_total', 0)} "
        f"clusters feasible",
    ]
    stages = doc.get("stages") or {}
    if stages:
        lines.append("excluded by stage:")
        for code in STAGE_REASONS:
            st = stages.get(code)
            if not st:
                continue
            names = ", ".join(st.get("clusters", []))
            more = st.get("count", 0) - len(st.get("clusters", []))
            tail = f" (+{more} more)" if more > 0 else ""
            lines.append(f"  {code:<28} {st.get('count', 0):>6}  "
                         f"{names}{tail}")
    cands = doc.get("candidates") or []
    if cands:
        lines.append(
            f"{'candidate':<20} {'avail':>10} {'prev':>6} {'assigned':>9}"
            "  excluded_by"
        )
        for cd in cands:
            lines.append(
                f"{cd.get('cluster', '?'):<20} "
                f"{cd.get('available', 0):>10} {cd.get('prev', 0):>6} "
                f"{cd.get('assigned', 0):>9}  "
                + (",".join(cd.get("excluded_by", [])) or "-")
            )
    asg = doc.get("assignment") or {}
    if asg:
        lines.append(
            "assignment: "
            + ", ".join(f"{k}={v}" for k, v in sorted(asg.items()))
        )
    return "\n".join(lines)


def render_worst_table(ctx: dict) -> str:
    """The flight-record attachment as text — what ``trace analyze``
    appends when a breaching wave carried worst-binding explanations."""
    summary = ctx.get("summary") or {}
    verdicts = summary.get("verdicts") or {}
    lines = [
        f"explain: wave {summary.get('wave')} — "
        + (
            ", ".join(f"{k} x{v}" for k, v in sorted(verdicts.items()))
            or "no verdicts"
        ),
    ]
    for doc in ctx.get("worst") or []:
        top_stage = max(
            (doc.get("stages") or {}).items(),
            key=lambda kv: kv[1].get("count", 0),
            default=(None, None),
        )[0]
        lines.append(
            f"  {doc.get('binding'):<40} {doc.get('reason'):<24} "
            f"group={doc.get('group_rank', 0)} feasible="
            f"{doc.get('clusters_feasible', 0)}"
            + (f" top_stage={top_stage}" if top_stage else "")
        )
    return "\n".join(lines)
