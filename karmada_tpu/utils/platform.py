"""Child-process jax platform policy.

The accelerator environment's sitecustomize registers the tunnel backend
and overrides platform selection PROGRAMMATICALLY at interpreter start,
so a parent setting ``JAX_PLATFORMS=cpu`` in a child's env is silently
ignored — the child's first jax use would dial the (single-client)
accelerator tunnel. ``spawn_child`` therefore passes the requested
platform in ``KARMADA_TPU_PLATFORM`` and every child entrypoint calls
``apply_child_platform()`` before its first jax use, re-asserting the
policy through ``jax.config`` the same way the sitecustomize set it.

Ref: the reference pins components to nodes/devices via pod scheduling
(operator-rendered Deployments); here the analogue is per-process
backend selection.
"""

from __future__ import annotations

import os


def apply_child_platform() -> None:
    """Apply the parent-requested jax platform (no-op when unset).

    Must run before any jax backend initializes; safe to call multiple
    times. Import of jax is deferred so non-jax children don't pay it.
    """
    plat = os.environ.get("KARMADA_TPU_PLATFORM")
    if not plat:
        return
    os.environ["JAX_PLATFORMS"] = plat
    import sys

    if "jax" not in sys.modules:
        # jax not imported yet: nothing has overridden the env var, and
        # importing jax here just to re-assert it would make every
        # non-jax child pay the import. (Under the tunnel sitecustomize
        # jax IS already imported at this point — that is the case the
        # config override below exists for.)
        return
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception:
        # backends already initialized: the env var was our best effort
        pass
