"""Persistent XLA compilation cache policy — one module, every process.

The tunneled TPU backend charges 20-40 s per fresh trace, and the engine's
static specializations (chunk counts, kernel variants, entry-buffer caps)
legitimately produce several traces per workload shape. Round 5's verdict
pinned the remaining headroom on exactly this: the whole-plane COLD wave
ran 129 s against a ~15-30 s warm wave because every plane restart, HA
failover, and fleet-table rebuild re-paid full XLA trace+compile on the
serving path.

This module is the single resolution point for where that cost is paid
once:

- ``resolve_cache_dir()`` — the on-disk cache root (repo-local
  ``.jax_cache`` in a checkout, the user cache dir for installed
  packages), partitioned per configured platform set so a tunneled
  accelerator backend's remote-host CPU artifacts can never be loaded by
  a local CPU process (machine-feature mismatch, observed SIGILL).
- ``enable()`` — applies the jax.config knobs; called by
  ``karmada_tpu.ops`` at import (every jax-using component passes through
  it) and re-callable to tighten the persistence threshold.
- ``default_manifest_path()`` — where the trace-signature manifest
  (scheduler.prewarm.TraceManifest) lives by default: BESIDE the cache,
  in the same platform partition, because manifest records replay into
  exactly that cache.

Env knobs (the process-tree plumbing localup/solver/bench ride):

- ``JAX_COMPILATION_CACHE_DIR`` — cache root override; ``""`` disables.
- ``KARMADA_TPU_TRACE_MANIFEST`` — manifest path override; ``""``
  disables manifest recording/restoring entirely.
- ``KARMADA_TPU_CACHE_MIN_COMPILE_SECS`` — persistence threshold
  (default 1.0; prewarm drops it to 0.0 so warmed artifacts always
  persist).
"""

from __future__ import annotations

import os

MIN_COMPILE_SECS_ENV = "KARMADA_TPU_CACHE_MIN_COMPILE_SECS"
MANIFEST_ENV = "KARMADA_TPU_TRACE_MANIFEST"
CACHE_DIR_ENV = "JAX_COMPILATION_CACHE_DIR"


def _platform_partition() -> str:
    """The configured jax platform list, config-first: the tunnel
    sitecustomize sets it programmatically, so the env var alone is not
    authoritative."""
    try:
        import jax

        plat = jax.config.jax_platforms
    except Exception:  # noqa: BLE001 — knob missing in this jax
        plat = None
    plat = plat or os.environ.get("JAX_PLATFORMS") or "default"
    return plat.replace(",", "_") or "default"


def resolve_cache_dir() -> str:
    """The effective persistent-cache directory ("" = disabled).

    ``JAX_COMPILATION_CACHE_DIR`` overrides verbatim (no platform
    partition — the operator pinned an exact path). Otherwise: repo
    checkout caches beside the package; installed package (parent dir not
    writable, e.g. site-packages) falls back to the user cache dir; both
    get a per-platform-set subdirectory.
    """
    override = os.environ.get(CACHE_DIR_ENV)
    if override is not None:
        return override
    repo_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if os.access(repo_parent, os.W_OK):
        root = os.path.join(repo_parent, ".jax_cache")
    else:
        root = os.path.join(
            os.path.expanduser("~"), ".cache", "karmada_tpu", "jax"
        )
    return os.path.join(root, _platform_partition())


def enable(
    cache_dir: str | None = None, *, min_compile_secs: float | None = None
) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (default:
    ``resolve_cache_dir()``). Returns the active directory ("" when
    disabled or when this jax has no cache knob). Safe to call again to
    tighten ``min_compile_secs`` (prewarm sets 0.0 so every warmed trace
    persists regardless of how fast it compiled)."""
    if cache_dir is None:
        cache_dir = resolve_cache_dir()
    if not cache_dir:
        return ""
    if min_compile_secs is None:
        try:
            min_compile_secs = float(
                os.environ.get(MIN_COMPILE_SECS_ENV, "1.0")
            )
        except ValueError:
            min_compile_secs = 1.0
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
    except Exception:  # older jax without the knob: run uncached
        return ""
    return cache_dir


def default_manifest_path() -> str:
    """Where the trace-signature manifest lives ("" = disabled).

    ``KARMADA_TPU_TRACE_MANIFEST`` overrides (empty string disables);
    otherwise the manifest sits inside the platform-partitioned cache dir
    so cache and manifest travel (and invalidate) together."""
    override = os.environ.get(MANIFEST_ENV)
    if override is not None:
        return override
    cache_dir = resolve_cache_dir()
    if not cache_dir:
        return ""
    return os.path.join(cache_dir, "trace_manifest.json")
