"""Pull-mode registration: token bootstrap + certificate records.

Ref: pkg/karmadactl/register (kubeadm-style token -> CSR -> signed agent
cert flow) and the agent-CSR-approving + cert-rotation controllers
(controllermanager.go:241, pkg/controllers/certificate/). The in-proc
transport needs no PKI, so this layer keeps the *protocol shape* — bootstrap
tokens with expiry, CSR records approved by the control plane, rotatable
certificate records — behind which a real PKI slots in.
"""

from __future__ import annotations

import secrets
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class BootstrapToken:
    token_id: str
    secret: str
    expires_at: float

    @property
    def token(self) -> str:
        return f"{self.token_id}.{self.secret}"


@dataclass
class CertificateRecord:
    cluster: str
    issued_at: float
    expires_at: float
    serial: str

    def needs_rotation(self, now: float, threshold: float = 0.2) -> bool:
        """Rotate when less than ``threshold`` of the lifetime remains."""
        lifetime = self.expires_at - self.issued_at
        return (self.expires_at - now) < lifetime * threshold


class RegistrationAuthority:
    """Token issuance + CSR approval + certificate rotation bookkeeping."""

    TOKEN_TTL = 24 * 3600.0
    CERT_TTL = 365 * 24 * 3600.0

    def __init__(self, clock=time.time):
        self.clock = clock
        self._tokens: dict[str, BootstrapToken] = {}
        self.certificates: dict[str, CertificateRecord] = {}
        self.approved_csrs: list[str] = []

    def create_token(self) -> BootstrapToken:
        """karmadactl token create."""
        tok = BootstrapToken(
            token_id=secrets.token_hex(3),
            secret=secrets.token_hex(8),
            expires_at=self.clock() + self.TOKEN_TTL,
        )
        self._tokens[tok.token_id] = tok
        return tok

    def validate_token(self, token: str) -> bool:
        token_id, _, secret = token.partition(".")
        tok = self._tokens.get(token_id)
        return (
            tok is not None
            and tok.secret == secret
            and tok.expires_at > self.clock()
        )

    def submit_csr(self, cluster: str, token: str) -> Optional[CertificateRecord]:
        """Agent bootstrap: CSR auto-approved for valid tokens
        (agent-CSR-approving controller)."""
        if not self.validate_token(token):
            return None
        now = self.clock()
        record = CertificateRecord(
            cluster=cluster,
            issued_at=now,
            expires_at=now + self.CERT_TTL,
            serial=secrets.token_hex(8),
        )
        self.certificates[cluster] = record
        self.approved_csrs.append(cluster)
        return record

    def rotate_if_needed(self, cluster: str) -> Optional[CertificateRecord]:
        """cert-rotation controller sweep."""
        record = self.certificates.get(cluster)
        if record is None or not record.needs_rotation(self.clock()):
            return None
        now = self.clock()
        renewed = CertificateRecord(
            cluster=cluster,
            issued_at=now,
            expires_at=now + self.CERT_TTL,
            serial=secrets.token_hex(8),
        )
        self.certificates[cluster] = renewed
        return renewed
