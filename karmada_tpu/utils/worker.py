"""Reconcile runtime: rate-limited work queues + a deterministic driver.

Ref: pkg/util/worker.go:33-140 (util.AsyncWorker — workqueue + reconcile
loop). The TPU build keeps the same enqueue/reconcile contract but adds a
deterministic cooperative mode (``Runtime.run_until_settled``) so the whole
control plane can be exercised in-process without sleeping threads — the
pattern SURVEY.md section 4.3 calls "distributed-without-a-cluster".
"""

from __future__ import annotations

import collections
import heapq
import itertools
import logging
import time
from typing import Callable, Hashable, Optional

log = logging.getLogger("karmada_tpu")

# Reconcile results
DONE = "done"
REQUEUE = "requeue"


class Worker:
    """A named reconcile queue. ``reconcile(key)`` returns DONE or REQUEUE
    (or raises — treated as REQUEUE with backoff count).

    Two requeue disciplines (pkg/util/worker.go wraps a rate-limiting
    workqueue — DefaultControllerRateLimiter: per-item exponential backoff
    5ms..1000s):

    - cooperative (default): REQUEUE re-enqueues immediately and drops the
      key after MAX_RETRIES — deterministic, for ``run_until_settled``
      test drivers where wall-clock delays would just burn the step budget.
    - wall-clock (``runtime.realtime = True``, the serve deployments):
      REQUEUE parks the key for ``backoff_base * 2^(retries-1)`` seconds
      (capped at ``backoff_max``) and retries indefinitely — a persistently
      failing key costs one reconcile per backoff window instead of 16
      hot-loop attempts followed by a permanent drop.

    Ownership sharding (ISSUE 11): with ``shard_fn`` set, keys route to
    per-ownership-token queues (the binding/detector workers shard by
    namespace) drained round-robin, and a BATCH drain holds keys of one
    token only — so one namespace's storm (or a poisoned key's bisect
    fan-out, or a parked batch flush) never head-of-line-blocks another
    namespace's drain, and each batched write set stays within one
    ownership domain.
    """

    MAX_RETRIES = 16

    def __init__(
        self,
        name: str,
        reconcile: Callable[[Hashable], Optional[str]],
        *,
        reconcile_batch: Optional[
            Callable[[list[Hashable]], dict[Hashable, Optional[str]]]
        ] = None,
        batch_size: int = 1024,
        runtime: Optional["Runtime"] = None,
        backoff_base: float = 0.005,
        backoff_max: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
        shard_fn: Optional[Callable[[Hashable], Hashable]] = None,
    ):
        self.name = name
        self.reconcile = reconcile
        # optional vectorized drain: given up to batch_size queued keys,
        # returns per-key results (missing keys count as DONE). Lets batch
        # engines (the tensor scheduler) amortize one kernel pass over every
        # queued item instead of paying per-key packing/dispatch.
        self.reconcile_batch = reconcile_batch
        self.batch_size = batch_size
        self.runtime = runtime
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.clock = clock
        # key -> ownership token; tokens materialize shard queues lazily
        # (a namespace that never enqueues costs nothing)
        self.shard_fn = shard_fn
        self._queue: collections.deque[Hashable] = collections.deque()
        self._shards: dict[Hashable, collections.deque] = {}
        self._shard_rr: collections.deque = collections.deque()
        self._queued: set[Hashable] = set()
        self._retries: collections.Counter = collections.Counter()
        self._delayed: list[tuple] = []  # (not_before, seq, key) heap
        #: live parked entry per key: key -> (not_before, seq). Heap
        #: entries not matching this map are stale and skipped on promote
        #: (client-go's delaying queue keeps ONE ready-time per item —
        #: the earliest; without dedup a watch-triggered direct enqueue
        #: would leave a stale long-backoff entry to fire a spurious
        #: reconcile later)
        self._parked: dict[Hashable, tuple] = {}
        self._seq = itertools.count()

    def enqueue(self, key: Hashable) -> None:
        # a direct enqueue supersedes any parked retry of the same key
        self._parked.pop(key, None)
        if key in self._queued:
            return
        self._queued.add(key)
        if self.shard_fn is None:
            self._queue.append(key)
            return
        token = self.shard_fn(key)
        q = self._shards.get(token)
        if q is None:
            q = self._shards[token] = collections.deque()
            self._shard_rr.append(token)
        q.append(key)

    def _pop_batch(self, limit: int) -> list:
        """Pop up to ``limit`` queued keys. Sharded workers drain from ONE
        ownership token per call (round-robin across tokens), so a batch
        never mixes ownership domains."""
        keys: list = []
        if self.shard_fn is None:
            while self._queue and len(keys) < limit:
                k = self._queue.popleft()
                self._queued.discard(k)
                keys.append(k)
            return keys
        while self._shard_rr and not keys:
            token = self._shard_rr.popleft()
            q = self._shards.get(token)
            if not q:
                self._shards.pop(token, None)
                continue
            while q and len(keys) < limit:
                k = q.popleft()
                self._queued.discard(k)
                keys.append(k)
            if q:
                self._shard_rr.append(token)  # remainder: back of rotation
            else:
                self._shards.pop(token, None)
        return keys

    def enqueue_after(self, key: Hashable, delay: float) -> None:
        """Park ``key`` until ``delay`` seconds from now (workqueue
        AddAfter): the EARLIEST pending ready-time per key wins, and a
        direct enqueue while parked wins outright (retries sooner)."""
        due = self.clock() + delay
        live = self._parked.get(key)
        if live is not None and live[0] <= due:
            return
        entry = (due, next(self._seq), key)
        self._parked[key] = (due, entry[1])
        heapq.heappush(self._delayed, entry)

    def _promote_due(self) -> None:
        now = self.clock()
        while self._delayed and self._delayed[0][0] <= now:
            due, seq, key = heapq.heappop(self._delayed)
            if self._parked.get(key) != (due, seq):
                continue  # superseded by a direct enqueue or earlier park
            del self._parked[key]
            self.enqueue(key)

    def __len__(self) -> int:
        # _queued mirrors the queued key set exactly (enqueue dedups on
        # it, every pop discards from it) across both queue layouts
        return len(self._queued)

    @property
    def delayed(self) -> int:
        """Keys parked in a backoff window (not yet due)."""
        return len(self._parked)

    def next_due(self) -> Optional[float]:
        """Seconds until the earliest parked key is due (<= 0 if due now),
        or None when nothing is parked."""
        while self._delayed and (
            self._parked.get(self._delayed[0][2])
            != (self._delayed[0][0], self._delayed[0][1])
        ):
            heapq.heappop(self._delayed)  # drop stale heads lazily
        if not self._delayed:
            return None
        return self._delayed[0][0] - self.clock()

    def process_one(self) -> bool:
        """Pop and reconcile one key (or one batch when a batch reconciler
        is installed and multiple keys are queued). Returns True if work was
        done."""
        if self._delayed:
            self._promote_due()
        if not self._queued:
            return False
        if self.reconcile_batch is not None and len(self._queued) > 1:
            keys = self._pop_batch(self.batch_size)
            results = self._drain_batch(keys)
            for k in keys:
                self._finish(k, results.get(k, DONE))
            return True
        popped = self._pop_batch(1)
        if not popped:
            return False
        key = popped[0]
        try:
            result = self.reconcile(key)
        except Exception:  # noqa: BLE001 — reconcile errors requeue, like workqueue
            log.exception("worker %s: reconcile %r failed", self.name, key)
            result = REQUEUE
        self._finish(key, result)
        return True

    #: poisoned keys tolerated per drain before the failure is treated as
    #: systemic (whole engine down, not bad keys); each poisoned key costs
    #: ~log2(batch) failing sub-batch calls down its bisect path
    POISON_TOLERANCE = 4

    def _drain_batch(self, keys: list[Hashable]) -> dict[Hashable, Optional[str]]:
        """Run reconcile_batch with poisoned-key isolation.

        A batch-wide REQUEUE on exception would make every key in the batch
        burn retries together with the one bad key (all dropped together at
        MAX_RETRIES). Instead, bisect the failing batch: healthy halves stay
        batched, and only genuinely failing keys pay a retry. A failure
        budget caps the fan-out when the failure is systemic (every sub-call
        failing) so a batch-wide transient costs O(budget) calls and one
        logged traceback, not O(batch) of each."""
        results: dict[Hashable, Optional[str]] = {}
        failures = 0
        budget = self.POISON_TOLERANCE * max(1, len(keys).bit_length())

        def run(ks: list[Hashable]) -> None:
            nonlocal failures
            if failures > budget:
                for k in ks:
                    results[k] = REQUEUE
                return
            try:
                if len(ks) == 1:
                    results[ks[0]] = self.reconcile(ks[0])
                else:
                    results.update(self.reconcile_batch(ks))
                return
            except Exception:  # noqa: BLE001
                failures += 1
                if failures == 1:
                    log.exception(
                        "worker %s: batch reconcile failed; bisecting", self.name
                    )
                else:
                    log.error(
                        "worker %s: reconcile of %d key(s) failed (failure %d)",
                        self.name, len(ks), failures,
                    )
                if len(ks) == 1:
                    results[ks[0]] = REQUEUE
                    return
            mid = len(ks) // 2
            run(ks[:mid])
            run(ks[mid:])

        run(keys)
        return results

    def _finish(self, key: Hashable, result: Optional[str]) -> None:
        if result == REQUEUE:
            self._retries[key] += 1
            if self.runtime is not None and self.runtime.realtime:
                # exponent is capped: retries grow without bound in
                # realtime mode and 2**1025 overflows float conversion
                delay = min(
                    self.backoff_base
                    * (2 ** min(self._retries[key] - 1, 30)),
                    self.backoff_max,
                )
                self.enqueue_after(key, delay)
            elif self._retries[key] <= self.MAX_RETRIES:
                self.enqueue(key)
            else:
                log.error("worker %s: dropping %r after max retries", self.name, key)
                del self._retries[key]
        else:
            self._retries.pop(key, None)


class Runtime:
    """Holds all workers of a control plane and drives them cooperatively.

    ``run_until_settled`` round-robins workers until every queue is empty
    (i.e. the control plane reached a fixed point) or the step budget is hit.
    """

    def __init__(self) -> None:
        self.workers: list[Worker] = []
        self._tickers: list[Callable[[], None]] = []
        #: wall-clock mode (serve deployments): failing keys back off
        #: exponentially instead of hot-looping; see Worker._finish
        self.realtime = False

    def new_worker(self, name: str, reconcile, **kw) -> Worker:
        w = Worker(name, reconcile, runtime=self, **kw)
        self.workers.append(w)
        return w

    def next_due(self) -> Optional[float]:
        """Seconds until the earliest backed-off key anywhere is due, or
        None — the serve loop's sleep bound."""
        dues = [d for w in self.workers if (d := w.next_due()) is not None]
        return min(dues) if dues else None

    def add_ticker(self, fn: Callable[[], None]) -> None:
        """Periodic function run at the start of each run_until_settled call
        (cluster status refresh, descheduler sweep, etc. — the analogue of
        wait.Until loops)."""
        self._tickers.append(fn)

    def tick(self) -> None:
        for fn in self._tickers:
            fn()

    def pending(self) -> int:
        return sum(len(w) for w in self.workers)

    # called every HEARTBEAT_EVERY drained items mid-settle (None = off).
    # Returning False aborts the drain with work still queued — the seam a
    # leader-elected plane uses to renew its Lease during a storm settle
    # and to STOP reconciling the moment it is deposed (client-go renews on
    # a background goroutine; this runtime is cooperative, so renewal must
    # ride the drain loop itself)
    heartbeat = None
    HEARTBEAT_EVERY = 256

    def run_until_settled(self, max_steps: int = 100_000, *, tick: bool = True) -> int:
        """Process queued work until quiescent. Returns steps executed.

        Tickers run once at the start (not per pass — a ticker that always
        enqueues would never settle); wall-clock periodicity comes from the
        caller invoking this repeatedly, as a real deployment's main loop
        does. ``heartbeat`` (if set) is invoked every HEARTBEAT_EVERY items
        so long drains cannot starve time-critical duties; a False return
        aborts the drain (remaining keys stay queued for the next call).

        Wave tracing: a settle with queued work is the unit the wave tree
        hangs off — a ``settle`` root span wraps the drain, one
        ``controller.<worker>`` child span per contiguous worker drain
        (NOT per key: a 100k-binding storm is a handful of spans, not
        100k), and the wave closes at quiescence so the next trigger
        starts a fresh wave. Per-worker drain counts feed the
        karmada_tpu_worker_* metric families once per drain — never per
        key, the drain loop is the storm hot path."""
        if tick:
            self.tick()
        if self.pending() == 0:
            due = self.next_due()
            if due is None or due > 0:
                return 0  # quiescent (no queued keys, no due-parked keys)
        from .metrics import settle_seconds, worker_queue_depth, worker_reconciles
        from .tracing import tracer

        tracer.ensure_wave("settle")
        steps = 0
        next_beat = self.HEARTBEAT_EVERY
        aborted = False
        with tracer.span("settle") as root:
            while steps < max_steps and not aborted:
                progressed = False
                for w in self.workers:
                    drained = 0
                    # the whole drain — including its FIRST item — runs
                    # inside the controller span; an idle poll discards
                    # the span so quiescent workers leave no trace
                    with tracer.span(f"controller.{w.name}") as sp:
                        while (
                            steps < max_steps
                            and not aborted
                            and w.process_one()
                        ):
                            steps += 1
                            drained += 1
                            if (
                                self.heartbeat is not None
                                and steps >= next_beat
                            ):
                                next_beat = steps + self.HEARTBEAT_EVERY
                                if self.heartbeat() is False:
                                    aborted = True
                        sp.attrs["items"] = drained
                        if not drained:
                            sp.attrs["_discard"] = True
                    if not drained:
                        continue
                    progressed = True
                    worker_reconciles.inc(drained, worker=w.name)
                    worker_queue_depth.set(len(w), worker=w.name)
                    if aborted or steps >= max_steps:
                        break
                if not progressed:
                    break
            root.attrs["steps"] = steps
        settle_seconds.observe(root.duration)
        if self.pending() == 0:
            tracer.end_wave()
        return steps
