"""Reconcile runtime: rate-limited work queues + a deterministic driver.

Ref: pkg/util/worker.go:33-140 (util.AsyncWorker — workqueue + reconcile
loop). The TPU build keeps the same enqueue/reconcile contract but adds a
deterministic cooperative mode (``Runtime.run_until_settled``) so the whole
control plane can be exercised in-process without sleeping threads — the
pattern SURVEY.md section 4.3 calls "distributed-without-a-cluster".
"""

from __future__ import annotations

import collections
import logging
from typing import Callable, Hashable, Optional

log = logging.getLogger("karmada_tpu")

# Reconcile results
DONE = "done"
REQUEUE = "requeue"


class Worker:
    """A named reconcile queue. ``reconcile(key)`` returns DONE or REQUEUE
    (or raises — treated as REQUEUE with backoff count)."""

    MAX_RETRIES = 16

    def __init__(
        self,
        name: str,
        reconcile: Callable[[Hashable], Optional[str]],
        *,
        reconcile_batch: Optional[
            Callable[[list[Hashable]], dict[Hashable, Optional[str]]]
        ] = None,
        batch_size: int = 1024,
    ):
        self.name = name
        self.reconcile = reconcile
        # optional vectorized drain: given up to batch_size queued keys,
        # returns per-key results (missing keys count as DONE). Lets batch
        # engines (the tensor scheduler) amortize one kernel pass over every
        # queued item instead of paying per-key packing/dispatch.
        self.reconcile_batch = reconcile_batch
        self.batch_size = batch_size
        self._queue: collections.deque[Hashable] = collections.deque()
        self._queued: set[Hashable] = set()
        self._retries: collections.Counter = collections.Counter()

    def enqueue(self, key: Hashable) -> None:
        if key not in self._queued:
            self._queued.add(key)
            self._queue.append(key)

    def __len__(self) -> int:
        return len(self._queue)

    def process_one(self) -> bool:
        """Pop and reconcile one key (or one batch when a batch reconciler
        is installed and multiple keys are queued). Returns True if work was
        done."""
        if not self._queue:
            return False
        if self.reconcile_batch is not None and len(self._queue) > 1:
            keys = []
            while self._queue and len(keys) < self.batch_size:
                k = self._queue.popleft()
                self._queued.discard(k)
                keys.append(k)
            results = self._drain_batch(keys)
            for k in keys:
                self._finish(k, results.get(k, DONE))
            return True
        key = self._queue.popleft()
        self._queued.discard(key)
        try:
            result = self.reconcile(key)
        except Exception:  # noqa: BLE001 — reconcile errors requeue, like workqueue
            log.exception("worker %s: reconcile %r failed", self.name, key)
            result = REQUEUE
        self._finish(key, result)
        return True

    #: poisoned keys tolerated per drain before the failure is treated as
    #: systemic (whole engine down, not bad keys); each poisoned key costs
    #: ~log2(batch) failing sub-batch calls down its bisect path
    POISON_TOLERANCE = 4

    def _drain_batch(self, keys: list[Hashable]) -> dict[Hashable, Optional[str]]:
        """Run reconcile_batch with poisoned-key isolation.

        A batch-wide REQUEUE on exception would make every key in the batch
        burn retries together with the one bad key (all dropped together at
        MAX_RETRIES). Instead, bisect the failing batch: healthy halves stay
        batched, and only genuinely failing keys pay a retry. A failure
        budget caps the fan-out when the failure is systemic (every sub-call
        failing) so a batch-wide transient costs O(budget) calls and one
        logged traceback, not O(batch) of each."""
        results: dict[Hashable, Optional[str]] = {}
        failures = 0
        budget = self.POISON_TOLERANCE * max(1, len(keys).bit_length())

        def run(ks: list[Hashable]) -> None:
            nonlocal failures
            if failures > budget:
                for k in ks:
                    results[k] = REQUEUE
                return
            try:
                if len(ks) == 1:
                    results[ks[0]] = self.reconcile(ks[0])
                else:
                    results.update(self.reconcile_batch(ks))
                return
            except Exception:  # noqa: BLE001
                failures += 1
                if failures == 1:
                    log.exception(
                        "worker %s: batch reconcile failed; bisecting", self.name
                    )
                else:
                    log.error(
                        "worker %s: reconcile of %d key(s) failed (failure %d)",
                        self.name, len(ks), failures,
                    )
                if len(ks) == 1:
                    results[ks[0]] = REQUEUE
                    return
            mid = len(ks) // 2
            run(ks[:mid])
            run(ks[mid:])

        run(keys)
        return results

    def _finish(self, key: Hashable, result: Optional[str]) -> None:
        if result == REQUEUE:
            self._retries[key] += 1
            if self._retries[key] <= self.MAX_RETRIES:
                self.enqueue(key)
            else:
                log.error("worker %s: dropping %r after max retries", self.name, key)
                del self._retries[key]
        else:
            self._retries.pop(key, None)


class Runtime:
    """Holds all workers of a control plane and drives them cooperatively.

    ``run_until_settled`` round-robins workers until every queue is empty
    (i.e. the control plane reached a fixed point) or the step budget is hit.
    """

    def __init__(self) -> None:
        self.workers: list[Worker] = []
        self._tickers: list[Callable[[], None]] = []

    def new_worker(self, name: str, reconcile, **kw) -> Worker:
        w = Worker(name, reconcile, **kw)
        self.workers.append(w)
        return w

    def add_ticker(self, fn: Callable[[], None]) -> None:
        """Periodic function run at the start of each run_until_settled call
        (cluster status refresh, descheduler sweep, etc. — the analogue of
        wait.Until loops)."""
        self._tickers.append(fn)

    def tick(self) -> None:
        for fn in self._tickers:
            fn()

    def pending(self) -> int:
        return sum(len(w) for w in self.workers)

    def run_until_settled(self, max_steps: int = 100_000, *, tick: bool = True) -> int:
        """Process queued work until quiescent. Returns steps executed.

        Tickers run once at the start (not per pass — a ticker that always
        enqueues would never settle); wall-clock periodicity comes from the
        caller invoking this repeatedly, as a real deployment's main loop
        does."""
        if tick:
            self.tick()
        steps = 0
        while steps < max_steps:
            progressed = False
            for w in self.workers:
                while w.process_one():
                    progressed = True
                    steps += 1
                    if steps >= max_steps:
                        return steps
            if not progressed:
                break
        return steps
