"""Object builders for tests, benchmarks and synthetic fleets.

The analogue of the reference's test/helper/resource.go builders
(NewCluster, NewClusterWithResource, ...) plus synthetic-fleet generators for
the BASELINE.json workloads (100 bindings x 20 clusters up to 100k x 5k).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from ..api.cluster import (
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
    Taint,
)
from ..api.core import Condition, ObjectMeta
from ..api.policy import (
    ClusterAffinity,
    ClusterPreferences,
    LabelSelector,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
    StaticClusterWeight,
)
from .quantity import parse_resource_list


def new_cluster(
    name: str,
    *,
    cpu: str | int = "100",
    memory: str | int = "200Gi",
    pods: int = 1000,
    allocated: Optional[Mapping[str, int | str]] = None,
    labels: Optional[Mapping[str, str]] = None,
    provider: str = "",
    region: str = "",
    zone: str = "",
    taints: Sequence[Taint] = (),
    api_enablements: Sequence[str] = ("apps/v1/Deployment",),
    complete_enablements: bool = True,
    ready: bool = True,
) -> Cluster:
    allocatable = parse_resource_list({"cpu": cpu, "memory": memory, "pods": pods})
    conditions = [Condition(type="Ready", status=ready)]
    if complete_enablements:
        conditions.append(Condition(type="CompleteAPIEnablements", status=True))
    return Cluster(
        meta=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=ClusterSpec(
            provider=provider,
            region=region,
            zones=[zone] if zone else [],
            taints=list(taints),
        ),
        status=ClusterStatus(
            api_enablements=list(api_enablements),
            conditions=conditions,
            resource_summary=ResourceSummary(
                allocatable=allocatable,
                allocated=parse_resource_list(dict(allocated)) if allocated else {},
            ),
        ),
    )


def duplicated_placement(**kw) -> Placement:
    return Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Duplicated"
        ),
        **kw,
    )


def static_weight_placement(
    weights: Mapping[str, int], **kw
) -> Placement:
    """Weights keyed by cluster name."""
    return Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Weighted",
            weight_preference=ClusterPreferences(
                static_weight_list=[
                    StaticClusterWeight(
                        target_cluster=ClusterAffinity(cluster_names=[n]), weight=w
                    )
                    for n, w in weights.items()
                ]
            ),
        ),
        **kw,
    )


def dynamic_weight_placement(**kw) -> Placement:
    return Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Weighted",
            weight_preference=ClusterPreferences(dynamic_weight="AvailableReplicas"),
        ),
        **kw,
    )


def aggregated_placement(**kw) -> Placement:
    return Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Aggregated",
        ),
        **kw,
    )


def new_deployment(
    name: str,
    *,
    namespace: str = "default",
    replicas: int = 2,
    cpu: str = "250m",
    memory: str = "512Mi",
    image: str = "nginx:1.25",
    labels: Optional[Mapping[str, str]] = None,
) -> "Resource":
    """A kube-shaped Deployment template (the samples/nginx analogue)."""
    from ..api.core import ObjectMeta, Resource

    return Resource(
        api_version="apps/v1",
        kind="Deployment",
        meta=ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec={
            "replicas": replicas,
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": name,
                            "image": image,
                            "resources": {
                                "requests": {"cpu": cpu, "memory": memory}
                            },
                        }
                    ]
                }
            },
        },
    )


def synthetic_fleet(
    num_clusters: int,
    *,
    seed: int = 0,
    regions: int = 8,
    zones_per_region: int = 4,
    providers: Sequence[str] = ("aws", "gcp", "azure"),
    taint_fraction: float = 0.05,
    label_sets: int = 16,
) -> list[Cluster]:
    """Synthetic member fleet mirroring the scale knobs of BASELINE.json:
    heterogeneous capacity, topology spread, a tainted slice, label variety."""
    rng = np.random.default_rng(seed)
    clusters = []
    for i in range(num_clusters):
        region = f"region-{rng.integers(0, regions)}"
        zone = f"{region}-z{rng.integers(0, zones_per_region)}"
        cores = int(rng.choice([16, 32, 64, 128]))
        nodes = int(rng.integers(2, 50))
        taints = (
            [Taint(key="fleet.io/dedicated", value="infra", effect="NoSchedule")]
            if rng.random() < taint_fraction
            else []
        )
        labels = {
            "tier": f"t{rng.integers(0, label_sets)}",
            "env": str(rng.choice(["prod", "staging", "dev"])),
        }
        alloc_frac = float(rng.uniform(0.2, 0.8))
        total_cpu = cores * nodes
        clusters.append(
            new_cluster(
                f"member-{i}",
                cpu=total_cpu,
                memory=f"{4 * total_cpu}Gi",
                pods=nodes * 110,
                allocated={
                    "cpu": total_cpu * alloc_frac,  # cores (canonicalized to milli)
                    "memory": int(4 * total_cpu * alloc_frac * (1 << 30)),
                    "pods": int(nodes * 110 * alloc_frac),
                },
                labels=labels,
                provider=str(rng.choice(list(providers))),
                region=region,
                zone=zone,
                taints=taints,
            )
        )
    return clusters
