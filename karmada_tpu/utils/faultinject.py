"""Deterministic fault injection: every failure scenario is a replayable seed.

The chaos half of the failover plane (ISSUE 7 tentpole a). A seeded
registry of *fault rules* armed from the environment
(``KARMADA_TPU_FAULT_SPEC`` + ``KARMADA_TPU_FAULT_SEED``) or
programmatically (``arm()``), consulted at fixed *injection points* at the
transport seams (estimator/solver/bus RPCs) and the cluster model (member
health). Disarmed — the default — an injection point costs ONE module-
global ``is None`` check and allocates nothing, so the production hot path
is untouched; armed, every firing decision derives from
``blake2b(seed, point, invocation-index)``, so a failure storm replays
bit-identically from its seed and the fired-event log is itself the replay
script a numpy oracle can consume (refimpl/failover_np.py).

Spec grammar (semicolon-separated rules)::

    point=action[,rate=R][,count=N][,after=K][,match=SUBSTR][,delay=S]

    estimator.rpc=error,rate=0.5,count=10      # fail ~half of 10 firings
    solver.rpc=drop,match=ScoreAndAssign       # black-hole solver scoring
    bus.rpc=delay,delay=0.2                    # slow the bus write path
    cluster.health=down,match=member3          # flip member3 NotReady
    estimator.rpc=sever,after=100              # kill the channel later on

Actions:
- ``error``  — the seam raises an injected transport error (a subclass of
  the channel's natural error type, so retry/breaker paths engage).
- ``drop``   — like ``error`` but after sleeping the attempt timeout
  (a black-holed RPC: the deadline is paid, then the failure surfaces).
- ``delay``  — sleep ``delay`` seconds, then proceed normally.
- ``sever``  — the seam closes its connection/channel before erroring,
  forcing a reconnect (and a batch-protocol re-probe) on next use.
- ``down``   — the cluster model reads the member as unreachable
  (``cluster.health`` point only).

Injection points shipped in-tree (grep ``fault_point(`` for the live set):
``estimator.rpc``, ``solver.rpc``, ``bus.rpc``, ``bus.watch``,
``cluster.health``.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

#: spec + seed environment knobs (registered in utils.flags ENV_FLAGS)
FAULT_SPEC_ENV = "KARMADA_TPU_FAULT_SPEC"
FAULT_SEED_ENV = "KARMADA_TPU_FAULT_SEED"

_ACTIONS = ("error", "drop", "delay", "sever", "down")


class FaultError(Exception):
    """Base of every injected failure (seams re-dress it as the channel's
    natural error type via ``injected_error`` so retry paths engage)."""


_grpc_fault_cls = None


def injected_error(point: str, key: str = "") -> Exception:
    """An exception that is BOTH ``FaultError`` and ``grpc.RpcError`` with
    ``code() == UNAVAILABLE`` — the gRPC seams raise this so their callers'
    ``except grpc.RpcError`` retry/failover paths treat an injected fault
    exactly like a real channel failure."""
    global _grpc_fault_cls
    if _grpc_fault_cls is None:
        import grpc  # lazy: keep module import jax/grpc-free

        class _InjectedRpcError(FaultError, grpc.RpcError):
            def __init__(self, message: str):
                super().__init__(message)

            def code(self):
                return grpc.StatusCode.UNAVAILABLE

            def details(self):
                return str(self)

        _grpc_fault_cls = _InjectedRpcError
    return _grpc_fault_cls(f"injected fault at {point} ({key})")


@dataclass
class FaultRule:
    point: str
    action: str
    rate: float = 1.0  # firing probability per eligible invocation
    count: Optional[int] = None  # max firings (None = unbounded)
    after: int = 0  # eligible only from this invocation index on
    match: str = ""  # substring filter over the call-site key
    delay_s: float = 0.05  # sleep for ``delay`` (and pre-error for ``drop``)
    fired: int = 0

    def eligible(self, key: str, invocation: int) -> bool:
        if self.match and self.match not in key:
            return False
        if invocation < self.after:
            return False
        return self.count is None or self.fired < self.count


@dataclass
class FaultEvent:
    """One fired fault — the registry's log is the replay script."""

    seq: int
    point: str
    action: str
    key: str


class FaultInjector:
    """Seeded rule registry. Thread-safe: injection points fire from RPC
    fan-out executors and controller workers concurrently; the per-point
    invocation counters (the determinism source) mutate under one lock."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules: dict[str, list[FaultRule]] = {}
        for r in rules:
            self.rules.setdefault(r.point, []).append(r)
        self.seed = seed
        self.log: list[FaultEvent] = []
        self._invocations: dict[str, int] = {}
        self._lock = threading.Lock()

    def _decide(self, point: str, invocation: int, rate: float) -> bool:
        if rate >= 1.0:
            return True
        h = hashlib.blake2b(
            f"{self.seed}:{point}:{invocation}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(h, "little") / 2**64 < rate

    def fire(self, point: str, key: str = "") -> Optional[FaultRule]:
        """The armed half of ``fault_point``: returns the first rule that
        fires for this invocation (and logs it), else None."""
        rules = self.rules.get(point)
        if not rules:
            return None
        with self._lock:
            inv = self._invocations.get(point, 0)
            self._invocations[point] = inv + 1
            for rule in rules:
                if not rule.eligible(key, inv):
                    continue
                if not self._decide(point, inv, rule.rate):
                    continue
                rule.fired += 1
                self.log.append(
                    FaultEvent(len(self.log), point, rule.action, key)
                )
                return rule
        return None


#: the armed injector; None = disarmed (the zero-overhead steady state)
_INJECTOR: Optional[FaultInjector] = None


def parse_spec(spec: str) -> list[FaultRule]:
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, opts = part.partition(",")
        point, _, action = head.partition("=")
        point, action = point.strip(), action.strip()
        if not point or action not in _ACTIONS:
            raise ValueError(
                f"fault rule {part!r}: want point=action with action in "
                f"{_ACTIONS}"
            )
        rule = FaultRule(point=point, action=action)
        for opt in opts.split(","):
            opt = opt.strip()
            if not opt:
                continue
            k, _, v = opt.partition("=")
            if k == "rate":
                rule.rate = float(v)
            elif k == "count":
                rule.count = int(v)
            elif k == "after":
                rule.after = int(v)
            elif k == "match":
                rule.match = v
            elif k == "delay":
                rule.delay_s = float(v)
            else:
                raise ValueError(f"fault rule {part!r}: unknown option {k!r}")
        rules.append(rule)
    return rules


def arm(spec: str, seed: int = 0) -> FaultInjector:
    """Install (replace) the process-wide injector from a spec string."""
    global _INJECTOR
    _INJECTOR = FaultInjector(parse_spec(spec), seed=seed)
    return _INJECTOR


def disarm() -> None:
    global _INJECTOR
    _INJECTOR = None


def injector() -> Optional[FaultInjector]:
    return _INJECTOR


def arm_from_env() -> Optional[FaultInjector]:
    """Arm from KARMADA_TPU_FAULT_SPEC / KARMADA_TPU_FAULT_SEED (process
    entrypoints call this once at boot; empty spec leaves it disarmed)."""
    spec = os.environ.get(FAULT_SPEC_ENV, "")
    if not spec:
        return None
    try:
        seed = int(os.environ.get(FAULT_SEED_ENV, "0") or 0)
    except ValueError:
        seed = 0
    return arm(spec, seed)


def fault_point(point: str, key: str = "") -> Optional[FaultRule]:
    """THE injection-point call. Disarmed: one global load + ``is None``
    test, no allocation — safe on every hot path."""
    if _INJECTOR is None:
        return None
    return _INJECTOR.fire(point, key)


def apply_fault(
    rule: Optional[FaultRule], point: str, key: str = "", *, channel=None
) -> None:
    """Standard action interpreter for RPC seams: sleep for delay/drop,
    close the channel for sever, raise the injected transport error for
    error/drop/sever. ``delay`` returns normally (the call proceeds)."""
    if rule is None:
        return
    import time as _time

    if rule.action == "delay":
        _time.sleep(rule.delay_s)
        return
    if rule.action == "drop":
        _time.sleep(rule.delay_s)
    if rule.action == "sever" and channel is not None:
        try:
            channel.close()
        except Exception:  # noqa: BLE001 — sever teardown is best-effort
            pass
    raise injected_error(point, key)
