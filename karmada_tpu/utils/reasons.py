"""REASONS: the plane-wide reason-code taxonomy (ISSUE 13).

Ref: the reference scheduler's whole diagnostic story is per-binding
``Scheduled`` conditions and filter-stage events out of the
Filter/Score/Select/AssignReplicas pipeline (scheduler.go:827-919,
generic_scheduler.go) — every ``reason`` it stamps is a well-known
CamelCase code, never free text. Until this module the repo's reasons
were ad-hoc string literals scattered across controllers (a free-text
``QuotaExceeded`` here, an uncoded ``NoClusterFit`` there) and silence
from the kernels; provenance needs one registry the exclusion bitmask
(ops/explain.py), the ``Scheduled=False`` breakdowns, the
``karmada_tpu_unschedulable_total{reason}`` family, the generated docs
table and graftlint GL010 can all key on.

Three kinds of reason:

- ``stage`` — one per decision stage of the scheduling pipeline, in
  EXCLUSION-BIT ORDER: ``STAGE_REASONS[i]`` is the meaning of bit ``i``
  in the packed per-binding x per-cluster exclusion mask the explain
  kernel emits (ops/explain.py derives its bit constants from this
  tuple, and refimpl/explain_np.py is asserted bit-identical against
  it). Appending a stage appends a bit; NEVER reorder.
- ``condition`` — codes written into API object conditions
  (``Scheduled``, ``Ready``, ``Applied``...).
- ``event`` — codes attached to evictions and other one-shot
  transitions (graceful-eviction producers).

graftlint GL010 (the GL008 span-taxonomy pattern) fails tier-1 when any
``Condition(reason="...")`` or ``.inc(reason="...")`` literal in the
import graph is missing here, and the docs reason table is generated
from this registry between the ``reasontaxonomy`` markers
(``tools/docs_from_bench.py --reasons-table`` + a drift check on every
regen), so a code can never ship undocumented.

Stdlib-only: the bus and every other lean process can import this (and
the linter imports it live) without dragging in numpy or jax.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Reason:
    """One registered reason code. ``stage_bit`` is the exclusion-mask
    bit position for ``kind="stage"`` reasons (None otherwise)."""

    code: str
    #: "stage" | "condition" | "event"
    kind: str
    description: str
    stage_bit: Optional[int] = None


#: THE decision-stage order — index IS the exclusion-mask bit position
#: (ops/explain.py packs, utils/explainstore.py decodes, and the numpy
#: oracle mirrors exactly this order). Append-only; never reorder.
STAGE_REASONS: tuple[str, ...] = (
    "AffinityMismatch",  # bit 0
    "TaintUntolerated",  # bit 1
    "ApiNotEnabled",  # bit 2
    "NoAvailableReplicas",  # bit 3
    "QuotaCapExceeded",  # bit 4
    "QuotaExceeded",  # bit 5
    "SpreadConstraintUnsatisfied",  # bit 6
    "PreemptedByHigherPriority",  # bit 7
)


def _stage(code: str, description: str) -> Reason:
    return Reason(
        code=code, kind="stage", description=description,
        stage_bit=STAGE_REASONS.index(code),
    )


REASONS: dict[str, Reason] = {
    r.code: r
    for r in (
        # -- decision stages (exclusion-mask bits, in order) ---------------
        _stage(
            "AffinityMismatch",
            "cluster is outside the binding's selected ClusterAffinities "
            "group (affinity/group-rank stage; the explain capture also "
            "records WHICH ordered fallback group was selected)",
        ),
        _stage(
            "TaintUntolerated",
            "cluster carries an untolerated NoSchedule/NoExecute taint or "
            "an active graceful-eviction task (already-placed leniency "
            "composed, taint_toleration.go) — also the graceful-eviction "
            "producer code the cluster controller stamps",
        ),
        _stage(
            "ApiNotEnabled",
            "cluster does not enable the workload's API/GVK "
            "(api_enablement.go; already-placed leniency composed)",
        ),
        _stage(
            "NoAvailableReplicas",
            "merged estimator availability is zero for this cluster "
            "(dynamic-weight strategies only — Duplicated never consults "
            "availability)",
        ),
        _stage(
            "QuotaCapExceeded",
            "a FederatedResourceQuota static-assignment hard cap answers "
            "zero replicas for this cluster (quota cluster-cap stage)",
        ),
        _stage(
            "QuotaExceeded",
            "binding denied by batched FIFO quota admission (wave-level: "
            "the bit is set on every cluster) — also the Scheduled=False "
            "condition code",
        ),
        _stage(
            "SpreadConstraintUnsatisfied",
            "cluster dropped by spread-constraint group selection "
            "(select_clusters.go), or fails a spread field filter",
        ),
        _stage(
            "PreemptedByHigherPriority",
            "the binding holds a preemption graceful-eviction task from "
            "this cluster (the scarcity plane's victim path) — also the "
            "eviction reason the preemption controller stamps and the "
            "karmada_tpu_preemptions_total reason label",
        ),
        # -- scheduling conditions (Scheduled + unschedulable taxonomy) ----
        Reason("Success", "condition", "binding scheduled successfully"),
        Reason(
            "NoClusterFit", "condition",
            "no cluster survives the filter stages for any affinity group",
        ),
        Reason(
            "InsufficientReplicas", "condition",
            "candidate clusters' summed availability cannot cover the "
            "requested replicas (the divider's unschedulable cohort)",
        ),
        Reason(
            "NoAffinityGroupFits", "condition",
            "every ordered ClusterAffinities fallback group was tried and "
            "none schedules",
        ),
        Reason(
            "Unschedulable", "condition",
            "binding not scheduled for an unclassified engine reason "
            "(the residual bucket of the unschedulable taxonomy)",
        ),
        # -- cluster/remedy/work/operator conditions ------------------------
        Reason("ClusterReady", "condition", "cluster reachable and healthy"),
        Reason(
            "ClusterNotReachable", "condition",
            "push-mode cluster stopped answering collect",
        ),
        Reason(
            "AgentLeaseRenewed", "condition",
            "pull-mode agent lease is fresh",
        ),
        Reason(
            "AgentLeaseExpired", "condition",
            "pull-mode agent lease expired",
        ),
        Reason(
            "DomainNameResolved", "condition",
            "remedy probe: cluster ingress domain resolves",
        ),
        Reason(
            "DomainNameResolutionFailed", "condition",
            "remedy probe: cluster ingress domain resolution failed",
        ),
        Reason(
            "AppliedSuccessful", "condition",
            "work manifests applied on the member",
        ),
        Reason(
            "ClusterUnreachable", "condition",
            "work could not be dispatched: member unreachable",
        ),
        Reason(
            "ResourceConflict", "condition",
            "work apply rejected: conflicting resource on the member",
        ),
        Reason(
            "SuspendDispatching", "condition",
            "work dispatching administratively suspended",
        ),
        Reason(
            "FullyAppliedSuccess", "condition",
            "every scheduled cluster's work applied",
        ),
        Reason("Completed", "condition", "operator task completed"),
        Reason("TaskFailed", "condition", "operator task failed"),
        Reason("Removed", "condition", "operator instance removed"),
        Reason(
            "CrashLoopBackOff", "condition",
            "operator-managed component restarting repeatedly",
        ),
        Reason(
            "BackOff", "condition",
            "operator-managed component down, restart pending",
        ),
        Reason(
            "AllAlive", "condition",
            "every operator-managed component process is alive",
        ),
        # -- scarcity-plane conditions/events (ISSUE 14) ---------------------
        Reason(
            "Preempted", "condition",
            "victim binding displaced by the plane-wide preemption "
            "kernel, awaiting re-placement through the ranked failover "
            "path (condition type Preempted; the message names the "
            "displacing binding)",
        ),
        Reason(
            "RebalanceTriggered", "event",
            "continuous-descheduler drift re-placement: the binding's "
            "resident placement scored worse than a fresh solve and a "
            "RescheduleTriggeredAt was stamped within the disruption "
            "budget — also a karmada_tpu_preemptions_total reason label",
        ),
        # -- eviction events -------------------------------------------------
        Reason(
            "ApplicationFailure", "event",
            "graceful eviction produced by application-failure failover",
        ),
    )
}

assert all(
    REASONS[c].stage_bit == i for i, c in enumerate(STAGE_REASONS)
), "STAGE_REASONS order drifted from the registry"


def reason_registered(code: str) -> bool:
    return code in REASONS


#: engine free-text errors -> reason codes (the unschedulable taxonomy).
#: ScheduleResult.error strings are wire/compat surface (tests and the
#: oracle match on them), so the classification maps rather than renames.
_ERROR_REASONS: tuple[tuple[str, str], ...] = (
    ("namespace quota exceeded", "QuotaExceeded"),
    ("no clusters fit the placement", "NoClusterFit"),
    ("clusters available replicas are not enough", "InsufficientReplicas"),
    ("no affinity group fits", "NoAffinityGroupFits"),
)


def classify_error(error: str) -> str:
    """Reason code for an engine ``ScheduleResult.error`` ("" answers
    ``Success``; unknown text answers the residual ``Unschedulable``)."""
    if not error:
        return "Success"
    for needle, code in _ERROR_REASONS:
        if needle in error:
            return code
    return "Unschedulable"


class TransitionDedup:
    """Shared once-per-transition counter gate (ISSUE 13 satellite).

    ``observe(key, reason, generation)`` answers True exactly when the
    (reason, generation) pair differs from the last observation for
    ``key`` — so a parked binding re-enqueued across passes within one
    generation can never double-increment ``quota_denied_total`` /
    ``unschedulable_total``, while a NEW generation (quota moved, spec
    changed) counts again. Lock-disciplined; bounded by ``cap`` (full =
    wholesale reset — counters over-count once rather than grow without
    bound, the ring discipline)."""

    def __init__(self, cap: int = 1 << 20):
        self.cap = cap
        self._lock = threading.Lock()
        self._last: dict = {}

    def observe(self, key, reason: str, generation=None) -> bool:
        state = (reason, generation)
        with self._lock:
            if self._last.get(key) == state:
                return False
            if len(self._last) >= self.cap and key not in self._last:
                self._last.clear()
            self._last[key] = state
            return True

    def forget(self, key) -> None:
        with self._lock:
            self._last.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._last.clear()


def render_reasons_table() -> str:
    """The docs/OPERATIONS.md reason-taxonomy table, generated from
    ``REASONS`` so prose can never drift from the registry the linter
    and the explain surface enforce (tools/docs_from_bench.py writes it
    between the reasontaxonomy markers and fails loudly on drift)."""
    lines = [
        "| reason | kind | exclusion bit | meaning |",
        "|---|---|---|---|",
    ]

    def sort_key(r: Reason):
        return (
            {"stage": 0, "condition": 1, "event": 2}[r.kind],
            r.stage_bit if r.stage_bit is not None else -1,
            r.code,
        )

    for r in sorted(REASONS.values(), key=sort_key):
        bit = "—" if r.stage_bit is None else str(r.stage_bit)
        lines.append(f"| `{r.code}` | {r.kind} | {bit} | {r.description} |")
    return "\n".join(lines)
