"""Resource quantity parsing and canonical units.

The reference uses k8s ``resource.Quantity`` everywhere. We canonicalize every
resource into a plain ``int`` in a fixed per-resource unit so that capacity
math is exact integer arithmetic (and packs into int32/int64 tensors):

- ``cpu``  -> millicores ("1" == 1000, "250m" == 250)
- ``memory``/storage-like -> bytes ("1Gi" == 2**30)
- everything else (``pods``, extended resources) -> absolute count

Division semantics mirror the reference estimator (integer floor division,
cpu compared in milli, others in absolute value):
pkg/estimator/client/general.go:156-196.
"""

from __future__ import annotations

import re
from typing import Mapping

# Binary (Ki/Mi/...) and decimal (k/M/...) suffix multipliers.
_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "": 1.0, "k": 1e3, "M": 1e6, "G": 1e9,
        "T": 1e12, "P": 1e15, "E": 1e18}

# sign + digits + optional exponent ("1e9", "100e-3" are legal Quantity
# serializations), then an optional unit suffix. A bare trailing E is the
# decimal exa suffix; E followed by digits is an exponent (k8s semantics).
_QTY_RE = re.compile(r"^\s*([+-]?[0-9.]+(?:[eE][-+]?[0-9]+)?)\s*([A-Za-z]*)\s*$")

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"


def parse_quantity(value: "int | float | str", resource: str = "") -> int:
    """Parse a quantity into its canonical integer unit.

    ``resource`` selects the canonical unit (cpu -> milli). Numbers are taken
    to be in the resource's natural unit (cores for cpu, bytes for memory).
    """
    if isinstance(value, bool):  # bool is an int subclass; reject explicitly
        raise TypeError(f"invalid quantity: {value!r}")
    if isinstance(value, (int, float)):
        base = float(value)
    else:
        m = _QTY_RE.match(value)
        if not m:
            raise ValueError(f"invalid quantity: {value!r}")
        num, suffix = m.groups()
        if suffix in _BIN:
            base = float(num) * _BIN[suffix]
        elif suffix in _DEC:
            base = float(num) * _DEC[suffix]
        else:
            raise ValueError(f"invalid quantity suffix: {value!r}")
    if resource == CPU:
        return int(round(base * 1000))
    return int(round(base))


def parse_resource_list(resources: Mapping[str, "int | float | str"]) -> dict[str, int]:
    """Canonicalize a resource map, e.g. {"cpu": "250m", "memory": "1Gi"}."""
    return {name: parse_quantity(v, name) for name, v in resources.items()}


def sub_resource_lists(a: Mapping[str, int], b: Mapping[str, int]) -> dict[str, int]:
    """a - b per resource (missing in b treated as 0)."""
    return {k: v - b.get(k, 0) for k, v in a.items()}


def add_resource_lists(a: Mapping[str, int], b: Mapping[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out
