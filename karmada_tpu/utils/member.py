"""Member-cluster clients: the boundary to each member's state.

Ref analogues: pkg/util/membercluster_client.go (per-cluster clients),
pkg/util/objectwatcher/objectwatcher.go:43-307 (versioned create/update/
delete of propagated objects), pkg/util/fedinformer (per-cluster informers —
here watch handlers on the member store).

A MemberCluster is an in-process stand-in for one member kube-apiserver:
resources keyed by (gvk, namespace, name), node state for estimators, and a
reachability flag for failure injection (the e2e trick of SURVEY.md
section 4.3 / failover tests). A real deployment replaces this class with a
REST client; the controller code above it is transport-agnostic.
"""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from ..api.core import Resource
from ..estimator.accurate import NodeState
from .clone import clone_resource


class UnreachableError(Exception):
    pass


class ConflictError(Exception):
    """Propagation target already exists and is not managed by the control
    plane (ConflictResolution=Abort)."""


MANAGED_ANNOTATION = "karmada.io/managed"


@dataclass(frozen=True)
class MemberEvent:
    type: str  # Added | Modified | Deleted
    cluster: str
    gvk: str
    namespace: str
    name: str
    obj: Resource


class MemberCluster:
    """One member cluster's state."""

    def __init__(self, name: str):
        self.name = name
        self.reachable = True
        self.kubernetes_version = "v1.31.0"
        self.api_enablements: list[str] = [
            "apps/v1/Deployment",
            "apps/v1/StatefulSet",
            "batch/v1/Job",
            "v1/Pod",
            "v1/ConfigMap",
            "v1/Secret",
            "v1/Service",
            "v1/ServiceAccount",
        ]
        self.nodes: list[NodeState] = []
        self._resources: dict[tuple[str, str, str], Resource] = {}
        self._watchers: list[Callable[[MemberEvent], None]] = []
        self._lock = threading.RLock()
        # workload-key -> unschedulable replica count (descheduler input;
        # ref: estimator server/replica/replica.go)
        self.unschedulable_replicas: dict[str, int] = {}
        # workload-key -> metric sample {"pods", "ready_pods",
        # "cpu_utilization"} (metrics.k8s.io stand-in for the metrics adapter)
        self.pod_metrics: dict[str, dict] = {}
        # workload-key -> PER-POD sample set (the federated podList the
        # FederatedHPA replica calculator groups by readiness; field names
        # are controllers.replica_calculator.PodSample kwargs — request/
        # value in milli-units): [{"name", "phase", "ready", "request",
        # "value", ...}, ...]
        self.workload_pods: dict[str, list[dict]] = {}
        # metrics.k8s.io per-object surfaces (metricsadapter ResourceMetrics):
        # "namespace/pod" -> {"cpu": milli, "memory": bytes, "labels": {...}}
        self.pod_metrics_detail: dict[str, dict] = {}
        # node name -> {"cpu": milli, "memory": bytes, "labels": {...}}
        self.node_metrics: dict[str, dict] = {}
        # custom.metrics.k8s.io series (metricsadapter CustomMetrics): each
        # {"resource": "pods", "namespaced": bool, "namespace": str,
        #  "object": str, "metric": str, "value": float, "labels": {...}}
        self.custom_metric_series: list[dict] = []
        # external.metrics.k8s.io series: each {"namespace": str,
        #  "metric": str, "value": float, "labels": {...}}
        self.external_metric_series: list[dict] = []
        # pod runtime seam: log buffers + pluggable exec handler
        self._pod_logs: dict[tuple[str, str], list[str]] = {}
        self._log_arrived = threading.Condition(self._lock)
        self.exec_handler: Optional[Callable[[Resource, list], dict]] = None
        # streaming runtime seam: iterator[str] of live output lines
        # (SubprocessExecRuntime = a real OS subprocess end-to-end)
        self.exec_stream_handler: Optional[Callable] = None
        # proxy-passthrough audit: (path, impersonated user/groups) records
        self.proxy_audit: list[dict] = []

    # -- client surface ----------------------------------------------------

    def _check(self) -> None:
        if not self.reachable:
            raise UnreachableError(f"cluster {self.name} unreachable")

    def apply(self, obj: Resource) -> Resource:
        self._check()
        key = (f"{obj.api_version}/{obj.kind}", obj.meta.namespace, obj.meta.name)
        with self._lock:
            existed = key in self._resources
            obj.meta.resource_version += 1
            self._resources[key] = obj
        self._notify(
            MemberEvent(
                "Modified" if existed else "Added",
                self.name, key[0], key[1], key[2], obj,
            )
        )
        return obj

    def get(self, gvk: str, namespace: str, name: str) -> Optional[Resource]:
        self._check()
        with self._lock:
            return self._resources.get((gvk, namespace, name))

    def delete(self, gvk: str, namespace: str, name: str) -> Optional[Resource]:
        self._check()
        with self._lock:
            obj = self._resources.pop((gvk, namespace, name), None)
        if obj is not None:
            self._notify(MemberEvent("Deleted", self.name, gvk, namespace, name, obj))
        return obj

    def list(self, gvk: Optional[str] = None) -> list[Resource]:
        self._check()
        with self._lock:
            return [
                o for (g, _, _), o in self._resources.items() if gvk is None or g == gvk
            ]

    def watch(self, handler: Callable[[MemberEvent], None]) -> None:
        self._watchers.append(handler)

    def _notify(self, event: MemberEvent) -> None:
        for h in list(self._watchers):
            h(event)

    # -- pod runtime seam (logs / exec / attach + unschedulable counting) --

    def add_pod(
        self,
        namespace: str,
        name: str,
        *,
        owner_key: str = "",
        conditions: Optional[list[dict]] = None,
        labels: Optional[dict[str, str]] = None,
    ) -> Resource:
        """Register a pod in the member state. Pods are ordinary "v1/Pod"
        resources; ``owner_key`` links the pod to its workload (the stand-in
        for the ownerRef/label-selector match in estimator
        server/replica/replica.go:43-77)."""
        from ..api.core import ObjectMeta

        pod = Resource(
            api_version="v1",
            kind="Pod",
            meta=ObjectMeta(namespace=namespace, name=name, labels=dict(labels or {})),
            spec={"owner_key": owner_key},
            status={"conditions": list(conditions or [])},
        )
        return self.apply(pod)

    def mark_pod_unschedulable(
        self, namespace: str, name: str, since: float
    ) -> None:
        """Set the PodScheduled=False/Unschedulable condition (the signal
        GetUnschedulableReplicas counts)."""
        pod = self.get("v1/Pod", namespace, name)
        if pod is None:
            return
        conds = [
            c
            for c in pod.status.setdefault("conditions", [])
            if c.get("type") != "PodScheduled"
        ]
        conds.append(
            {
                "type": "PodScheduled",
                "status": "False",
                "reason": "Unschedulable",
                "last_transition": since,
            }
        )
        pod.status["conditions"] = conds
        self.apply(pod)

    def count_unschedulable(
        self, now: float, threshold_seconds: float = 60.0
    ) -> dict[str, int]:
        """workload-key -> replicas stuck PodScheduled=False/Unschedulable
        for longer than the threshold (ref: server/replica/replica.go:43-77;
        the threshold mirrors --unschedulable-threshold). Explicit
        ``unschedulable_replicas`` entries (simulation overrides) are merged
        in, taking the max per workload."""
        counts: dict[str, int] = {}
        for pod in self.list("v1/Pod"):
            owner = (pod.spec or {}).get("owner_key", "")
            if not owner:
                continue
            for cond in (pod.status or {}).get("conditions", []):
                if (
                    cond.get("type") == "PodScheduled"
                    and cond.get("status") == "False"
                    and cond.get("reason") == "Unschedulable"
                    and now - cond.get("last_transition", now) >= threshold_seconds
                ):
                    counts[owner] = counts.get(owner, 0) + 1
                    break
        for key, n in self.unschedulable_replicas.items():
            counts[key] = max(counts.get(key, 0), n)
        return counts

    def append_pod_log(self, namespace: str, name: str, line: str) -> None:
        self._check()
        with self._lock:
            self._pod_logs.setdefault((namespace, name), []).append(line)
            self._log_arrived.notify_all()

    def wait_pod_logs(
        self, namespace: str, name: str, after: int, timeout: float = 1.0
    ) -> list[str]:
        """Block up to ``timeout`` for log lines beyond index ``after``
        (the log-follow seam the proxy passthrough streams from)."""
        self._check()
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                lines = self._pod_logs.get((namespace, name), [])
                if len(lines) > after:
                    return list(lines[after:])
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._log_arrived.wait(remaining)

    def record_proxy_request(self, path: str, headers: dict) -> None:
        """Audit seam: the unified-auth tests assert the member saw the
        impersonated identity, not the plane's own credentials."""
        self.proxy_audit.append(
            {
                "path": path,
                "user": headers.get("Impersonate-User", ""),
                "groups": list(headers.get("Impersonate-Group", []) or []),
            }
        )

    def pod_logs(
        self, namespace: str, name: str, tail: Optional[int] = None
    ) -> list[str]:
        """kubectl logs analogue (karmadactl logs reaches this through the
        clusters/{name}/proxy passthrough)."""
        self._check()
        if self.get("v1/Pod", namespace, name) is None:
            raise KeyError(f"pod {namespace}/{name} not found in {self.name}")
        with self._lock:
            lines = list(self._pod_logs.get((namespace, name), []))
        if tail is None:
            return lines
        return lines[-tail:] if tail > 0 else []

    def pod_exec(self, namespace: str, name: str, command: list[str]) -> dict:
        """kubectl exec/attach analogue. The runtime is pluggable via
        ``exec_handler(pod, command) -> {"stdout", "rc"}``; the default echoes
        (there is no container runtime in-proc)."""
        self._check()
        pod = self.get("v1/Pod", namespace, name)
        if pod is None:
            raise KeyError(f"pod {namespace}/{name} not found in {self.name}")
        if self.exec_handler is not None:
            return self.exec_handler(pod, command)
        if self.exec_stream_handler is not None:
            # collect the streaming runtime's lines (kubectl's exit-code
            # trailer becomes the rc)
            lines, rc = split_exec_trailer(
                list(self.exec_stream_handler(pod, command))
            )
            return {"stdout": "\n".join(lines), "rc": rc}
        return {"stdout": " ".join(command), "rc": 0}

    def pod_exec_stream(self, namespace: str, name: str, command: list[str]):
        """Streaming exec: yields output lines AS THEY APPEAR (the SPDY
        session the reference's karmadactl exec holds open through the
        proxy, pkg/karmadactl/exec/exec.go). Pluggable via
        ``exec_stream_handler(pod, command) -> iterator[str]`` —
        ``SubprocessExecRuntime`` wires a real OS subprocess; the default
        falls back to the one-shot ``pod_exec`` result."""
        self._check()
        pod = self.get("v1/Pod", namespace, name)
        if pod is None:
            raise KeyError(f"pod {namespace}/{name} not found in {self.name}")
        if self.exec_stream_handler is not None:
            yield from self.exec_stream_handler(pod, command)
            return
        res = (
            self.exec_handler(pod, command)
            if self.exec_handler is not None
            else {"stdout": " ".join(command), "rc": 0}
        )
        for line in str(res.get("stdout", "")).splitlines():
            yield line
        rc = int(res.get("rc", 0))
        if rc:
            yield f"{EXEC_EXIT_TRAILER}{rc}"

    # -- member-side simulation helpers (tests / failure injection) --------

    def set_workload_status(
        self, gvk: str, namespace: str, name: str, status: dict
    ) -> None:
        obj = self.get(gvk, namespace, name)
        if obj is not None:
            obj.status = dict(status)
            self.apply(obj)

    def summary_allocatable(self) -> dict[str, int]:
        total: dict[str, int] = {}
        for n in self.nodes:
            for k, v in n.allocatable.items():
                total[k] = total.get(k, 0) + v
        return total

    def summary_allocated(self) -> dict[str, int]:
        total: dict[str, int] = {}
        for n in self.nodes:
            for k, v in n.requested.items():
                total[k] = total.get(k, 0) + v
        return total


#: kubectl's exec failure trailer — the ONE definition every producer
#: (pod_exec, SubprocessExecRuntime) and parser (split_exec_trailer,
#: the remote CLI chain) shares, so the wire format cannot drift
EXEC_EXIT_TRAILER = "command terminated with exit code "


def split_exec_trailer(lines: list[str]) -> tuple[list[str], int]:
    """(output lines without the trailer, exit code) — rc 0 when no
    trailer is present."""
    if lines and lines[-1].startswith(EXEC_EXIT_TRAILER):
        return lines[:-1], int(lines[-1].rsplit(" ", 1)[1])
    return lines, 0


class SubprocessExecRuntime:
    """A real-process exec runtime for the streaming seam: runs the
    command as an OS subprocess and yields stdout lines as they appear —
    the end-to-end analogue of the reference's SPDY exec session
    (pkg/karmadactl/exec/exec.go streams a real container's TTY through
    the proxy; here the "container" is a subprocess, which is as real as
    an in-proc member gets). Wire it per member:
    ``member.exec_stream_handler = SubprocessExecRuntime()``. Intended
    for tests/e2e harnesses — it executes whatever command the caller
    sends, exactly like a kubectl-exec-able container would."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def __call__(self, pod, command):
        import subprocess

        proc = subprocess.Popen(
            list(command), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                yield line.rstrip("\n")
            try:
                rc = proc.wait(timeout=self.timeout)
            except subprocess.TimeoutExpired:
                # stdout closed but the process lingers: kill and report
                # (raising here would leave a chunked response
                # unterminated mid-stream)
                proc.kill()
                proc.wait(timeout=5)
                rc = proc.returncode
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=5)
        if rc:
            yield f"{EXEC_EXIT_TRAILER}{rc}"


class MemberClientRegistry:
    def __init__(self) -> None:
        self._clients: dict[str, MemberCluster] = {}

    def register(self, member: MemberCluster) -> None:
        self._clients[member.name] = member

    def deregister(self, name: str) -> None:
        self._clients.pop(name, None)

    def get(self, name: str) -> Optional[MemberCluster]:
        return self._clients.get(name)

    def names(self) -> Iterable[str]:
        return list(self._clients)


class ObjectWatcher:
    """Versioned create/update/delete of propagated objects into members
    (objectwatcher.go:75-307): records the version it wrote so the status
    collector can tell member drift from control-plane intent, and runs the
    interpreter's Retain hook on update."""

    def __init__(self, members: MemberClientRegistry, interpreter) -> None:
        self.members = members
        self.interpreter = interpreter
        self._versions: dict[tuple[str, str, str, str], int] = {}
        # (cluster, gvk, ns, name) -> (desired manifest pin, applied rv,
        # conflict_resolution): re-applying the SAME manifest object onto an
        # un-drifted member is a no-op, and the execution controller echoes
        # one such apply per Work condition update — the pin (a strong ref,
        # so the id cannot be recycled) collapses that loop. Any member
        # drift changes the observed resource_version and misses the cache.
        self._applied: dict[tuple[str, str, str, str], tuple] = {}

    def create_or_update(
        self, cluster: str, desired: Resource, conflict_resolution: str = "Overwrite"
    ) -> Resource:
        member = self.members.get(cluster)
        if member is None:
            raise UnreachableError(f"no client for cluster {cluster}")
        gvk = f"{desired.api_version}/{desired.kind}"
        vkey = (cluster, gvk, desired.meta.namespace, desired.meta.name)
        observed = member.get(gvk, desired.meta.namespace, desired.meta.name)
        cached = self._applied.get(vkey)
        if (
            cached is not None
            and cached[0] is desired
            and observed is not None
            and observed.meta.resource_version == cached[1]
            and conflict_resolution == cached[2]
        ):
            return observed
        if observed is not None:
            # an unmanaged pre-existing object is a conflict
            # (execution_controller + objectwatcher ConflictResolution)
            if (
                observed.meta.annotations.get(MANAGED_ANNOTATION) != "true"
                and conflict_resolution == "Abort"
            ):
                raise ConflictError(
                    f"{gvk} {desired.meta.namespaced_name} already exists in "
                    f"{cluster} and is not managed"
                )
            # retain() tiers return a fresh object; clone only if a no-hook
            # tier passed `desired` straight through (one copy per apply,
            # not two — the copy chain was the storm's dominant cost)
            to_apply = self.interpreter.retain(desired, observed)
            if to_apply is desired:
                to_apply = clone_resource(desired)
            to_apply.meta.annotations[MANAGED_ANNOTATION] = "true"
            to_apply.meta.resource_version = observed.meta.resource_version
            # member status is owned by the member; never push it down
            to_apply.status = observed.status
        else:
            to_apply = clone_resource(desired)
            to_apply.meta.annotations[MANAGED_ANNOTATION] = "true"
        applied = member.apply(to_apply)
        self._versions[vkey] = applied.meta.resource_version
        self._applied[vkey] = (
            desired, applied.meta.resource_version, conflict_resolution,
        )
        return applied

    def delete(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        member = self.members.get(cluster)
        if member is None:
            return
        member.delete(gvk, namespace, name)
        self._versions.pop((cluster, gvk, namespace, name), None)
        self._applied.pop((cluster, gvk, namespace, name), None)

    def needs_update(self, cluster: str, desired: Resource) -> bool:
        gvk = f"{desired.api_version}/{desired.kind}"
        member = self.members.get(cluster)
        if member is None:
            return True
        observed = member.get(gvk, desired.meta.namespace, desired.meta.name)
        return observed is None
