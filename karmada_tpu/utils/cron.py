"""Minimal 5-field cron matcher (minute hour dom month dow).

Supports: ``*``, lists (``1,2,3``), ranges (``1-5``), steps (``*/15``,
``2-10/2``). Enough for the CronFederatedHPA rules the reference drives with
gocron.
"""

from __future__ import annotations

import time


def _parse_field(field: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
        out.update(range(start, end + 1, step))
    return out


def cron_matches(schedule: str, ts: float) -> bool:
    fields = schedule.split()
    if len(fields) != 5:
        raise ValueError(f"invalid cron schedule {schedule!r}")
    t = time.gmtime(ts)
    minute, hour, dom, month, dow = fields
    checks = (
        (minute, t.tm_min, 0, 59),
        (hour, t.tm_hour, 0, 23),
        (dom, t.tm_mday, 1, 31),
        (month, t.tm_mon, 1, 12),
        (dow, t.tm_wday + 1 if t.tm_wday < 6 else 0, 0, 6),  # 0=Sunday
    )
    for field, value, lo, hi in checks:
        if value not in _parse_field(field, lo, hi):
            return False
    return True
