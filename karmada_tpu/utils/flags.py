"""Per-binary flag surfaces with the reference's flag names.

Ref: cmd/*/app/options/options.go — each reference process exposes its
configuration as pflag surfaces (--plugins, --feature-gates,
--enable-scheduler-estimator, --descheduling-interval, ...). The in-proc
runtime collapses nine binaries into constructor kwargs; these parsers keep
the FLAG CONTRACT: an operator's existing launch args parse here and map
onto the corresponding in-proc configuration, so deployment manifests carry
over. Each ``parse_*`` returns the kwargs dict its component constructor
accepts (plus a ``settings`` section for flags that configure live
behavior such as feature gates, applied by ``apply_common``).

Semantics preserved from the reference:
- ``--plugins`` (scheduler, options.go:163): '*' enables all in-tree
  plugins; '*,-Foo' disables Foo; an explicit list enables only those.
- ``--controllers`` (controller-manager, options.go:165): same grammar
  over controller names.
- ``--feature-gates``: key=bool pairs applied to the feature registry.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Optional, Sequence

# -- environment-flag registry ----------------------------------------------
#
# Every KARMADA_TPU_* environment variable any process in this repo reads
# MUST be declared here (graftlint rule GL003 enforces it) and is rendered
# into the docs/OPERATIONS.md env table by ``render_env_table()``
# (tools/docs_from_bench.py regenerates the table and fails loudly on
# drift). The read sites stay where they are — this registry is the
# DECLARATION surface, the analogue of the reference's pflag definitions
# for knobs that configure processes below the flag parser (backend
# selection, cache policy) or from test/bench drivers.


@dataclass(frozen=True)
class EnvFlag:
    name: str
    default: str
    description: str
    #: read outside the package tree (test/bench drivers): exempt from
    #: graftlint's registered-but-never-read staleness check
    external: bool = False


ENV_FLAGS: dict[str, EnvFlag] = {
    f.name: f
    for f in (
        EnvFlag(
            "KARMADA_TPU_PLATFORM", "",
            "Authoritative jax platform for a spawned component (the "
            "tunnel sitecustomize overrides JAX_PLATFORMS programmatically"
            ", so the env var alone is not enough); set by "
            "localup.spawn_child, applied by utils.platform."
            "apply_child_platform at package import.",
        ),
        EnvFlag(
            "KARMADA_TPU_TRACE_MANIFEST", "<cache dir>/trace_manifest.json",
            "Trace-signature manifest path (scheduler.prewarm."
            "TraceManifest): fleet engines record fresh solve-family "
            "traces into it and AOT prewarm replays it at boot. Empty "
            "string disables recording and restoring.",
        ),
        EnvFlag(
            "KARMADA_TPU_CACHE_MIN_COMPILE_SECS", "1.0",
            "Persistent XLA compile-cache threshold (utils.compilecache): "
            "compiles faster than this are not persisted. Prewarm drops "
            "it to 0 so every warmed trace survives the process.",
        ),
        EnvFlag(
            "KARMADA_TPU_PREWARM_ON_REBUILD", "0",
            "Set to 1/true to replay the trace manifest on a daemon "
            "thread whenever a fleet table is (re)built, compiling the "
            "rebuilt table's upcoming shapes off the serving path.",
        ),
        EnvFlag(
            "KARMADA_TPU_DENSE_BUDGET", str(6 << 30),
            "HBM byte budget for the dense-resident fleet table; tables "
            "whose dense mirror exceeds it fall back to the "
            "entry-resident legacy path. Raise on parts with more HBM.",
        ),
        EnvFlag(
            "KARMADA_TPU_NO_NATIVE", "0",
            "Set to 1 to skip building/loading the ctypes native decode "
            "helpers and always use the numpy fallback path.",
        ),
        EnvFlag(
            "KARMADA_TPU_ESTIMATOR_BATCH", "1",
            "Batched estimator wire protocol (estimator.accurate): set to "
            "0 to force every connection onto the per-profile unary "
            "fallback — the mixed-version escape hatch; servers that "
            "answer UNIMPLEMENTED negotiate the fallback per connection "
            "automatically.",
        ),
        EnvFlag(
            "KARMADA_TPU_BUS_BATCH", "4096",
            "Columnar bus channel (bus.service): max write-through ops "
            "per ApplyBatch RPC and watch events per WatchBatch frame. "
            "0 forces every connection onto the per-object unary "
            "fallback — the mixed-version escape hatch; servers that "
            "answer UNIMPLEMENTED negotiate the fallback per connection "
            "automatically.",
        ),
        EnvFlag(
            "KARMADA_TPU_BUS_FLUSH_MS", "2",
            "Watch-frame coalescing window (ms): after the first queued "
            "event a WatchBatch stream waits this long for more before "
            "flushing the frame — the latency bound of event batching "
            "(count bound: KARMADA_TPU_BUS_BATCH).",
        ),
        EnvFlag(
            "KARMADA_TPU_BUS_TEMPLATE_DELTA", "1",
            "Template-delta Work rendering kill switch (controllers."
            "propagation): 0 renders every Work as a full manifest "
            "clone instead of one content-addressed WorkloadTemplate "
            "plus per-cluster replica patches. Targets with custom "
            "ReviseReplica hooks or matching override rules full-render "
            "either way.",
        ),
        EnvFlag(
            "KARMADA_TPU_ESTIMATOR_PING_SECONDS", "0",
            "Seconds a cluster's snapshot-generation confirmation stays "
            "trusted across EstimatorRegistry.invalidate(); 0 re-pings "
            "the estimator servers (one GetGenerations per server) on "
            "every invalidated pass.",
        ),
        EnvFlag(
            "KARMADA_TPU_ESTIMATOR_FALLBACK_WIDTH", "4",
            "In-flight MaxAvailableReplicas calls per server CHANNEL when "
            "the unary fallback is negotiated: the per-profile queries "
            "pipeline over each channel via grpc futures (bounded, so the "
            "HTTP/2 stream limit is never flooded) instead of blocking "
            "sequentially per cluster. 1 disables pipelining.",
        ),
        EnvFlag(
            "KARMADA_TPU_METRICS_PORT", "",
            "Default /metrics + /healthz (+ /debug/traces) port (or "
            "HOST:PORT — loopback unless a host is given) for the "
            "standalone process entrypoints (solver sidecar, estimator "
            "servers, store bus) when --metrics-port is not given "
            "(utils.metrics.serve_process_metrics). Empty disables the "
            "endpoint; 0 binds an ephemeral port (printed at startup).",
        ),
        EnvFlag(
            "KARMADA_TPU_FAULT_SPEC", "",
            "Deterministic fault-injection spec (utils.faultinject): "
            "semicolon-separated `point=action[,rate=][,count=][,after=]"
            "[,match=][,delay=]` rules armed at process boot by the "
            "entrypoints (localup serve, solver sidecar, estimator "
            "__main__, bus agent). Empty (the default) leaves injection "
            "disarmed — one `is None` check per injection point, zero "
            "overhead. Actions: error/drop/delay/sever/down.",
        ),
        EnvFlag(
            "KARMADA_TPU_FAULT_SEED", "0",
            "Seed for the fault-injection firing decisions: rules with "
            "rate < 1 derive every decision from blake2b(seed, point, "
            "invocation index), so a chaos run replays bit-identically "
            "from (spec, seed) and the fired-event log doubles as the "
            "numpy oracle's replay script.",
        ),
        EnvFlag(
            "KARMADA_TPU_BACKOFF_BASE", "0.05",
            "First decorrelated-jitter retry sleep (seconds) of the "
            "unified channel policy (utils.backoff.default_policy); "
            "every retried RPC on the solver/estimator/bus channels "
            "sleeps within [base, 3x previous], capped.",
        ),
        EnvFlag(
            "KARMADA_TPU_BACKOFF_CAP", "2.0",
            "Cap (seconds) on one decorrelated-jitter retry sleep of the "
            "unified channel policy.",
        ),
        EnvFlag(
            "KARMADA_TPU_BREAKER_RESET_SECONDS", "5.0",
            "Seconds an open circuit breaker waits before admitting the "
            "single half-open probe; the probe's success closes the "
            "breaker without operator action (karmada_tpu_circuit_state "
            "tracks the transitions).",
        ),
        EnvFlag(
            "KARMADA_TPU_MESH_DEVICES", "",
            "Device count of the scheduling-grid mesh "
            "(parallel.mesh.resolve_mesh): engines shard the fleet solve "
            "along the bindings axis over the first N visible devices. "
            "Empty/0/1 = single-device (mesh off); 'auto' = every visible "
            "device. CPU CI dry-runs combine it with "
            "--xla_force_host_platform_device_count=N in XLA_FLAGS. A "
            "value the backend cannot host fails engine construction "
            "loudly instead of silently running single-device.",
        ),
        EnvFlag(
            "KARMADA_TPU_MESH_CLUSTER_AXIS", "1",
            "Cluster-axis extent of the scheduling mesh (the 'c' axis): "
            "1 = pure binding-parallel; >1 additionally shards the "
            "cluster axis (the dispense sorts ride c-axis collectives). "
            "Must divide KARMADA_TPU_MESH_DEVICES.",
        ),
        EnvFlag(
            "KARMADA_TPU_TRACE_CAPACITY", "8192",
            "Span capacity of the wave-trace ring "
            "(utils.tracing.WaveTracer): 1M-tier storms outgrow the "
            "default and spans silently aging off the ring degrade "
            "wave_summary coverage — evictions are counted "
            "(karmada_tpu_trace_spans_dropped_total + the `dropped` "
            "field of /debug/traces) so the operator sees when to raise "
            "it. Read once at tracer construction.",
        ),
        EnvFlag(
            "KARMADA_TPU_TRACE_SLO_SECONDS", "",
            "Arms the slow-wave flight recorder (utils.tracing): a "
            "closing wave whose wall exceeds this many seconds — or "
            "during which a breaker transition, degraded pass or "
            "QuotaExceeded denial fired — persists its stitched trace + "
            "metrics delta + fired-fault log as one JSONL record under "
            "KARMADA_TPU_FLIGHT_DIR. Empty (the default) disarms the "
            "recorder entirely: one env read per wave boundary, nothing "
            "per span.",
        ),
        EnvFlag(
            "KARMADA_TPU_FLIGHT_DIR", "<tmp>/karmada_tpu_flight",
            "Directory the flight recorder appends flight.jsonl under "
            "(ring-capped on disk; `karmadactl-tpu trace analyze` "
            "re-renders a record's attribution offline).",
        ),
        EnvFlag(
            "KARMADA_TPU_FLIGHT_CAP", "64",
            "Maximum flight-recorder records kept in flight.jsonl "
            "(oldest dropped first).",
        ),
        EnvFlag(
            "KARMADA_TPU_HISTORY_CAP", "512",
            "Wave capacity of the per-process telemetry-history ring "
            "(utils.history.WaveHistory): every end_wave() samples one "
            "structured wave row (per-phase self seconds, engine pass "
            "stats, per-channel RPC counts, device bytes) served as "
            "/debug/history and aggregated by `karmadactl-tpu top`. "
            "0 disables sampling entirely; evictions past the cap are "
            "counted, never silent. Read once at history construction.",
        ),
        EnvFlag(
            "KARMADA_TPU_HISTORY_STITCH", "1",
            "Per-wave stitched history sampling: when trace peers are "
            "registered (KARMADA_TPU_TRACE_PEERS), each closing wave's "
            "history row takes its phase attribution from the "
            "cross-process stitched summary — one narrowed "
            "/debug/traces?wave=N fetch per peer per wave close. 0 keeps "
            "sampling local-only (rows still record every local series).",
        ),
        EnvFlag(
            "KARMADA_TPU_EXPLAIN", "",
            "Placement-provenance arm switch (utils.explainstore): set "
            "to 1 and every engine pass runs ONE extra batched explain "
            "dispatch (ops.explain.explain_pass) capturing per-binding x "
            "per-cluster stage-exclusion masks + top-k candidate "
            "summaries into the /debug/explain ring. Unset/0 — the "
            "default — costs one `is None` check per pass.",
        ),
        EnvFlag(
            "KARMADA_TPU_EXPLAIN_CAP", "8",
            "Explain-capture ring cap in WAVES (utils.explainstore."
            "ExplainStore): older waves' captures evict (counted, never "
            "silent) once more than this many waves are retained; 0 "
            "disables the store even when armed.",
        ),
        EnvFlag(
            "KARMADA_TPU_TRACE_PEERS", "",
            "Comma-separated `name=host:port` metrics endpoints of the "
            "plane's peer processes (solver sidecar, estimator servers, "
            "store bus) for the cross-process trace stitcher; parsed at "
            "process boot by utils.tracing.register_peers_from_env. "
            "`trace dump --stitch`, wave_summary(stitched=True) and the "
            "flight recorder pull /debug/traces from every entry.",
        ),
        EnvFlag(
            "KARMADA_TPU_QUOTA_ENFORCEMENT", "1",
            "FederatedResourceQuota admission in the scheduler "
            "(controllers.scheduler_controller): set to 0 to disable the "
            "quota plane entirely — no QuotaSnapshot is built and the "
            "engine's admission hook stays a single `is None` check. "
            "Member-side static-assignment Works still sync either way.",
        ),
        EnvFlag(
            "KARMADA_TPU_DRYRUN_REAL_DEVICES", "0",
            "Multichip dryrun escape hatch (__graft_entry__): set to 1 to "
            "run on the default backend's real devices instead of forcing "
            "a virtual CPU mesh.",
            external=True,
        ),
        EnvFlag(
            "KARMADA_TPU_TPU_SOLVER_E2E", "0",
            "Set to 1 to enable the live-TPU solver-sidecar e2e "
            "(tests/test_tpu_solver_localup.py); run alone — the "
            "single-client tunnel grant can linger after an unclean kill.",
            external=True,
        ),
        EnvFlag(
            "KARMADA_TPU_SOLVER_PLATFORM", "axon,cpu",
            "Platform handed to the solver sidecar by the TPU e2e — the "
            "one component allowed to dial the accelerator tunnel.",
            external=True,
        ),
        EnvFlag(
            "KARMADA_TPU_TPU_E2E_RECORD", "",
            "Path the TPU solver e2e writes its timing record to "
            "(TPU_E2E_r*.json); empty disables recording.",
            external=True,
        ),
        EnvFlag(
            "KARMADA_TPU_PREEMPTION", "1",
            "Scarcity-plane kill switch (scheduler controller + engine): "
            "0 disarms the batched preemption kernel — high-priority "
            "waves that cannot fit stay unschedulable instead of "
            "selecting victims. Disarmed costs one `is None` check per "
            "engine pass (the quota/fault-injection pattern).",
        ),
        EnvFlag(
            "KARMADA_TPU_DELTA_SOLVE", "1",
            "Incremental (dirty-row) solve kill switch (scheduler engine "
            "+ fleet table): 0 forces every pass back onto the full "
            "repack/resolve path — churn waves re-dispatch the whole "
            "batch instead of packing only dirty rows against the "
            "resident mesh state. Disarmed costs one env read per pass; "
            "eligibility is additionally gated on the graftlint "
            "delta-safety certification (tools/graftlint/dep.py) at "
            "arm time.",
        ),
        EnvFlag(
            "KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", "64",
            "Continuous-descheduler disruption budget: the maximum "
            "bindings one drift-rebalance round may stamp "
            "RescheduleTriggeredAt on (highest-drift first; FIFO ties). "
            "0 disables the tier entirely. Published per round as "
            "karmada_tpu_desched_disruption_budget.",
        ),
        EnvFlag(
            "KARMADA_TPU_ADMISSION_TIMEOUT", "5",
            "Per-request read deadline (seconds) for the external "
            "admission webhook channel (webhook.server.RemoteAdmission). "
            "Each request gets ONE bounded retry on an unreachable/"
            "timed-out webhook before admission fails — the webhook-boot "
            "window under full-machine load is the case this absorbs; "
            "raise it on oversubscribed CI rigs.",
        ),
    )
}


def render_env_table() -> str:
    """The docs/OPERATIONS.md environment-variable table, generated from
    ``ENV_FLAGS`` so prose can never drift from the declaration surface
    (tools/docs_from_bench.py writes it between the envflags markers and
    fails loudly when the committed table differs)."""
    lines = [
        "| variable | default | what it does |",
        "|---|---|---|",
    ]
    for name in sorted(ENV_FLAGS):
        f = ENV_FLAGS[name]
        default = f.default if f.default else '""'
        lines.append(f"| `{name}` | `{default}` | {f.description} |")
    return "\n".join(lines)


#: the in-tree scheduler plugin set (framework/plugins/registry.go:30-39)
IN_TREE_PLUGINS = (
    "APIEnablement",
    "ClusterAffinity",
    "ClusterEviction",
    "ClusterLocality",
    "SpreadConstraint",
    "TaintToleration",
)

#: controllers the manager can toggle (controller-manager options.go:165)
CONTROLLERS = (
    "binding", "cluster", "clusterStatus", "execution", "workStatus",
    "namespace", "gracefulEviction", "applicationFailover", "remedy",
    "workloadRebalancer", "federatedResourceQuota", "unifiedAuth",
    "serviceExport", "multiclusterservice", "federatedHorizontalPodAutoscaler",
    "cronFederatedHorizontalPodAutoscaler", "dependenciesDistributor",
)


def parse_star_list(values: Sequence[str], universe: Sequence[str], what: str):
    """'*' / '*,-Foo' / explicit-list grammar shared by --plugins and
    --controllers. Returns (enabled set, disabled set)."""
    items = [v.strip() for v in values for v in v.split(",") if v.strip()]
    if not items:
        return set(universe), set()
    has_star = "*" in items
    disabled = {v[1:] for v in items if v.startswith("-")}
    explicit = {v for v in items if v != "*" and not v.startswith("-")}
    unknown = (disabled | explicit) - set(universe)
    if unknown:
        raise ValueError(f"unknown {what}: {sorted(unknown)}")
    if has_star:
        return set(universe) - disabled, disabled
    if disabled and not explicit:
        return set(universe) - disabled, disabled
    return explicit, set(universe) - explicit


def _feature_gates(value: str) -> dict[str, bool]:
    out: dict[str, bool] = {}
    for pair in value.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, _, raw = pair.partition("=")
        if raw.lower() not in ("true", "false"):
            raise ValueError(f"feature gate {pair!r} must be key=true|false")
        out[key] = raw.lower() == "true"
    return out


def _common(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--kubeconfig", default="")
    parser.add_argument("--master", default="")
    parser.add_argument("--metrics-bind-address", default=":8080")
    parser.add_argument("--health-probe-bind-address", default=":10351")
    parser.add_argument("--feature-gates", type=_feature_gates, default={})
    parser.add_argument("--leader-elect", default="true")


def apply_common(ns: argparse.Namespace) -> None:
    """Apply process-wide settings (feature gates) from parsed flags."""
    from .features import feature_gate

    for gate, value in (ns.feature_gates or {}).items():
        feature_gate.set(gate, value)


# -- karmada-scheduler (cmd/scheduler/app/options/options.go) ---------------


def scheduler_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="karmada-scheduler", add_help=False)
    _common(p)
    p.add_argument("--scheduler-name", default="default-scheduler")
    p.add_argument("--plugins", action="append", default=[])
    p.add_argument("--enable-scheduler-estimator", default="false")
    p.add_argument("--disable-scheduler-estimator-in-pull-mode", default="false")
    p.add_argument("--scheduler-estimator-timeout", default="3s")
    p.add_argument("--scheduler-estimator-port", type=int, default=10352)
    p.add_argument("--enable-empty-workload-propagation", default="false")
    return p


def parse_scheduler_flags(argv: Sequence[str]) -> dict:
    ns = scheduler_parser().parse_args(argv)
    apply_common(ns)
    enabled, disabled = parse_star_list(
        ns.plugins or ["*"], IN_TREE_PLUGINS, "plugins"
    )
    return {
        "scheduler_name": ns.scheduler_name,
        "disabled_plugins": tuple(sorted(disabled)),
        "enable_scheduler_estimator": ns.enable_scheduler_estimator == "true",
        "scheduler_estimator_timeout_seconds": _duration(
            ns.scheduler_estimator_timeout
        ),
        "scheduler_estimator_port": ns.scheduler_estimator_port,
    }


# -- karmada-controller-manager ---------------------------------------------


def controller_manager_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="karmada-controller-manager", add_help=False
    )
    _common(p)
    p.add_argument("--controllers", action="append", default=[])
    p.add_argument("--cluster-monitor-period", default="5m")
    p.add_argument("--cluster-monitor-grace-period", default="40s")
    p.add_argument("--failover-eviction-timeout", default="5m")
    p.add_argument("--graceful-eviction-timeout", default="10m")
    p.add_argument("--concurrent-work-syncs", type=int, default=5)
    return p


def parse_controller_manager_flags(argv: Sequence[str]) -> dict:
    ns = controller_manager_parser().parse_args(argv)
    apply_common(ns)
    enabled, disabled = parse_star_list(
        ns.controllers or ["*"], CONTROLLERS, "controllers"
    )
    return {
        "enabled_controllers": tuple(sorted(enabled)),
        "disabled_controllers": tuple(sorted(disabled)),
        "eviction_timeout": _duration(ns.failover_eviction_timeout),
        "cluster_monitor_grace_period": _duration(
            ns.cluster_monitor_grace_period
        ),
    }


# -- karmada-descheduler -----------------------------------------------------


def descheduler_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="karmada-descheduler", add_help=False)
    _common(p)
    p.add_argument("--descheduling-interval", default="2m")
    p.add_argument("--unschedulable-threshold", default="5m")
    return p


def parse_descheduler_flags(argv: Sequence[str]) -> dict:
    ns = descheduler_parser().parse_args(argv)
    apply_common(ns)
    return {
        "descheduling_interval": _duration(ns.descheduling_interval),
        "unschedulable_threshold": _duration(ns.unschedulable_threshold),
    }


# -- karmada-agent (cmd/agent/app/options/options.go) ------------------------


def agent_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="karmada-agent", add_help=False)
    _common(p)
    p.add_argument("--cluster-name", required=True)
    p.add_argument("--cluster-namespace", default="karmada-cluster")
    p.add_argument("--cluster-status-update-frequency", default="10s")
    p.add_argument("--report-secrets", action="append",
                   default=["KubeCredentials", "KubeImpersonator"])
    return p


def parse_agent_flags(argv: Sequence[str]) -> dict:
    ns = agent_parser().parse_args(argv)
    apply_common(ns)
    return {
        "cluster_name": ns.cluster_name,
        "cluster_namespace": ns.cluster_namespace,
        "status_update_frequency": _duration(
            ns.cluster_status_update_frequency
        ),
    }


# -- helpers -----------------------------------------------------------------


_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}


def _duration(value: str) -> float:
    """Go duration strings ('3s', '5m', '1h30m', '500ms') -> seconds."""
    value = value.strip()
    total = 0.0
    num = ""
    i = 0
    while i < len(value):
        ch = value[i]
        if ch.isdigit() or ch == ".":
            num += ch
            i += 1
            continue
        unit = ch
        if value[i:i + 2] == "ms":
            unit = "ms"
        if unit not in _UNITS or not num:
            raise ValueError(f"unparseable duration {value!r}")
        total += float(num) * _UNITS[unit]
        num = ""
        i += len(unit)
    if num:  # bare number = seconds
        total += float(num)
    return total
