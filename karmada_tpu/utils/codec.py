"""Typed dataclass <-> JSON-able codec for API objects on the wire.

The control plane's API types are plain dataclasses (api/*.py); the wire
surfaces (solver sidecar, networked watch bus, checkpoints) need a stable,
language-neutral encoding. ``to_jsonable`` flattens dataclasses into plain
dict/list/scalar trees; ``from_jsonable`` rebuilds them from the declared
field types (handles Optional, list[...], dict[...], tuple[...], and nested
dataclasses). Unknown keys are ignored on decode (forward compatibility,
the CRD contract); missing keys fall back to field defaults.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Optional, Union, get_args, get_origin, get_type_hints

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_jsonable(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    return obj


def _hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def _decode(value: Any, tp: Any) -> Any:
    if value is None:
        return None
    origin = get_origin(tp)
    if origin is Union:  # Optional[X] and unions: first matching arm wins
        for arm in get_args(tp):
            if arm is type(None):
                continue
            try:
                return _decode(value, arm)
            except (TypeError, ValueError, KeyError):
                continue
        return value
    if origin in (list, tuple):
        args = get_args(tp)
        elem = args[0] if args else Any
        seq = [_decode(v, elem) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {k: _decode(v, vt) for k, v in value.items()}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        return from_jsonable(tp, value)
    return value


def from_jsonable(cls: type, data: Optional[dict]) -> Any:
    """Rebuild dataclass ``cls`` from a jsonable dict (None passes through)."""
    if data is None:
        return None
    hints = _hints(cls)
    kwargs = {}
    names = {f.name for f in dataclasses.fields(cls)}
    for key, value in data.items():
        if key not in names:
            continue  # forward compatibility: unknown fields are dropped
        kwargs[key] = _decode(value, hints.get(key, Any))
    return cls(**kwargs)
