"""Small shared networking helpers."""

from __future__ import annotations


def parse_hostport(spec: str, default_host: str = "127.0.0.1") -> tuple[str, int]:
    """``HOST:PORT`` / ``:PORT`` / ``PORT-less`` spec -> (host, port);
    missing pieces default (port 0 = ephemeral bind)."""
    host, _, port = spec.partition(":")
    return (host or default_host, int(port or 0))
