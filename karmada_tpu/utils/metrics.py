"""Observability: counters/histograms with a Prometheus-style registry.

Ref: pkg/scheduler/metrics/metrics.go:61-115 (schedule_attempts_total,
e2e_scheduling_duration_seconds, scheduling_algorithm_duration_seconds
{schedule_step=Filter|Score|Select|AssignReplicas}, per-plugin timers) and
pkg/metrics (controller metrics). Text exposition follows the Prometheus
format so a scraper can consume ``render()`` directly.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Iterable, Optional

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] += amount

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def render(self) -> Iterable[str]:
        yield f"# TYPE {self.name} counter"
        for key, v in sorted(self._values.items()):
            label_s = ",".join(f'{k}="{val}"' for k, val in key)
            yield f"{self.name}{{{label_s}}} {v}" if label_s else f"{self.name} {v}"


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    @contextmanager
    def time(self, **labels):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - start, **labels)

    def summary(self, **labels) -> Optional[dict]:
        key = _label_key(labels)
        if key not in self._totals:
            return None
        return {
            "count": self._totals[key],
            "sum": self._sums[key],
            "avg": self._sums[key] / max(self._totals[key], 1),
        }

    def render(self) -> Iterable[str]:
        yield f"# TYPE {self.name} histogram"
        for key in sorted(self._totals):
            label_s = ",".join(f'{k}="{v}"' for k, v in key)
            prefix = f"{self.name}_bucket{{{label_s}" if label_s else f"{self.name}_bucket{{"
            counts = self._counts[key]  # already cumulative (observe adds to
            # every bucket whose bound covers the value)
            for i, bound in enumerate(self.buckets):
                sep = "," if label_s else ""
                yield f'{prefix}{sep}le="{bound}"}} {counts[i]}'
            sep = "," if label_s else ""
            yield f'{prefix}{sep}le="+Inf"}} {self._totals[key]}'
            base = f"{self.name}_sum{{{label_s}}}" if label_s else f"{self.name}_sum"
            yield f"{base} {self._sums[key]}"
            base = f"{self.name}_count{{{label_s}}}" if label_s else f"{self.name}_count"
            yield f"{base} {self._totals[key]}"


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []

    def counter(self, name: str, help_: str = "") -> Counter:
        c = Counter(name, help_)
        self._metrics.append(c)
        return c

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        h = Histogram(name, help_, buckets)
        self._metrics.append(h)
        return h

    def render(self) -> str:
        lines: list[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# global registry + the scheduler metric set (metrics.go:61-115)
registry = Registry()

schedule_attempts = registry.counter(
    "karmada_scheduler_schedule_attempts_total",
    "scheduling attempts by result and type",
)
e2e_scheduling_duration = registry.histogram(
    "karmada_scheduler_e2e_scheduling_duration_seconds",
    "end-to-end schedule latency",
)
scheduling_algorithm_duration = registry.histogram(
    "karmada_scheduler_scheduling_algorithm_duration_seconds",
    "per-step scheduling latency",
)
queue_incoming_bindings = registry.counter(
    "karmada_scheduler_queue_incoming_bindings_total",
    "queue pressure by event",
)


class MetricsServer:
    """Prometheus text exposition over HTTP: every reference binary serves
    /metrics on --metrics-bind-address (cmd/scheduler/app/options/
    options.go:148); this is that endpoint for the TPU-native processes.
    Also answers /healthz (the readiness probe the reference wires via
    healthz.InstallHandler)."""

    def __init__(
        self,
        reg: Registry | None = None,
        address: tuple[str, int] = ("127.0.0.1", 0),
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        self.registry = reg or registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = outer.registry.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.port = self._httpd.server_address[1]
        self._threading = threading
        self._thread = None

    def start(self) -> int:
        self._thread = self._threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
