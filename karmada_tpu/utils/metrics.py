"""Observability: counters/gauges/histograms with a Prometheus registry.

Ref: pkg/scheduler/metrics/metrics.go:61-115 (schedule_attempts_total,
e2e_scheduling_duration_seconds, scheduling_algorithm_duration_seconds
{schedule_step=Filter|Score|Select|AssignReplicas}, per-plugin timers) and
pkg/metrics (controller metrics). Text exposition follows the Prometheus
format so a scraper can consume ``render()`` directly: ``# HELP`` before
``# TYPE``, cumulative histogram buckets, label values escaped per the
text-format rules.

Every long-running process (plane, solver sidecar, estimator servers, the
store bus) serves this registry at ``/metrics`` (+ ``/healthz`` and the
``/debug/traces`` wave-trace dump) through ``MetricsServer``; the shared
``--metrics-port`` flag semantics live in ``serve_process_metrics``.

Thread-safety contract: ``inc()``/``set()``/``observe()`` mutate under the
per-metric lock, and every READ path (``value()``, ``summary()``, both
``render()`` paths) snapshots the sample dicts under that same lock before
iterating — a scrape racing a storm of observes must never see a bucket
list mid-update or die on a dict that grew mid-iteration. (This is the
GL004 invariant stated in code rather than carried by a single-writer
pragma: there IS no single writer here, so the lock is load-bearing on
both sides.)
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Iterable, Optional

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)

#: end-to-end bucket set for whole-wave / settle-pass latencies: a 1M-tier
#: settle pass legitimately runs 14-15 s and a cold wave minutes — with the
#: default buckets every such observation landed in +Inf and the histogram
#: said nothing (ISSUE 6 satellite). Scrapers still get sub-second
#: resolution at the fast end.
E2E_BUCKETS = (
    0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 15.0, 30.0, 60.0,
    120.0, 300.0,
)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline must be escaped inside the quoted label value."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(key: tuple) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)


def _help_line(name: str, help_: str) -> str:
    # HELP text escaping: backslash and newline (the text format's rules
    # for HELP differ from label values — no quote escaping)
    escaped = help_.replace("\\", "\\\\").replace("\n", "\\n")
    return f"# HELP {name} {escaped}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] += amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> dict[tuple, float]:
        """Label-set -> value snapshot (bench records enumerate these)."""
        with self._lock:
            return dict(self._values)

    def snapshot(self) -> dict[str, float]:
        """JSON-stable samples (label string -> value) — the flight
        recorder's metrics-delta surface."""
        return {_label_str(k): v for k, v in self.samples().items()}

    def render(self) -> Iterable[str]:
        if self.help:
            yield _help_line(self.name, self.help)
        yield f"# TYPE {self.name} counter"
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            label_s = _label_str(key)
            yield f"{self.name}{{{label_s}}} {v}" if label_s else f"{self.name} {v}"


class Gauge:
    """A settable sample (queue depth, subscriber count). Same lock
    contract as Counter: set/add mutate and every read snapshots under the
    lock."""

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            key = _label_key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> dict[tuple, float]:
        """Label-set -> value snapshot (the Counter contract; the
        history sampler and bench records enumerate these)."""
        with self._lock:
            return dict(self._values)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {_label_str(k): v for k, v in self._values.items()}

    def remove_matching(self, **labels) -> None:
        """Drop every sample whose label set CONTAINS these pairs — the
        cleanup hook for gauges keyed by a deleted object (e.g. a removed
        FederatedResourceQuota's per-resource limit/used samples)."""
        match = set(labels.items())
        with self._lock:
            for key in [k for k in self._values if match <= set(k)]:
                del self._values[key]

    def render(self) -> Iterable[str]:
        if self.help:
            yield _help_line(self.name, self.help)
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            label_s = _label_str(key)
            yield f"{self.name}{{{label_s}}} {v}" if label_s else f"{self.name} {v}"


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    @contextmanager
    def time(self, **labels):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - start, **labels)

    def snapshot(self) -> dict[str, dict]:
        """{label string: {count, sum}} — buckets are derivable and the
        flight recorder's delta only needs the two scalars."""
        with self._lock:
            return {
                _label_str(k): {
                    "count": self._totals[k], "sum": self._sums[k]
                }
                for k in self._totals
            }

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Bucket-interpolated quantile of one label set (ISSUE 12
        satellite): the Prometheus ``histogram_quantile`` estimate over
        the cumulative bucket counts, so CLIs stop eyeballing raw
        buckets. None when the label set has no observations."""
        key = _label_key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            if not total:
                return None
            counts = list(self._counts[key])
        return bucket_quantile(q, self.buckets, counts, total)

    def summary(self, **labels) -> Optional[dict]:
        key = _label_key(labels)
        with self._lock:
            if key not in self._totals:
                return None
            total = self._totals[key]
            s = self._sums[key]
        return {"count": total, "sum": s, "avg": s / max(total, 1)}

    def render(self) -> Iterable[str]:
        if self.help:
            yield _help_line(self.name, self.help)
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            # consistent snapshot of all three sample dicts: counts lists
            # are copied so a concurrent observe cannot mutate a row
            # mid-render (the totals/sums pair for a key stays coherent
            # because both are written under this same lock)
            keys = sorted(self._totals)
            counts_snap = {k: list(self._counts[k]) for k in keys}
            sums_snap = {k: self._sums[k] for k in keys}
            totals_snap = {k: self._totals[k] for k in keys}
        for key in keys:
            label_s = _label_str(key)
            prefix = f"{self.name}_bucket{{{label_s}" if label_s else f"{self.name}_bucket{{"
            counts = counts_snap[key]  # already cumulative (observe adds to
            # every bucket whose bound covers the value)
            sep = "," if label_s else ""
            for i, bound in enumerate(self.buckets):
                yield f'{prefix}{sep}le="{bound}"}} {counts[i]}'
            yield f'{prefix}{sep}le="+Inf"}} {totals_snap[key]}'
            base = f"{self.name}_sum{{{label_s}}}" if label_s else f"{self.name}_sum"
            yield f"{base} {sums_snap[key]}"
            base = f"{self.name}_count{{{label_s}}}" if label_s else f"{self.name}_count"
            yield f"{base} {totals_snap[key]}"


def bucket_quantile(
    q: float, bounds, cumulative_counts, total: int
) -> Optional[float]:
    """THE bucket-interpolation core (Prometheus ``histogram_quantile``
    semantics): ``bounds`` are the finite upper bounds, ``cumulative_
    counts`` the cumulative observation counts per bound, ``total`` the
    +Inf count. Linear interpolation inside the landing bucket (the
    first bucket interpolates from 0); a rank landing in +Inf answers
    the highest finite bound — the estimate cannot exceed what the
    buckets resolve. Shared by ``Histogram.quantile`` and the CLI
    exposition parsers (karmadactl-tpu quota status / top), so the two
    sides can never drift."""
    if total <= 0 or not bounds:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q={q} outside [0, 1]")
    rank = q * total
    prev_bound = 0.0
    prev_count = 0
    for bound, count in zip(bounds, cumulative_counts):
        if count >= rank:
            in_bucket = count - prev_count
            if in_bucket <= 0:
                return float(bound)
            frac = (rank - prev_count) / in_bucket
            return float(prev_bound + (bound - prev_bound) * frac)
        prev_bound, prev_count = float(bound), count
    return float(bounds[-1])


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []

    def counter(self, name: str, help_: str = "") -> Counter:
        c = Counter(name, help_)
        self._metrics.append(c)
        return c

    def gauge(self, name: str, help_: str = "") -> Gauge:
        g = Gauge(name, help_)
        self._metrics.append(g)
        return g

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        h = Histogram(name, help_, buckets)
        self._metrics.append(h)
        return h

    def snapshot(self) -> dict[str, dict]:
        """family name -> {label string: value | {count, sum}} across the
        whole registry — the flight recorder snapshots it at wave open
        and deltas it at wave close (utils.tracing.maybe_flight_record)."""
        return {m.name: m.snapshot() for m in self._metrics}

    def families(self) -> list:
        """(name, type, help) per registered metric — the docs metric
        table and its drift guard (tools/docs_from_bench.py) read this."""
        return [
            (m.name, type(m).__name__.lower(), m.help) for m in self._metrics
        ]

    def render(self) -> str:
        lines: list[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# global registry + the scheduler metric set (metrics.go:61-115)
registry = Registry()

schedule_attempts = registry.counter(
    "karmada_scheduler_schedule_attempts_total",
    "scheduling attempts by result and type",
)
e2e_scheduling_duration = registry.histogram(
    "karmada_scheduler_e2e_scheduling_duration_seconds",
    "end-to-end schedule latency",
    buckets=E2E_BUCKETS,
)
scheduling_algorithm_duration = registry.histogram(
    "karmada_scheduler_scheduling_algorithm_duration_seconds",
    "per-step scheduling latency",
)
queue_incoming_bindings = registry.counter(
    "karmada_scheduler_queue_incoming_bindings_total",
    "queue pressure by event",
)

# -- plane-wide families (ISSUE 6) ------------------------------------------
#
# Defined centrally so EVERY process that imports utils.metrics exposes the
# full family set on /metrics (a family with no samples still renders its
# HELP/TYPE header — scrapers and the docs drift guard see the complete
# catalogue regardless of which subsystem ran yet).

scheduler_pass_seconds = registry.histogram(
    "karmada_tpu_scheduler_pass_seconds",
    "one engine pass over a queued binding batch (batched drain of the "
    "scheduler worker)",
    buckets=E2E_BUCKETS,
)
settle_seconds = registry.histogram(
    "karmada_tpu_settle_seconds",
    "one run_until_settled drain of the whole controller fleet (a storm "
    "wave is one settle)",
    buckets=E2E_BUCKETS,
)
kernel_compiles = registry.counter(
    "karmada_tpu_kernel_compiles_total",
    "fresh XLA trace signatures dispatched by the fleet engine, by kernel "
    "family (each is one compile, on or off the serving path)",
)
kernel_prewarmed = registry.counter(
    "karmada_tpu_kernel_prewarmed_total",
    "trace-manifest records AOT-compiled by prewarm (off the serving "
    "path), by outcome",
)
kernel_phase_seconds = registry.histogram(
    "karmada_tpu_kernel_phase_seconds",
    "fleet kernel hot-path wall time split by phase: host (pack/upsert/"
    "sync/decode), dispatch, device (fenced execute, compile included "
    "when the pass minted a fresh trace), fetch",
)
estimator_rpcs = registry.counter(
    "karmada_tpu_estimator_rpcs_total",
    "scheduler-side estimator wire traffic by kind (batch matrix RPCs, "
    "per-profile unary fallback calls, generation pings)",
)
estimator_delta_requeries = registry.counter(
    "karmada_tpu_estimator_delta_requery_total",
    "clusters whose availability was re-fetched after a generation "
    "movement (the delta half of the generation-gated refresh)",
)
estimator_refresh_seconds = registry.histogram(
    "karmada_tpu_estimator_refresh_seconds",
    "wall time of one registry refresh (pings + grouped fan-out)",
)
estimator_server_requests = registry.counter(
    "karmada_tpu_estimator_server_requests_total",
    "estimator-server RPCs served, by method",
)
solver_requests = registry.counter(
    "karmada_tpu_solver_requests_total",
    "solver-sidecar RPCs served, by method",
)
bus_events = registry.counter(
    "karmada_tpu_bus_events_total",
    "store-bus watch events fanned out to subscribers (dropped = a slow "
    "subscriber's stream was closed for re-list)",
)
bus_subscribers = registry.gauge(
    "karmada_tpu_bus_subscribers",
    "live store-bus watch subscribers",
)
bus_queue_depth = registry.gauge(
    "karmada_tpu_bus_queue_depth",
    "deepest subscriber queue at the last fan-out (backpressure signal)",
)
bus_event_age_seconds = registry.histogram(
    "karmada_tpu_bus_event_age_seconds",
    "time a watch event waited in a subscriber queue before the stream "
    "picked it up (recorded PER EVENT even under frame coalescing, so "
    "batching cannot fake a low queue age)",
)
bus_batch_size = registry.histogram(
    "karmada_tpu_bus_batch_size",
    "items per batched bus message: ops per ApplyBatch RPC served and "
    "events per WatchBatch frame flushed (count histogram — a value of "
    "1 means the channel is effectively unary)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
)
works_rendered = registry.counter(
    "karmada_tpu_controller_works_rendered_total",
    "Work objects created or updated by the binding controller (the "
    "work-render throughput ROADMAP item 3 optimizes)",
)
worker_reconciles = registry.counter(
    "karmada_tpu_worker_reconciles_total",
    "reconciles drained, by worker queue",
)
worker_queue_depth = registry.gauge(
    "karmada_tpu_worker_queue_depth",
    "keys still queued per worker after its last drain",
)
circuit_state = registry.gauge(
    "karmada_tpu_circuit_state",
    "per-channel circuit-breaker state (0 closed, 1 open, 2 half-open) — "
    "the unified resilience policy of utils.backoff; an open estimator/"
    "solver/bus breaker marks every pass it shadows as degraded",
)
channel_retries = registry.counter(
    "karmada_tpu_channel_retries_total",
    "RPC attempts retried under the unified backoff policy, by channel "
    "(each is one decorrelated-jitter sleep inside one deadline budget)",
)
degraded_passes = registry.counter(
    "karmada_tpu_degraded_passes_total",
    "passes served on a channel's degraded path, by channel: solver = "
    "in-proc fallback solve, estimator = at least one registered cluster "
    "answered UnauthenticReplica (such a pass never arms batch-identity "
    "replay)",
)
unschedulable_total = registry.counter(
    "karmada_tpu_unschedulable_total",
    "bindings transitioning to Scheduled=False, by REASONS-taxonomy "
    "code (QuotaExceeded, NoClusterFit, InsufficientReplicas, ...) — "
    "one increment per (binding, reason, generation) transition; a "
    "parked binding re-enqueued within one generation never "
    "double-counts (utils.reasons.TransitionDedup)",
)
preemptions_total = registry.counter(
    "karmada_tpu_preemptions_total",
    "bindings displaced by the scarcity plane, by REASONS-taxonomy code "
    "(PreemptedByHigherPriority = victim of the batched preemption "
    "kernel, RebalanceTriggered = continuous-descheduler drift "
    "re-placement) — one increment per (binding, reason, generation) "
    "transition via utils.reasons.TransitionDedup, so a twice-displaced "
    "binding re-enqueued within one generation never double-counts",
)
desched_disruption_budget = registry.gauge(
    "karmada_tpu_desched_disruption_budget",
    "the continuous descheduler's per-wave disruption budget "
    "(KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION): the maximum bindings one "
    "drift-rebalance round may stamp RescheduleTriggeredAt on; 0 = tier "
    "disabled (published once per rebalance round beside the per-round "
    "used level)",
)
desched_disruption_used = registry.gauge(
    "karmada_tpu_desched_disruption_used",
    "bindings the LAST drift-rebalance round actually re-placed (always "
    "<= the published budget — the bench asserts the bound exactly)",
)
quota_denied = registry.counter(
    "karmada_tpu_quota_denied_total",
    "bindings newly denied admission by FederatedResourceQuota "
    "enforcement, by namespace (incremented when the QuotaExceeded "
    "condition lands on the binding; a denied binding retries on the "
    "next quota generation, not every pass)",
)
quota_limit = registry.gauge(
    "karmada_tpu_quota_limit",
    "FederatedResourceQuota spec.overall limit by namespace and resource "
    "(canonical integer units; set by the FRQ status controller)",
)
quota_used = registry.gauge(
    "karmada_tpu_quota_used",
    "FederatedResourceQuota status.overall_used by namespace and "
    "resource, recomputed live from bound ResourceBindings",
)
trace_spans_dropped = registry.counter(
    "karmada_tpu_trace_spans_dropped_total",
    "wave-trace spans evicted off the tracer ring (one inc per "
    "overwrite) — nonzero means wave_summary coverage is undercounting; "
    "raise KARMADA_TPU_TRACE_CAPACITY for 1M-tier storms",
)
device_bytes = registry.gauge(
    "karmada_tpu_device_bytes",
    "resident device bytes by ledger kind and table bucket (exact "
    "nbytes of the arrays the fleet table / engine hold: slot tables, "
    "packed grid, donated residents, quota cap tensors) — the platform "
    "label says WHOSE memory (cpu = forced-host bytes, never HBM); "
    "published once per engine pass",
)
kernel_memory_bytes = registry.gauge(
    "karmada_tpu_kernel_memory_bytes",
    "per-compiled-kernel XLA memory_analysis footprint by kind (temp = "
    "transient scratch, output, argument) — recorded when prewarm "
    "AOT-compiles a manifest trace, so an operator can budget HBM "
    "before putting a resident grid on real devices",
)


def render_families_table() -> str:
    """The docs/OPERATIONS.md metric-families table, generated from the
    live registry so prose can never drift from the exposition
    (tools/docs_from_bench.py writes it between the metricfamilies
    markers and fails loudly on drift — the env-table pattern)."""
    lines = [
        "| family | type | what it measures |",
        "|---|---|---|",
    ]
    for name, type_, help_ in sorted(registry.families()):
        lines.append(f"| `{name}` | {type_} | {help_} |")
    return "\n".join(lines)


class MetricsServer:
    """Prometheus text exposition over HTTP: every reference binary serves
    /metrics on --metrics-bind-address (cmd/scheduler/app/options/
    options.go:148); this is that endpoint for the TPU-native processes.
    Also answers /healthz (the readiness probe the reference wires via
    healthz.InstallHandler), /debug/traces (the wave-trace ring as
    JSON — utils.tracing.tracer.dump()), /debug/history (the per-wave
    telemetry ring + sliding-window digests — utils.history) and
    /debug/explain (the placement-provenance capture ring —
    utils.explainstore)."""

    def __init__(
        self,
        reg: Registry | None = None,
        address: tuple[str, int] = ("127.0.0.1", 0),
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        self.registry = reg or registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = outer.registry.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                elif self.path.startswith("/debug/history"):
                    import json
                    from urllib.parse import parse_qs, urlsplit

                    from .history import history_for
                    from .tracing import tracer

                    # query contract: ?window=N paginates to the last N
                    # rows (digests cover the same window), ?wave=N
                    # narrows to one wave, ?digests=0 drops the digest
                    # block. Malformed values answer 400 — `top` must
                    # never mistake a mis-filtered full dump for a page
                    qs = parse_qs(urlsplit(self.path).query)
                    try:
                        raw_window = (qs.get("window") or [None])[0]
                        window = (
                            int(raw_window) if raw_window is not None
                            else None
                        )
                        raw_wave = (qs.get("wave") or [None])[0]
                        wave = (
                            int(raw_wave) if raw_wave is not None else None
                        )
                        with_digests = (qs.get("digests") or ["1"])[0] in (
                            "1", "true", "yes",
                        )
                    except ValueError:
                        body = json.dumps(
                            {"error": f"bad history query {self.path!r}"}
                        ).encode()
                        self.send_response(400)
                        self.send_header(
                            "Content-Type", "application/json"
                        )
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(
                        history_for(tracer).debug_doc(
                            window=window, wave=wave,
                            with_digests=with_digests, proc=tracer.proc,
                        )
                    ).encode()
                    ctype = "application/json"
                elif self.path.startswith("/debug/explain"):
                    import json
                    from urllib.parse import parse_qs, urlsplit

                    from .explainstore import store as explain_store
                    from .tracing import tracer

                    # query contract: ?binding=<ns>/<name> answers one
                    # binding's decision chain, ?wave=N pins/narrows to
                    # one wave; no binding = the wave's verdict summary
                    # + worst bindings. Malformed wave answers 400.
                    qs = parse_qs(urlsplit(self.path).query)
                    raw_wave = (qs.get("wave") or [None])[0]
                    try:
                        wave = (
                            int(raw_wave) if raw_wave is not None else None
                        )
                    except ValueError:
                        body = json.dumps(
                            {"error": f"bad wave={raw_wave!r}"}
                        ).encode()
                        self.send_response(400)
                        self.send_header(
                            "Content-Type", "application/json"
                        )
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    binding = (qs.get("binding") or [None])[0]
                    body = json.dumps(
                        explain_store().debug_doc(
                            binding=binding, wave=wave, proc=tracer.proc
                        )
                    ).encode()
                    ctype = "application/json"
                elif self.path.startswith("/debug/traces"):
                    import json
                    from urllib.parse import parse_qs, urlsplit

                    from .tracing import trace_debug_doc

                    # query contract: ?wave=N restricts to one wave,
                    # ?summary=1 drops the raw span list. Malformed
                    # values answer 400 — the stitcher must never
                    # mistake a mis-filtered full dump for a wave dump
                    qs = parse_qs(urlsplit(self.path).query)
                    wave = None
                    raw_wave = (qs.get("wave") or [None])[0]
                    try:
                        if raw_wave is not None:
                            wave = int(raw_wave)
                        summary = (qs.get("summary") or ["0"])[0] in (
                            "1", "true", "yes",
                        )
                    except ValueError:
                        body = json.dumps(
                            {"error": f"bad wave={raw_wave!r}"}
                        ).encode()
                        self.send_response(400)
                        self.send_header(
                            "Content-Type", "application/json"
                        )
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(
                        trace_debug_doc(wave, summary=summary)
                    ).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.port = self._httpd.server_address[1]
        self._threading = threading
        self._thread = None

    def start(self) -> int:
        self._thread = self._threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_process_metrics(port: Optional[str]) -> Optional[MetricsServer]:
    """THE shared ``--metrics-port`` semantics for the standalone process
    entrypoints (solver sidecar, estimator servers, store bus; the plane
    has its own --metrics-address): flag value wins, an absent flag falls
    back to $KARMADA_TPU_METRICS_PORT, and an empty value means disabled.
    The value is a port (``0`` = ephemeral, loopback bind) or
    ``HOST:PORT`` (``0.0.0.0:9090`` for an off-host scraper — loopback
    stays the DEFAULT so an operator opts in to exposure explicitly).
    Returns the STARTED server (caller prints/exports ``server.port``)
    or None when disabled."""
    import os

    if port is None:
        port = os.environ.get("KARMADA_TPU_METRICS_PORT", "")
    port = str(port).strip()
    if port == "":
        return None
    host = "127.0.0.1"
    if ":" in port:
        host, _, port = port.rpartition(":")
        host = host or "127.0.0.1"
    server = MetricsServer(address=(host, int(port)))
    server.start()
    return server
