"""Shared runtime utilities (ref: pkg/util)."""

from .quantity import (  # noqa: F401
    CPU,
    MEMORY,
    PODS,
    add_resource_lists,
    parse_quantity,
    parse_resource_list,
    sub_resource_lists,
)
from .store import ADDED, DELETED, MODIFIED, Event, Store, obj_key, obj_kind  # noqa: F401
from .worker import DONE, REQUEUE, Runtime, Worker  # noqa: F401
