"""Unified channel resilience: one retry/deadline/breaker policy for every
RPC channel (ISSUE 7 tentpole c).

Before this module each channel hand-rolled its own story — the solver
client re-paid its full timeout up to three times across score/sync/retry,
the estimator pool answered whatever the executor happened to produce, the
bus reconnected on a fixed 200 ms loop. Now all three share:

- ``Deadline`` — ONE overall budget threaded through a multi-step call
  (score -> re-sync -> retry pays one budget, not three stacked timeouts).
- ``BackoffPolicy`` — decorrelated-jitter sleeps (AWS architecture-blog
  form: ``sleep = min(cap, uniform(base, prev * 3))``), seeded per policy
  so chaos runs replay deterministically.
- ``CircuitBreaker`` — the closed/open/half-open machine per channel, with
  ``karmada_tpu_circuit_state`` / ``karmada_tpu_channel_retries_total``
  metrics and a breaker-transition span in the wave trace so a degraded
  pass is attributable after the fact. Half-open admits ONE probe; its
  success closes the breaker without operator action.
- ``call_with_resilience`` — the retry loop composing all three.

Degraded-mode rules (who falls back to what) stay with the channel owners:
a broken estimator channel answers UnauthenticReplica and never arms the
batch-identity replay (estimator/accurate.py), a broken solver sidecar
fails over to the in-proc engine (controllers/scheduler_controller.py), a
broken bus blocks the writer — backpressure — until the budget expires
(bus/service.py). See docs/OPERATIONS.md "Failure modes & degraded
operation".
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

# env knobs of the unified policy (utils.flags ENV_FLAGS)
BACKOFF_BASE_ENV = "KARMADA_TPU_BACKOFF_BASE"
BACKOFF_CAP_ENV = "KARMADA_TPU_BACKOFF_CAP"
BREAKER_RESET_ENV = "KARMADA_TPU_BREAKER_RESET_SECONDS"


def _as_float(raw: str, default: float) -> float:
    try:
        return float(raw or default)
    except ValueError:
        return default


# breaker states (the gauge's value encoding)
CLOSED = 0
OPEN = 1
HALF_OPEN = 2

_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}


class Deadline:
    """One overall wall-clock budget for a multi-step call."""

    def __init__(self, budget_seconds: float, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self.budget = float(budget_seconds)

    def remaining(self) -> float:
        return max(self.budget - (self._clock() - self._t0), 0.0)

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def attempt_timeout(self, per_attempt: Optional[float] = None) -> float:
        """Per-RPC timeout: the remaining budget, capped by the policy's
        per-attempt bound so one black-holed attempt cannot eat the whole
        budget (raised as a floor of 1 ms so gRPC never sees 0)."""
        rem = self.remaining()
        if per_attempt is not None:
            rem = min(rem, per_attempt)
        return max(rem, 0.001)


class DeadlineExceeded(Exception):
    """The overall budget ran out before an attempt succeeded. ``cause``
    carries the last transport error (None when the budget expired before
    any attempt ran, e.g. breaker-open fast-fail)."""

    def __init__(self, message: str, cause: Optional[Exception] = None):
        super().__init__(message)
        self.cause = cause


class CircuitBreakerOpen(Exception):
    """Fast-fail: the channel's breaker is open — the caller should take
    its degraded path immediately instead of burning a doomed RPC."""


@dataclass(frozen=True)
class BackoffPolicy:
    """Decorrelated-jitter retry schedule + attempt bounds."""

    base: float = 0.05  # first sleep (and jitter floor)
    cap: float = 2.0  # max sleep between attempts
    attempt_timeout: Optional[float] = None  # per-RPC bound (None = budget)
    max_attempts: int = 4

    def sleeps(self, rng: random.Random):
        """Yields the decorrelated-jitter sleep sequence."""
        prev = self.base
        while True:
            prev = min(self.cap, rng.uniform(self.base, prev * 3))
            yield prev


def default_policy(
    *,
    attempt_timeout: Optional[float] = None,
    max_attempts: int = 4,
) -> BackoffPolicy:
    """The env-tuned policy every channel starts from (one knob surface,
    three channels — the whole point of the unification)."""
    import os

    return BackoffPolicy(
        base=_as_float(os.environ.get(BACKOFF_BASE_ENV, ""), 0.05),
        cap=_as_float(os.environ.get(BACKOFF_CAP_ENV, ""), 2.0),
        attempt_timeout=attempt_timeout,
        max_attempts=max_attempts,
    )


def default_breaker(
    channel: str,
    *,
    failure_threshold: int = 3,
    reset_default: float = 5.0,
    clock=time.monotonic,
) -> "CircuitBreaker":
    """``reset_default`` is the channel owner's reset window when the env
    knob is unset — the bus uses a short one (its single cheap probe is
    an agent's lifeline back to the plane), the estimator/solver channels
    the standard 5 s. KARMADA_TPU_BREAKER_RESET_SECONDS overrides all."""
    import os

    return CircuitBreaker(
        channel,
        failure_threshold=failure_threshold,
        reset_seconds=_as_float(
            os.environ.get(BREAKER_RESET_ENV, ""), reset_default
        ),
        clock=clock,
    )


class CircuitBreaker:
    """Per-channel closed/open/half-open machine.

    - CLOSED: calls flow; ``failure_threshold`` consecutive failures open.
    - OPEN: ``allow()`` answers False until ``reset_seconds`` elapse.
    - HALF_OPEN: exactly one probe is admitted; success closes, failure
      re-opens (and restarts the reset window).

    Transitions move the ``karmada_tpu_circuit_state`` gauge and record a
    zero-duration ``channel.breaker`` span so a wave trace shows WHEN the
    channel degraded/recovered. All state mutates under one lock —
    ``allow``/``record_*`` race from fan-out executors.
    """

    def __init__(
        self,
        channel: str,
        *,
        failure_threshold: int = 3,
        reset_seconds: float = 5.0,
        clock=time.monotonic,
    ):
        self.channel = channel
        self.failure_threshold = failure_threshold
        self.reset_seconds = reset_seconds
        self._clock = clock
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._lock = threading.Lock()
        self._publish(CLOSED)

    # -- state surface -----------------------------------------------------

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def engaged(self) -> bool:
        """Non-consuming: are calls currently being rejected? Unlike
        ``allow()`` this never takes the half-open probe slot, so routing
        layers (the estimator fan-out) can skip a dead connection without
        starving the probe that would heal it."""
        with self._lock:
            if self._state == OPEN:
                return self._clock() - self._opened_at < self.reset_seconds
            if self._state == HALF_OPEN:
                return self._probing
            return False

    def allow(self) -> bool:
        """May a call proceed right now? OPEN past the reset window flips
        to HALF_OPEN and admits one probe; concurrent callers during the
        probe stay rejected (one canary, not a thundering herd)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.reset_seconds:
                    return False
                self._transition(HALF_OPEN)
                self._probing = True
                return True
            # HALF_OPEN: the single probe slot
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                self._probing = False
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == OPEN:
                # a failure while already open restarts the reset window:
                # paths that gate on engaged() alone (future callbacks —
                # no allow()-driven HALF_OPEN transition ever runs there)
                # must stay protected while failures keep arriving, and
                # heal one reset window after they STOP
                self._opened_at = self._clock()
            elif (
                self._state == CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(OPEN)

    # -- internals ---------------------------------------------------------

    def _transition(self, to: int) -> None:
        """Called with the lock held."""
        frm, self._state = self._state, to
        self._publish(to, frm)

    def _publish(self, to: int, frm: Optional[int] = None) -> None:
        from .metrics import circuit_state
        from .tracing import tracer

        circuit_state.set(to, channel=self.channel)
        if frm is not None and frm != to:
            tracer.record(
                "channel.breaker", 0.0, channel=self.channel,
                from_state=_STATE_NAMES[frm], to_state=_STATE_NAMES[to],
            )


def call_with_resilience(
    fn: Callable[[float], object],
    *,
    channel: str,
    policy: BackoffPolicy,
    breaker: Optional[CircuitBreaker] = None,
    deadline: Optional[Deadline] = None,
    retryable: tuple = (Exception,),
    rng: Optional[random.Random] = None,
    sleep=time.sleep,
):
    """Run ``fn(attempt_timeout_seconds)`` under the unified policy.

    - breaker open -> ``CircuitBreakerOpen`` immediately (no RPC burned).
    - each attempt gets ``deadline.attempt_timeout(policy.attempt_timeout)``
      as its timeout; retries sleep decorrelated jitter, clamped to the
      remaining budget.
    - retries feed ``karmada_tpu_channel_retries_total{channel}``; the
      budget running out raises ``DeadlineExceeded`` wrapping the last
      transport error. Non-retryable exceptions propagate untouched.
    """
    from .metrics import channel_retries

    if breaker is not None and not breaker.allow():
        raise CircuitBreakerOpen(f"{channel} channel breaker is open")
    deadline = deadline or Deadline(
        policy.attempt_timeout
        if policy.attempt_timeout is not None
        else 60.0
    )
    rng = rng or random.Random()
    sleeps = policy.sleeps(rng)
    last: Optional[Exception] = None
    for attempt in range(policy.max_attempts):
        if deadline.expired:
            break
        try:
            result = fn(deadline.attempt_timeout(policy.attempt_timeout))
        except retryable as exc:  # noqa: PERF203 — retry loop
            last = exc
            if breaker is not None:
                breaker.record_failure()
                # non-consuming check: allow() here could take the half-
                # open probe slot and then leak it if the loop exits on
                # max_attempts/deadline without another fn() call —
                # wedging the breaker (nothing left to record)
                if breaker.engaged():
                    break  # opened mid-call: stop burning the budget
            if attempt + 1 >= policy.max_attempts:
                break
            channel_retries.inc(channel=channel)
            pause = min(next(sleeps), deadline.remaining())
            if pause > 0:
                sleep(pause)
            continue
        except BaseException:
            # non-retryable failure still resolves the breaker admission
            # (an unresolved half-open probe slot would wedge the breaker)
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return result
    raise DeadlineExceeded(
        f"{channel} call failed within {deadline.budget:.3f}s budget "
        f"({type(last).__name__ if last else 'no attempt ran'})",
        cause=last,
    )
