"""Feature gates (ref: pkg/features/features.go:33-86, defaults mirrored)."""

from __future__ import annotations

FAILOVER = "Failover"
GRACEFUL_EVICTION = "GracefulEviction"
PROPAGATE_DEPS = "PropagateDeps"
CUSTOMIZED_CLUSTER_RESOURCE_MODELING = "CustomizedClusterResourceModeling"
POLICY_PREEMPTION = "PropagationPolicyPreemption"
MULTI_CLUSTER_SERVICE = "MultiClusterService"
RESOURCE_QUOTA_ESTIMATE = "ResourceQuotaEstimate"
STATEFUL_FAILOVER_INJECTION = "StatefulFailoverInjection"

DEFAULTS = {
    FAILOVER: False,
    GRACEFUL_EVICTION: True,
    PROPAGATE_DEPS: True,
    CUSTOMIZED_CLUSTER_RESOURCE_MODELING: True,
    POLICY_PREEMPTION: False,
    MULTI_CLUSTER_SERVICE: False,
    RESOURCE_QUOTA_ESTIMATE: False,
    STATEFUL_FAILOVER_INJECTION: False,
}


class FeatureGate:
    def __init__(self, overrides: dict[str, bool] | None = None):
        self._state = dict(DEFAULTS)
        if overrides:
            self._state.update(overrides)

    def enabled(self, feature: str) -> bool:
        return self._state.get(feature, False)

    def set(self, feature: str, value: bool) -> None:
        self._state[feature] = value


# shared global gate, mirroring features.FeatureGate
feature_gate = FeatureGate()
