"""Scheduling-grid device mesh: env-resolved construction + shardings.

The fleet kernels (scheduler/fleet.py) take a ``jax.sharding.Mesh`` as a
static argument and partition the bucket-grid solve along the bindings
axis with ``with_sharding_constraint`` (and, opt-in, the cluster axis) —
SNIPPETS [2]'s naive-sharding pattern applied to the scheduling grid.
This module is everything AROUND that mesh:

- **Construction** (``scheduling_mesh``/``resolve_mesh``): a 1-D (or
  B×C) mesh over the first N visible devices, resolved once per engine
  from ``KARMADA_TPU_MESH_DEVICES`` / ``KARMADA_TPU_MESH_CLUSTER_AXIS``
  (the trace-manifest resolution pattern: an explicit Mesh passes
  through, ``False`` forces single-device even with the env set, None
  falls back to the env default). CPU CI dry-runs honor
  ``--xla_force_host_platform_device_count`` — ``ensure_host_devices``
  writes the flag when backends have not initialized yet.
- **Identity** (``mesh_shape``/``mesh_from_shape``): the canonical,
  JSON-serializable shape of a mesh — ``(("b", nb), ("c", nc))`` — used
  by the fleet trace keys, the prewarm manifest records, the solver
  sidecar's reporting line, and ``/debug/traces``. A Mesh object is not
  serializable; its shape is, and two processes whose meshes share a
  shape compile the same partitioned executables, so the shape IS the
  compile-identity component (a manifest recorded at mesh=1 can never
  seed a mesh=8 boot's ledger — the keys differ).
- **Kernel-family shardings** (``FAMILY_SPECS``/``family_shardings``):
  the documented in/out ``PartitionSpec`` layout of every fleet kernel
  family (divide / dispense / estimate / masks / quota) plus the fleet
  residents. The production paths place data via ``shard_rows`` (engine
  quota admission) and the fleet kernels' in-body constraints /
  ``FleetTable._alloc_resident`` — FAMILY_SPECS is the REFERENCE those
  layouts are written against (asserted well-formed in
  tests/test_mesh_sharding.py), and the construction surface for
  explicit placers a new sharded entry point may add (see
  DEVELOPMENT.md "Adding a sharded kernel entry point").

Padding contract: the fleet pads batches to a multiple of the effective
chunk (itself pow2 ≥ 256), and supported mesh extents are powers of two
≤ 8 axes-product — so every padded batch divides the mesh evenly and
padding rows (``rows == -1``) are masked out exactly like the existing
bucket padding. ``divisible`` is the predicate the dispatch site guards
on; a non-dividing mesh falls back to single-device semantics rather
than mis-sharding.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

log = logging.getLogger("karmada_tpu")

#: device count of the scheduling mesh: "" / "0" / "1" = single-device
#: (mesh off), an integer N = first N visible devices, "auto" = every
#: visible device. Declared in utils.flags.ENV_FLAGS.
MESH_ENV = "KARMADA_TPU_MESH_DEVICES"

#: cluster-axis extent of the mesh (the "c" axis): 1 (default) = pure
#: binding-parallel; >1 additionally shards the cluster axis (the
#: dispense sorts ride c-axis collectives). Must divide the device count.
CLUSTER_AXIS_ENV = "KARMADA_TPU_MESH_CLUSTER_AXIS"


def ensure_host_devices(n: int) -> None:
    """Best-effort: make >= n virtual CPU devices available by writing
    ``--xla_force_host_platform_device_count`` into XLA_FLAGS. Effective
    only before the first backend initialization; harmless afterwards
    (callers that need certainty check ``len(jax.devices())``)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m and int(m.group(1)) >= n:
        return
    opt = f"--xla_force_host_platform_device_count={n}"
    if m:
        flags = flags.replace(m.group(0), opt)
    else:
        flags = (flags + " " + opt).strip()
    os.environ["XLA_FLAGS"] = flags


def scheduling_mesh(
    n_devices: Optional[int] = None,
    *,
    cluster_axis: int = 1,
    allow_cpu_fallback: bool = False,
):
    """A ("b", "c") mesh over the first n visible devices (the
    binding-parallel axis carries n // cluster_axis). Thin delegate to
    ``solver.default_mesh`` so the two construction paths cannot drift."""
    from .solver import default_mesh

    return default_mesh(
        n_devices,
        cluster_axis=cluster_axis,
        allow_cpu_fallback=allow_cpu_fallback,
    )


def resolve_mesh(spec=None):
    """Normalize an engine's ``mesh`` argument.

    A Mesh passes through; ``False`` forces single-device even with the
    env set (the explicit opt-out, mirroring ``trace_manifest=""``);
    None falls back to the env default: ``KARMADA_TPU_MESH_DEVICES``
    unset/empty/"0"/"1" resolves to None (single-device), ``"auto"`` to
    every visible device, an integer N to the first N. A set env that
    cannot build (fewer devices than asked, bad integer, cluster axis
    not dividing) raises — the operator asked for a mesh; silently
    benchmarking single-device would mask a misconfigured rig."""
    if spec is False:
        return None
    if spec is not None:
        return spec  # an already-built Mesh (duck-typed: jax stays lazy)
    raw = os.environ.get(MESH_ENV, "").strip().lower()
    if raw in ("", "0", "1"):
        return None
    c_raw = os.environ.get(CLUSTER_AXIS_ENV, "1").strip() or "1"
    try:
        cluster_axis = int(c_raw)
    except ValueError:
        raise ValueError(
            f"{CLUSTER_AXIS_ENV}={c_raw!r} is not an integer"
        ) from None
    if raw == "auto":
        n = None
    else:
        try:
            n = int(raw)
        except ValueError:
            raise ValueError(
                f"{MESH_ENV}={raw!r}: expected an integer device count, "
                "'auto', or empty/0/1 for single-device"
            ) from None
    mesh = scheduling_mesh(n, cluster_axis=cluster_axis)
    record_active_mesh(mesh)
    return mesh


def mesh_shape(mesh) -> Optional[tuple]:
    """Canonical (JSON-round-trippable) identity of a mesh:
    ``(("b", nb), ("c", nc))``; None for single-device. This tuple is
    what fleet trace keys and manifest records carry — equal shapes
    compile equal partitioned executables."""
    if mesh is None:
        return None
    return tuple(
        (str(name), int(size))
        for name, size in zip(mesh.axis_names, mesh.devices.shape)
    )


def mesh_from_shape(shape):
    """Rebuild a mesh matching a recorded ``mesh_shape`` over THIS
    process's devices (prewarm replay of a meshed trace record). Raises
    when the current backend cannot host it — the caller (replay) counts
    that record failed, so it can never seed the new-trace ledger."""
    if shape is None:
        return None
    axes = {str(name): int(size) for name, size in shape}
    unknown = set(axes) - {"b", "c"}
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)} in {shape!r}")
    total = axes.get("b", 1) * axes.get("c", 1)
    return scheduling_mesh(total, cluster_axis=axes.get("c", 1))


def materialize_mesh_statics(statics: dict) -> dict:
    """Replace a serialized ``mesh`` shape entry (tuple/list form, as
    stored by the trace manifest and the IR spec grid) with a live Mesh
    built over this process's devices. Entries already holding a Mesh —
    or None — pass through untouched."""
    mesh = statics.get("mesh")
    if mesh is None or not isinstance(mesh, (tuple, list)):
        return statics
    out = dict(statics)
    out["mesh"] = mesh_from_shape(mesh)
    return out


def divisible(n: int, mesh, axis: str = "b") -> bool:
    """True when an ``n``-extent axis divides the mesh axis evenly — the
    dispatch-site guard before sharding that axis (padding has already
    rounded batch rows to the chunk quantum, so in practice only exotic
    non-pow2 meshes fail this)."""
    if mesh is None:
        return True
    size = dict(
        zip(mesh.axis_names, (int(s) for s in mesh.devices.shape))
    ).get(axis, 1)
    return size <= 1 or n % size == 0


def pad_to_mesh(n: int, mesh, axis: str = "b") -> int:
    """Round ``n`` up to the next multiple of the mesh axis extent (the
    mesh-divisible bucket; padding rows are masked out downstream)."""
    if mesh is None:
        return n
    size = dict(
        zip(mesh.axis_names, (int(s) for s in mesh.devices.shape))
    ).get(axis, 1)
    return n if size <= 1 else -(-n // size) * size


# -- kernel-family in/out layouts -------------------------------------------
#
# PartitionSpec element grammar: "b" = bindings axis, "c" = clusters axis,
# None = replicated dimension. One entry per positional kernel input, in
# dispatch order; "out" mirrors the kernel's outputs. Table-shaped inputs
# (interned slot tables, cap tensors, remaining) replicate — they are
# gathered per row on device and orders of magnitude smaller than the
# grid. These are the REFERENCE layouts: the fleet kernels realize them
# as in-body constraints and the engine's quota path via shard_rows;
# family_shardings turns an entry into concrete NamedShardings for
# explicit device_put placement.

FAMILY_SPECS: dict = {
    # divide_replicas(strategy[B], replicas[B], candidates[B,C],
    #                 static_w[B,C], avail[B,C], prev[B,C], fresh[B])
    "divide": {
        "in": (("b",), ("b",), ("b", "c"), ("b", "c"), ("b", "c"),
               ("b", "c"), ("b",)),
        "out": (("b", "c"), ("b",)),
    },
    # take_by_weight_batch(n[B], weights[B,C], limits[B,C], prev[B,C])
    "dispense": {
        "in": (("b",), ("b", "c"), ("b", "c"), ("b", "c")),
        "out": (("b", "c"),),
    },
    # general_estimate(available_cap[C,R], requests[B,R])
    "estimate": {
        "in": (("c", None), ("b", None)),
        "out": (("b", "c"),),
    },
    # contains_all/intersects(table[C,W], query[W])
    "masks": {
        "in": (("c", None), (None,)),
        "out": (("c",),),
    },
    # quota_admit(ns_ids[B], demand[B,R], remaining[N,R])
    "quota": {
        "in": (("b",), ("b", None), (None, None)),
        "out": (("b",), (None, None)),
    },
    # the fleet residents (donated, persistent): dense[cap,C], meta[cap],
    # entries[cap,k] — sharded over table rows so pass-to-pass donation
    # aliases shard-local buffers and no gather precedes the solve
    "fleet_resident": {
        "in": (("b", "c"), ("b",), ("b", None)),
        "out": (("b", "c"), ("b",), ("b", None)),
    },
}


def family_shardings(mesh, family: str, direction: str = "in") -> tuple:
    """NamedShardings for one kernel family's flat signature (see
    FAMILY_SPECS). The "c" element only engages when the mesh carries a
    >1 cluster axis — otherwise those dimensions replicate, matching the
    fleet kernels' ``shard_c`` gating."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    specs = FAMILY_SPECS[family][direction]
    sizes = dict(zip(mesh.axis_names, (int(s) for s in mesh.devices.shape)))
    c_on = sizes.get("c", 1) > 1

    def el(e):
        if e == "c" and not c_on:
            return None
        return e

    return tuple(
        NamedSharding(mesh, P(*(el(e) for e in spec))) for spec in specs
    )


def shard_rows(mesh, *arrays):
    """Place arrays with their LEADING axis sharded over the mesh "b"
    axis (trailing dims replicated) — the one-liner for batch-axis
    inputs like the quota admission wave. Arrays whose leading extent
    does not divide the mesh pass through unplaced (single-device
    semantics, the same fallback the fleet dispatch applies)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = []
    for a in arrays:
        if mesh is None or not divisible(int(a.shape[0]), mesh):
            out.append(a)
        else:
            spec = P("b", *([None] * (a.ndim - 1)))
            out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


# -- process-level mesh identity (reporting surfaces) -----------------------

#: last mesh this process resolved/adopted (shape form): the solver
#: sidecar line, /debug/traces, `karmadactl-tpu trace dump` and the
#: warmup stats all read THIS so an operator can tell a single-chip from
#: an 8-chip plane without poking jax
_ACTIVE_SHAPE: list = [None]


def record_active_mesh(mesh) -> None:
    """Adopt a mesh as this process's reported scheduling mesh (engines
    call it on construction; resolve_mesh on env resolution)."""
    if mesh is not None:
        _ACTIVE_SHAPE[0] = mesh_shape(mesh)


def active_mesh_shape() -> Optional[list]:
    """JSON form of the process's scheduling-mesh shape ([["b", nb],
    ["c", nc]]), or None when every engine runs single-device."""
    shape = _ACTIVE_SHAPE[0]
    if shape is None:
        return None
    return [[name, size] for name, size in shape]
