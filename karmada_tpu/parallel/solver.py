"""The fused scheduling step and its device-mesh sharding.

``schedule_step`` is the flagship jitted program: estimator availability +
min-merge + unified division in one XLA computation (the whole
Algorithm.Schedule subtree of SURVEY.md section 3.1 minus host-side group
search). Bindings are independent, so the batch axis shards like data
parallelism; the cluster axis can shard like model parallelism when
num_clusters x resource-dims outgrows a core (SURVEY.md section 5
"long-context" analogue: the per-row sorts over a sharded cluster axis are
where XLA inserts collectives).

``make_sharded_step`` places inputs with NamedSharding over a
``Mesh(axis_names=("b", "c"))`` and lets GSPMD partition: elementwise work
stays local; the lexicographic sorts along the cluster axis induce
all-gathers on the ``c`` axis only — exactly the collective structure the
scaling-book recipe predicts for sort-limited kernels. With ``c`` unsharded
(the default for <=5k clusters) the step runs with zero communication.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.divide import DivideResult, _divide_batch
from ..ops.estimate import (
    general_estimate,
    general_estimate_interned,
    merge_estimates,
)


def _merge_and_divide(
    general, has_summary, strategy, replicas, candidates, static_w, prev,
    fresh, has_aggregated, wide, fast,
) -> DivideResult:
    """Shared tail of both step variants: sentinel masking, estimator
    min-merge, unified division."""
    general = jnp.where(has_summary[None, :], general, jnp.int32(-1))
    avail = merge_estimates(replicas, (general,))
    out, unsched = _divide_batch(
        strategy, replicas, candidates, static_w, avail, prev, fresh,
        has_aggregated, wide, fast,
    )
    return DivideResult(assignment=out, unschedulable=unsched)


def _schedule_step(
    available_cap: jnp.ndarray,  # int64[C, R] cluster capacity
    has_summary: jnp.ndarray,  # bool[C]
    requests: jnp.ndarray,  # int64[B, R]
    strategy: jnp.ndarray,  # int32[B]
    replicas: jnp.ndarray,  # int32[B]
    candidates: jnp.ndarray,  # bool[B, C]
    static_w: jnp.ndarray,  # int32[B, C]
    prev: jnp.ndarray,  # int32[B, C]
    fresh: jnp.ndarray,  # bool[B]
    has_aggregated: bool = True,
    wide: bool = True,
    fast: tuple | None = None,
) -> DivideResult:
    general = general_estimate(available_cap, requests)
    return _merge_and_divide(
        general, has_summary, strategy, replicas, candidates, static_w,
        prev, fresh, has_aggregated, wide, fast,
    )


schedule_step = jax.jit(
    _schedule_step, static_argnames=("has_aggregated", "wide", "fast")
)


def _schedule_step_interned(
    available_cap: jnp.ndarray,  # int64[C, R] cluster capacity
    has_summary: jnp.ndarray,  # bool[C]
    profiles: jnp.ndarray,  # int64[U, R] unique request rows
    prof_idx: jnp.ndarray,  # int32[B]
    strategy: jnp.ndarray,  # int32[B]
    replicas: jnp.ndarray,  # int32[B]
    candidates: jnp.ndarray,  # bool[B, C]
    static_w: jnp.ndarray,  # int32[B, C]
    prev: jnp.ndarray,  # int32[B, C]
    fresh: jnp.ndarray,  # bool[B]
    has_aggregated: bool = True,
    wide: bool = True,
    fast: tuple | None = None,
) -> DivideResult:
    """``schedule_step`` with request-profile interning: the estimator runs
    per unique profile ([U, C] divisions) and the per-binding matrix is a
    one-hot-matmul gather — see ``ops.estimate.general_estimate_interned``."""
    general = general_estimate_interned(available_cap, profiles, prof_idx)
    return _merge_and_divide(
        general, has_summary, strategy, replicas, candidates, static_w,
        prev, fresh, has_aggregated, wide, fast,
    )


schedule_step_interned = jax.jit(
    _schedule_step_interned, static_argnames=("has_aggregated", "wide", "fast")
)


def make_sharded_step(mesh: Mesh, *, shard_clusters: bool = False):
    """jit ``schedule_step`` with bindings sharded over mesh axis ``b`` (and
    optionally clusters over ``c``). Inputs may be numpy; placement happens
    via in_shardings."""
    c_ax = "c" if shard_clusters and "c" in mesh.axis_names else None
    bc = P("b", c_ax)
    row_b = P("b")
    row_c = P(c_ax)
    in_shardings = tuple(
        NamedSharding(mesh, s)
        for s in (
            P(c_ax, None),  # available_cap[C, R]
            row_c,  # has_summary[C]
            P("b", None),  # requests[B, R]
            row_b,  # strategy
            row_b,  # replicas
            bc,  # candidates
            bc,  # static_w
            bc,  # prev
            row_b,  # fresh
        )
    )
    out_shardings = DivideResult(
        assignment=NamedSharding(mesh, bc),
        unschedulable=NamedSharding(mesh, row_b),
    )
    return jax.jit(
        _schedule_step,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        static_argnames=("has_aggregated", "wide", "fast"),
    )


def default_mesh(
    n_devices: int | None = None,
    *,
    cluster_axis: int = 1,
    allow_cpu_fallback: bool = False,
) -> Mesh:
    """Mesh over the first n devices: ("b", "c") with the cluster axis sized
    ``cluster_axis`` (1 = pure binding-parallel).

    ``allow_cpu_fallback`` is for dry-runs only: when the default backend
    exposes fewer than ``n_devices`` (e.g. one tunneled TPU chip) but enough
    virtual CPU devices exist via --xla_force_host_platform_device_count, the
    mesh is built over CPU devices instead. Perf-sensitive callers must leave
    it off so a misconfigured accelerator fails loudly instead of silently
    benchmarking CPU.
    """
    devs = jax.devices()
    if allow_cpu_fallback and n_devices and len(devs) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devs = cpu
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(
            f"default_mesh: {n} devices requested but only {len(devs)} visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N before "
            "the first jax import to dry-run multi-chip on CPU)"
        )
    if n % cluster_axis:
        raise ValueError(
            f"default_mesh: {n} devices not divisible by cluster_axis={cluster_axis}"
        )
    devs = devs[:n]
    b = n // cluster_axis
    import numpy as np

    grid = np.array(devs).reshape(b, cluster_axis)
    return Mesh(grid, axis_names=("b", "c"))
