"""Device-mesh sharding of the scheduling solver."""

from .solver import default_mesh, make_sharded_step, schedule_step  # noqa: F401
