"""Device-mesh sharding of the scheduling solver."""

from .mesh import (  # noqa: F401
    active_mesh_shape,
    mesh_from_shape,
    mesh_shape,
    resolve_mesh,
    scheduling_mesh,
)
from .solver import (  # noqa: F401
    default_mesh,
    make_sharded_step,
    schedule_step,
    schedule_step_interned,
)
