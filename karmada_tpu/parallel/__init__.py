"""Device-mesh sharding of the scheduling solver."""

from .solver import (  # noqa: F401
    default_mesh,
    make_sharded_step,
    schedule_step,
    schedule_step_interned,
)
