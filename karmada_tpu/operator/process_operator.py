"""Process-deployment operator: the Karmada CR installs REAL processes.

Ref: operator/pkg/tasks/init — the reference operator's core job is
standing up certs, etcd, the apiserver and every component as actual
workloads, then reconciling spec drift against the running deployment.
``KarmadaOperator`` (karmada_operator.py) keeps the task-graph/upgrade
semantics in-process; THIS operator runs the same workflow engine but its
tasks manage OS processes and PKI:

  validate -> certs (openssl CA + server cert) -> admission webhook (TLS
  process) -> solver sidecar -> estimator server -> control plane (bus +
  proxy + /metrics, wired to every sidecar) -> pull agents -> wait-ready
  (healthz + bus sync probes)

Upgrade reconciles diff the applied spec: component enable/disable
restarts the affected processes; version skew is validated before any
restart; pull-member changes start/stop agent processes. Deinit tears the
processes down in reverse order and removes the instance PKI.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from ..api.core import Condition, set_condition
from .karmada_operator import (
    Karmada,
    KarmadaSpec,
    _spec_copy,
    validate_version_skew,
)
from .workflow import Job, Task


@dataclass
class ProcessInstance:
    """One installed deployment: endpoints + child processes + PKI."""

    name: str
    pki_dir: str = ""
    procs: dict[str, subprocess.Popen] = field(default_factory=dict)
    endpoints: dict[str, object] = field(default_factory=dict)

    def alive(self, component: str) -> bool:
        proc = self.procs.get(component)
        return proc is not None and proc.poll() is None


from ..localup import scrape_line as _scrape, spawn_child as _spawn


def _stop(proc: Optional[subprocess.Popen], grace: float = 5.0) -> None:
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=grace)


class ProcessKarmadaOperator:
    """Reconciles Karmada CRs into multi-process deployments."""

    def __init__(self, checkpoint_interval: float = 15.0) -> None:
        self.instances: dict[str, ProcessInstance] = {}
        self._applied_specs: dict[str, KarmadaSpec] = {}
        self.checkpoint_interval = checkpoint_interval

    # -- public ------------------------------------------------------------

    def reconcile(self, karmada: Karmada) -> ProcessInstance:
        name = karmada.meta.name
        fresh = name not in self.instances
        job = (
            self._init_job(karmada) if fresh else self._upgrade_job(karmada)
        )
        karmada.status.failed_task = ""
        try:
            job.run()
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=True, reason="Completed"),
            )
            karmada.status.installed_version = karmada.spec.version
            karmada.status.observed_generation = karmada.meta.generation
            self._applied_specs[name] = _spec_copy(karmada.spec)
        except Exception as e:
            karmada.status.failed_task = getattr(e, "task_name", "")
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=False, reason="TaskFailed",
                          message=str(e)),
            )
            if fresh:
                inst = self.instances.pop(name, None)
                if inst is not None:
                    self._teardown(inst)
            raise
        finally:
            karmada.status.completed_tasks = list(job.completed)
        return self.instances[name]

    def supervise(self, karmada: Karmada) -> list[str]:
        """One supervision sweep (the Deployment-controller analogue the
        reference gets from Kubernetes itself): restart any dead component
        of an installed instance at its PINNED endpoint. The plane restarts
        from its latest periodic checkpoint; gRPC clients (RemoteSolver,
        estimator connections, StoreReplica agents) reconnect to the pinned
        ports on their own — the solver's snapshot-version fencing re-syncs
        cluster state on the first post-restart schedule. Returns the
        component names restarted."""
        inst = self.instances.get(karmada.meta.name)
        if inst is None:
            return []
        data = {"karmada": karmada}
        restarted: list[str] = []
        starters = {
            "webhook": self._start_webhook,
            "solver": self._start_solver,
            "estimator": self._start_estimator,
            "plane": self._start_plane,
        }
        for comp, proc in list(inst.procs.items()):
            if proc.poll() is None:
                continue
            if comp.startswith("agent-"):
                self._spawn_agent(inst, comp[len("agent-"):])
            else:
                starters[comp](data)
            restarted.append(comp)
        if restarted:
            self._wait_ready(data)
        return restarted

    def deinit(self, karmada: Karmada) -> None:
        inst = self.instances.pop(karmada.meta.name, None)
        self._applied_specs.pop(karmada.meta.name, None)
        if inst is not None:
            self._teardown(inst)
        set_condition(
            karmada.status.conditions,
            Condition(type="Ready", status=False, reason="Removed"),
        )

    def _teardown(self, inst: ProcessInstance) -> None:
        # reverse start order: agents, plane, sidecars, webhook
        for comp in reversed(list(inst.procs)):
            _stop(inst.procs[comp])
        if inst.pki_dir and os.path.isdir(inst.pki_dir):
            shutil.rmtree(inst.pki_dir, ignore_errors=True)

    # -- init pipeline -----------------------------------------------------

    def _init_job(self, karmada: Karmada) -> Job:
        karmada_spec = karmada.spec
        return Job(
            tasks=[
                Task(name="validate", run=self._validate),
                Task(name="certs", run=self._certs),
                Task(
                    name="webhook", run=self._start_webhook,
                    skip=lambda d: not karmada_spec.components.webhook.enabled,
                ),
                Task(name="solver", run=self._start_solver),
                Task(
                    name="estimator", run=self._start_estimator,
                    skip=lambda d: not karmada_spec.components.estimators.enabled,
                ),
                Task(name="control-plane", run=self._start_plane),
                Task(name="agents", run=self._start_agents),
                Task(name="wait-ready", run=self._wait_ready),
            ],
            data={"karmada": karmada},
        )

    def _instance(self, data: dict) -> ProcessInstance:
        karmada = data["karmada"]
        inst = self.instances.get(karmada.meta.name)
        if inst is None:
            inst = ProcessInstance(name=karmada.meta.name)
            self.instances[karmada.meta.name] = inst
        return inst

    def _validate(self, data: dict) -> None:
        karmada = data["karmada"]
        validate_version_skew(karmada.spec.version, karmada.spec.components)
        self._instance(data)

    def _certs(self, data: dict) -> None:
        """operator/pkg/tasks/init cert task: a real self-signed PKI for
        the instance's TLS surfaces (admission webhook)."""
        inst = self._instance(data)
        inst.pki_dir = tempfile.mkdtemp(prefix=f"karmada-pki-{inst.name}-")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", os.path.join(inst.pki_dir, "webhook.key"),
             "-out", os.path.join(inst.pki_dir, "webhook.crt"),
             "-days", "3650", "-subj", "/CN=localhost",
             "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
            check=True, capture_output=True,
        )

    def _start_webhook(self, data: dict) -> None:
        inst = self._instance(data)
        # pinned on restart: the live plane's RemoteAdmission keeps dialing
        # the URL it was constructed with
        prev = str(inst.endpoints.get("webhook", ""))
        port = prev.rsplit(":", 1)[-1].split("/")[0] if prev else "0"
        proc = _spawn(
            [sys.executable, "-m", "karmada_tpu.webhook.server",
             "--address", f"127.0.0.1:{port}",
             "--certfile", os.path.join(inst.pki_dir, "webhook.crt"),
             "--keyfile", os.path.join(inst.pki_dir, "webhook.key")]
        )
        inst.procs["webhook"] = proc
        port = _scrape(proc, r"listening on port (\d+)")
        inst.endpoints["webhook"] = f"https://127.0.0.1:{port}/admit"

    def _start_solver(self, data: dict) -> None:
        inst = self._instance(data)
        port = inst.endpoints.get("solver", 0)  # pinned on restart
        proc = _spawn(
            [sys.executable, "-m", "karmada_tpu.solver",
             "--address", f"127.0.0.1:{port}"]
        )
        inst.procs["solver"] = proc
        inst.endpoints["solver"] = int(_scrape(proc, r"port (\d+)"))

    def _start_estimator(self, data: dict) -> None:
        inst = self._instance(data)
        port = inst.endpoints.get("estimator", 0)  # pinned on restart
        proc = _spawn(
            [sys.executable, "-m", "karmada_tpu.estimator",
             "--cluster", "member1", "--address", f"127.0.0.1:{port}"]
        )
        inst.procs["estimator"] = proc
        inst.endpoints["estimator"] = int(_scrape(proc, r"port (\d+)"))

    def _plane_cmd(self, data: dict) -> list[str]:
        inst = self._instance(data)
        karmada = data["karmada"]
        spec = karmada.spec
        cmd = [
            sys.executable, "-m", "karmada_tpu.localup", "serve",
            "--members", str(max(1, len(spec.member_clusters) or 2)),
            "--state-file", os.path.join(inst.pki_dir, "store.ckpt"),
            "--checkpoint-interval", str(self.checkpoint_interval),
        ]
        # pinned surfaces on restart: agents / CLIs / supervision probes
        # keep their targets across plane replacements
        if "bus" in inst.endpoints:
            cmd += ["--bus-address", f"127.0.0.1:{inst.endpoints['bus']}"]
        if "proxy" in inst.endpoints:
            cmd += ["--proxy-address", f"127.0.0.1:{inst.endpoints['proxy']}"]
        if "metrics" in inst.endpoints:
            cmd += ["--metrics-address", f"127.0.0.1:{inst.endpoints['metrics']}"]
        for name in spec.pull_members:
            cmd += ["--pull", name]
        if "solver" in inst.endpoints:
            cmd += ["--solver", f"127.0.0.1:{inst.endpoints['solver']}"]
        if "estimator" in inst.endpoints:
            cmd += [
                "--estimator", f"member1=127.0.0.1:{inst.endpoints['estimator']}"
            ]
        if "webhook" in inst.endpoints:
            cmd += [
                "--admission", inst.endpoints["webhook"],
                "--admission-ca", os.path.join(inst.pki_dir, "webhook.crt"),
            ]
        if spec.components.descheduler.enabled:
            cmd += ["--descheduler"]
        gates = dict(spec.feature_gates)
        if gates:
            cmd += [
                "--feature-gates",
                ",".join(f"{k}={str(v).lower()}" for k, v in gates.items()),
            ]
        return cmd

    def _start_plane(self, data: dict) -> None:
        inst = self._instance(data)
        proc = _spawn(self._plane_cmd(data))
        inst.procs["plane"] = proc
        line = _scrape(proc, r"(\{.*\})")
        info = json.loads(line)
        inst.endpoints.update(
            bus=info["bus"], proxy=info["proxy"], metrics=info["metrics"],
            clusters=info["clusters"],
        )

    def _spawn_agent(self, inst: ProcessInstance, name: str) -> None:
        inst.procs[f"agent-{name}"] = _spawn(
            [sys.executable, "-m", "karmada_tpu.bus.agent",
             "--target", f"127.0.0.1:{inst.endpoints['bus']}",
             "--cluster", name]
        )

    def _start_agents(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        for name in karmada.spec.pull_members:
            self._spawn_agent(inst, name)

    def _wait_ready(self, data: dict) -> None:
        inst = self._instance(data)
        deadline = time.time() + 30
        url = f"http://127.0.0.1:{inst.endpoints['metrics']}/healthz"
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.read() == b"ok\n":
                        return
            except Exception:
                time.sleep(0.2)
        raise RuntimeError("control plane never became healthy")

    # -- upgrade reconcile -------------------------------------------------

    def _upgrade_job(self, karmada: Karmada) -> Job:
        prev = self._applied_specs[karmada.meta.name]
        spec = karmada.spec
        tasks = [Task(name="validate", run=self._validate)]
        # ANY field consumed by _plane_cmd (or by the sidecars it points
        # at) that drifted forces a plane restart — a partial diff here
        # would silently diverge the deployment from the CR while
        # reporting Ready
        plane_restart = (
            spec.components.descheduler.enabled
            != prev.components.descheduler.enabled
            or spec.feature_gates != prev.feature_gates
            or spec.pull_members != prev.pull_members
            or spec.member_clusters != prev.member_clusters
            or spec.version != prev.version
        )
        if (
            spec.components.estimators.enabled
            != prev.components.estimators.enabled
        ):
            tasks.append(Task(name="estimator", run=self._toggle_estimator))
            plane_restart = True
        if (
            spec.components.webhook.enabled
            != prev.components.webhook.enabled
        ):
            tasks.append(Task(name="webhook", run=self._toggle_webhook))
            plane_restart = True
        if plane_restart:
            tasks.append(Task(name="control-plane", run=self._restart_plane))
            tasks.append(Task(name="agents", run=self._restart_agents))
        tasks.append(Task(name="wait-ready", run=self._wait_ready))
        return Job(tasks=tasks, data={"karmada": karmada})

    def _toggle_estimator(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        if karmada.spec.components.estimators.enabled:
            if not inst.alive("estimator"):
                self._start_estimator(data)
        else:
            _stop(inst.procs.pop("estimator", None))
            inst.endpoints.pop("estimator", None)

    def _toggle_webhook(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        if karmada.spec.components.webhook.enabled:
            if not inst.alive("webhook"):
                self._start_webhook(data)
        else:
            _stop(inst.procs.pop("webhook", None))
            inst.endpoints.pop("webhook", None)

    def _restart_plane(self, data: dict) -> None:
        inst = self._instance(data)
        _stop(inst.procs.pop("plane", None))
        self._start_plane(data)

    def _restart_agents(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        want = set(karmada.spec.pull_members)
        for comp in [c for c in inst.procs if c.startswith("agent-")]:
            _stop(inst.procs.pop(comp))
        for name in want:
            self._spawn_agent(inst, name)
