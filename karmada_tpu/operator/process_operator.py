"""Process-deployment operator: the Karmada CR installs REAL processes.

Ref: operator/pkg/tasks/init — the reference operator's core job is
standing up certs, etcd, the apiserver and every component as actual
workloads, then reconciling spec drift against the running deployment.
``KarmadaOperator`` (karmada_operator.py) keeps the task-graph/upgrade
semantics in-process; THIS operator runs the same workflow engine but its
tasks manage OS processes and PKI:

  validate -> certs (openssl CA + server cert) -> admission webhook (TLS
  process) -> solver sidecar -> estimator server -> control plane (bus +
  proxy + /metrics, wired to every sidecar) -> pull agents -> wait-ready
  (healthz + bus sync probes)

Upgrade reconciles diff the applied spec: component enable/disable
restarts the affected processes; version skew is validated before any
restart; pull-member changes start/stop agent processes. Deinit tears the
processes down in reverse order and removes the instance PKI.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from ..api.core import Condition, set_condition
from .karmada_operator import (
    Karmada,
    KarmadaSpec,
    _spec_copy,
    validate_version_skew,
)
from .workflow import Job, Task


@dataclass
class ProcessInstance:
    """One installed deployment: endpoints + child processes + PKI."""

    name: str
    pki_dir: str = ""
    procs: dict[str, subprocess.Popen] = field(default_factory=dict)
    endpoints: dict[str, object] = field(default_factory=dict)
    solver_backend: str = ""  # scraped when the solver owns an accelerator

    def alive(self, component: str) -> bool:
        proc = self.procs.get(component)
        return proc is not None and proc.poll() is None


from ..localup import scrape_line as _scrape, spawn_child as _spawn


@dataclass
class ComponentHealth:
    """Per-component supervision state (the CrashLoopBackOff analogue:
    Kubernetes' kubelet applies exponential backoff to a container that
    keeps dying; the reference operator inherits that for free from the
    Deployments it renders — this build supplies it directly)."""

    restarts: int = 0  # lifetime restart count (surfaced on the CR)
    recent: list = field(default_factory=list)  # restart times in window
    backoff: float = 0.0  # current backoff seconds (0 = none)
    backoff_until: float = 0.0  # monotonic deadline; dead waits until then
    last_restart: float = 0.0

    def reset(self) -> None:
        self.recent.clear()
        self.backoff = 0.0
        self.backoff_until = 0.0


def _stop(proc: Optional[subprocess.Popen], grace: float = 5.0) -> None:
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=grace)


class ProcessKarmadaOperator:
    """Reconciles Karmada CRs into multi-process deployments."""

    def __init__(
        self,
        checkpoint_interval: float = 15.0,
        backoff_initial: float = 1.0,
        backoff_max: float = 30.0,
        storm_window: float = 30.0,
        storm_cap: int = 5,
    ) -> None:
        self.instances: dict[str, ProcessInstance] = {}
        self._applied_specs: dict[str, KarmadaSpec] = {}
        self.checkpoint_interval = checkpoint_interval
        # supervision policy: first death restarts immediately; repeat
        # deaths wait an exponentially growing backoff (doubling to
        # backoff_max); more than storm_cap restarts inside storm_window
        # is a CRASH LOOP — restarts continue at max backoff and the CR
        # reports ComponentsHealthy=False/CrashLoopBackOff
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.storm_window = storm_window
        self.storm_cap = storm_cap
        self._health: dict[tuple[str, str], ComponentHealth] = {}
        import threading

        self._lock = threading.RLock()  # reconcile vs watchdog sweeps

    # -- public ------------------------------------------------------------

    def reconcile(self, karmada: Karmada) -> ProcessInstance:
        with self._lock:
            return self._reconcile_locked(karmada)

    def _reconcile_locked(self, karmada: Karmada) -> ProcessInstance:
        name = karmada.meta.name
        fresh = name not in self.instances
        job = (
            self._init_job(karmada) if fresh else self._upgrade_job(karmada)
        )
        karmada.status.failed_task = ""
        try:
            job.run()
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=True, reason="Completed"),
            )
            karmada.status.installed_version = karmada.spec.version
            karmada.status.observed_generation = karmada.meta.generation
            self._applied_specs[name] = _spec_copy(karmada.spec)
        except Exception as e:
            karmada.status.failed_task = getattr(e, "task_name", "")
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=False, reason="TaskFailed",
                          message=str(e)),
            )
            if fresh:
                inst = self.instances.pop(name, None)
                if inst is not None:
                    self._teardown(inst)
            raise
        finally:
            karmada.status.completed_tasks = list(job.completed)
        return self.instances[name]

    def supervise(self, karmada: Karmada) -> list[str]:
        """One supervision sweep: restart any dead component of an
        installed instance at its PINNED endpoint, under the crash-loop
        policy (exponential backoff per component, restart-storm cap
        surfaced on the CR). The plane restarts from its latest periodic
        checkpoint; gRPC clients (RemoteSolver, estimator connections,
        StoreReplica agents) reconnect to the pinned ports on their own —
        the solver's snapshot-version fencing re-syncs cluster state on
        the first post-restart schedule. Returns the component names
        restarted this sweep (a component inside its backoff window stays
        down and is NOT in the list). ``Supervisor`` wraps this in a
        watchdog thread."""
        with self._lock:
            return self._supervise_locked(karmada)

    def _supervise_locked(self, karmada: Karmada) -> list[str]:
        name = karmada.meta.name
        inst = self.instances.get(name)
        if inst is None:
            return []
        now = time.monotonic()
        data = {"karmada": karmada}
        restarted: list[str] = []
        starters = {
            "webhook": self._start_webhook,
            "solver": self._start_solver,
            "estimator": self._start_estimator,
            "plane": self._start_plane,
        }
        for comp, proc in list(inst.procs.items()):
            h = self._health.setdefault((name, comp), ComponentHealth())
            if proc.poll() is None:
                # alive past the storm window: forgive the history so a
                # one-off crash next month starts from a fresh backoff
                if h.backoff and now - h.last_restart > self.storm_window:
                    h.reset()
                continue
            if now < h.backoff_until:
                continue  # backing off: stays down this sweep
            try:
                if comp.startswith("agent-"):
                    self._spawn_agent(inst, comp[len("agent-"):])
                else:
                    starters[comp](data)
                started = True
            except Exception:
                # a FAILED restart attempt (child died during startup,
                # scrape timeout) must still advance the backoff — or the
                # watchdog would hot-loop respawns with no cap at all
                started = False
            # the backoff clock starts when the restart attempt COMPLETES:
            # child startup (imports, port scrape) can take many seconds,
            # and a deadline anchored at sweep start would be expired
            t_done = time.monotonic()
            h.restarts += 1
            h.last_restart = t_done
            h.recent = [
                t for t in h.recent if t_done - t <= self.storm_window
            ] + [t_done]
            h.backoff = min(
                self.backoff_max,
                h.backoff * 2 if h.backoff else self.backoff_initial,
            )
            h.backoff_until = t_done + h.backoff
            if started:
                restarted.append(comp)
        self._surface_health(karmada, now)
        if restarted:
            self._wait_ready(data)
        return restarted

    def _surface_health(self, karmada: Karmada, now: float) -> None:
        """Crash-loop status on the Karmada CR (the reference surfaces
        component failures as Karmada CR conditions via its controller;
        operator/pkg/controller/karmada condition plumbing)."""
        name = karmada.meta.name
        inst = self.instances.get(name)
        karmada.status.component_restarts = {
            comp: h.restarts
            for (n, comp), h in self._health.items()
            if n == name and h.restarts
        }
        # crash loop = storm_cap exceeded inside the window OR the backoff
        # has been driven to its max (with doubling backoff the window can
        # physically hold only ~storm_cap restarts, so max-backoff is the
        # steady-state signature of a perpetually dying component)
        looping = sorted(
            comp
            for (n, comp), h in self._health.items()
            if n == name
            and (
                len([t for t in h.recent if now - t <= self.storm_window])
                > self.storm_cap
                or (h.backoff >= self.backoff_max and h.recent)
            )
        )
        dead = sorted(
            comp
            for comp in (inst.procs if inst else {})
            if not inst.alive(comp)
        )
        if looping:
            msgs = []
            for comp in looping:
                h = self._health[(name, comp)]
                msgs.append(
                    f"{comp}: {h.restarts} restarts "
                    f"({len(h.recent)} in {self.storm_window:.0f}s), "
                    f"backoff {h.backoff:.1f}s"
                )
            set_condition(
                karmada.status.conditions,
                Condition(
                    type="ComponentsHealthy", status=False,
                    reason="CrashLoopBackOff", message="; ".join(msgs),
                ),
            )
        elif dead:
            # down but not yet looping: waiting out a backoff window
            set_condition(
                karmada.status.conditions,
                Condition(
                    type="ComponentsHealthy", status=False,
                    reason="BackOff", message=", ".join(dead) + " down",
                ),
            )
        else:
            set_condition(
                karmada.status.conditions,
                Condition(
                    type="ComponentsHealthy", status=True, reason="AllAlive"
                ),
            )

    def deinit(self, karmada: Karmada) -> None:
        inst = self.instances.pop(karmada.meta.name, None)
        self._applied_specs.pop(karmada.meta.name, None)
        if inst is not None:
            self._teardown(inst)
        set_condition(
            karmada.status.conditions,
            Condition(type="Ready", status=False, reason="Removed"),
        )

    def _teardown(self, inst: ProcessInstance) -> None:
        # reverse start order: agents, plane, sidecars, webhook
        for comp in reversed(list(inst.procs)):
            _stop(inst.procs[comp])
        if inst.pki_dir and os.path.isdir(inst.pki_dir):
            shutil.rmtree(inst.pki_dir, ignore_errors=True)

    # -- init pipeline -----------------------------------------------------

    def _init_job(self, karmada: Karmada) -> Job:
        karmada_spec = karmada.spec
        return Job(
            tasks=[
                Task(name="validate", run=self._validate),
                Task(name="certs", run=self._certs),
                Task(
                    name="webhook", run=self._start_webhook,
                    skip=lambda d: not karmada_spec.components.webhook.enabled,
                ),
                Task(name="solver", run=self._start_solver),
                Task(
                    name="estimator", run=self._start_estimator,
                    skip=lambda d: not karmada_spec.components.estimators.enabled,
                ),
                Task(name="control-plane", run=self._start_plane),
                Task(name="agents", run=self._start_agents),
                Task(name="wait-ready", run=self._wait_ready),
            ],
            data={"karmada": karmada},
        )

    def _instance(self, data: dict) -> ProcessInstance:
        karmada = data["karmada"]
        inst = self.instances.get(karmada.meta.name)
        if inst is None:
            inst = ProcessInstance(name=karmada.meta.name)
            self.instances[karmada.meta.name] = inst
        return inst

    def _validate(self, data: dict) -> None:
        karmada = data["karmada"]
        validate_version_skew(karmada.spec.version, karmada.spec.components)
        self._instance(data)

    def _certs(self, data: dict) -> None:
        """operator/pkg/tasks/init cert task: a real self-signed PKI for
        the instance's TLS surfaces (admission webhook)."""
        inst = self._instance(data)
        inst.pki_dir = tempfile.mkdtemp(prefix=f"karmada-pki-{inst.name}-")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", os.path.join(inst.pki_dir, "webhook.key"),
             "-out", os.path.join(inst.pki_dir, "webhook.crt"),
             "-days", "3650", "-subj", "/CN=localhost",
             "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
            check=True, capture_output=True,
        )

    def _start_webhook(self, data: dict) -> None:
        inst = self._instance(data)
        # pinned on restart: the live plane's RemoteAdmission keeps dialing
        # the URL it was constructed with
        prev = str(inst.endpoints.get("webhook", ""))
        port = prev.rsplit(":", 1)[-1].split("/")[0] if prev else "0"
        proc = _spawn(
            [sys.executable, "-m", "karmada_tpu.webhook.server",
             "--address", f"127.0.0.1:{port}",
             "--certfile", os.path.join(inst.pki_dir, "webhook.crt"),
             "--keyfile", os.path.join(inst.pki_dir, "webhook.key")]
        )
        inst.procs["webhook"] = proc
        port = _scrape(proc, r"listening on port (\d+)")
        inst.endpoints["webhook"] = f"https://127.0.0.1:{port}/admit"

    def _start_solver(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        platform = karmada.spec.components.solver.platform or "cpu"
        port = inst.endpoints.get("solver", 0)  # pinned on restart
        cmd = [sys.executable, "-m", "karmada_tpu.solver",
               "--address", f"127.0.0.1:{port}"]
        if platform != "cpu":
            cmd.append("--report-backend")
        proc = _spawn(cmd, platform=platform)
        inst.procs["solver"] = proc
        inst.endpoints["solver"] = int(_scrape(proc, r"port (\d+)"))
        if platform != "cpu":
            # confirm the sidecar actually owns the accelerator — a tunnel
            # that fell back to CPU silently would fake the deployment
            # shape. Long timeout: a predecessor's unclean exit can hold
            # the single-client grant for minutes (see localup.py)
            inst.solver_backend = _scrape(
                proc, r"solver backend (\S+)", timeout=600.0
            )

    def _start_estimator(self, data: dict) -> None:
        inst = self._instance(data)
        port = inst.endpoints.get("estimator", 0)  # pinned on restart
        proc = _spawn(
            [sys.executable, "-m", "karmada_tpu.estimator",
             "--cluster", "member1", "--address", f"127.0.0.1:{port}"]
        )
        inst.procs["estimator"] = proc
        inst.endpoints["estimator"] = int(_scrape(proc, r"port (\d+)"))

    def _plane_cmd(self, data: dict) -> list[str]:
        inst = self._instance(data)
        karmada = data["karmada"]
        spec = karmada.spec
        cmd = [
            sys.executable, "-m", "karmada_tpu.localup", "serve",
            "--members", str(max(1, len(spec.member_clusters) or 2)),
            "--state-file", os.path.join(inst.pki_dir, "store.ckpt"),
            "--checkpoint-interval", str(self.checkpoint_interval),
        ]
        # pinned surfaces on restart: agents / CLIs / supervision probes
        # keep their targets across plane replacements
        if "bus" in inst.endpoints:
            cmd += ["--bus-address", f"127.0.0.1:{inst.endpoints['bus']}"]
        if "proxy" in inst.endpoints:
            cmd += ["--proxy-address", f"127.0.0.1:{inst.endpoints['proxy']}"]
        if "metrics" in inst.endpoints:
            cmd += ["--metrics-address", f"127.0.0.1:{inst.endpoints['metrics']}"]
        for name in spec.pull_members:
            cmd += ["--pull", name]
        if "solver" in inst.endpoints:
            cmd += ["--solver", f"127.0.0.1:{inst.endpoints['solver']}"]
        if "estimator" in inst.endpoints:
            cmd += [
                "--estimator", f"member1=127.0.0.1:{inst.endpoints['estimator']}"
            ]
        if "webhook" in inst.endpoints:
            cmd += [
                "--admission", inst.endpoints["webhook"],
                "--admission-ca", os.path.join(inst.pki_dir, "webhook.crt"),
            ]
        if spec.components.descheduler.enabled:
            cmd += ["--descheduler"]
        gates = dict(spec.feature_gates)
        if gates:
            cmd += [
                "--feature-gates",
                ",".join(f"{k}={str(v).lower()}" for k, v in gates.items()),
            ]
        return cmd

    def _start_plane(self, data: dict) -> None:
        inst = self._instance(data)
        proc = _spawn(self._plane_cmd(data))
        inst.procs["plane"] = proc
        # anchor on a JSON object (json.dumps always opens with `{"`):
        # the child's stderr is merged into the scraped stream, and a
        # stray log line containing braces (grpc error reprs carry
        # `{grpc_status:...}`) must not masquerade as the endpoints line
        line = _scrape(proc, r"(\{\".*\})")
        info = json.loads(line)
        inst.endpoints.update(
            bus=info["bus"], proxy=info["proxy"], metrics=info["metrics"],
            clusters=info["clusters"],
        )

    def _spawn_agent(self, inst: ProcessInstance, name: str) -> None:
        inst.procs[f"agent-{name}"] = _spawn(
            [sys.executable, "-m", "karmada_tpu.bus.agent",
             "--target", f"127.0.0.1:{inst.endpoints['bus']}",
             "--cluster", name]
        )

    def _start_agents(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        for name in karmada.spec.pull_members:
            self._spawn_agent(inst, name)

    def _wait_ready(self, data: dict) -> None:
        inst = self._instance(data)
        deadline = time.time() + 30
        url = f"http://127.0.0.1:{inst.endpoints['metrics']}/healthz"
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.read() == b"ok\n":
                        return
            except Exception:
                time.sleep(0.2)
        raise RuntimeError("control plane never became healthy")

    # -- upgrade reconcile -------------------------------------------------

    def _upgrade_job(self, karmada: Karmada) -> Job:
        prev = self._applied_specs[karmada.meta.name]
        spec = karmada.spec
        tasks = [Task(name="validate", run=self._validate)]
        # ANY field consumed by _plane_cmd (or by the sidecars it points
        # at) that drifted forces a plane restart — a partial diff here
        # would silently diverge the deployment from the CR while
        # reporting Ready
        plane_restart = (
            spec.components.descheduler.enabled
            != prev.components.descheduler.enabled
            or spec.feature_gates != prev.feature_gates
            or spec.pull_members != prev.pull_members
            or spec.member_clusters != prev.member_clusters
            or spec.version != prev.version
        )
        if (
            spec.components.estimators.enabled
            != prev.components.estimators.enabled
        ):
            tasks.append(Task(name="estimator", run=self._toggle_estimator))
            plane_restart = True
        if (
            spec.components.webhook.enabled
            != prev.components.webhook.enabled
        ):
            tasks.append(Task(name="webhook", run=self._toggle_webhook))
            plane_restart = True
        if plane_restart:
            tasks.append(Task(name="control-plane", run=self._restart_plane))
            tasks.append(Task(name="agents", run=self._restart_agents))
        tasks.append(Task(name="wait-ready", run=self._wait_ready))
        return Job(tasks=tasks, data={"karmada": karmada})

    def _toggle_estimator(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        if karmada.spec.components.estimators.enabled:
            if not inst.alive("estimator"):
                self._start_estimator(data)
        else:
            _stop(inst.procs.pop("estimator", None))
            inst.endpoints.pop("estimator", None)

    def _toggle_webhook(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        if karmada.spec.components.webhook.enabled:
            if not inst.alive("webhook"):
                self._start_webhook(data)
        else:
            _stop(inst.procs.pop("webhook", None))
            inst.endpoints.pop("webhook", None)

    def _restart_plane(self, data: dict) -> None:
        inst = self._instance(data)
        _stop(inst.procs.pop("plane", None))
        self._start_plane(data)

    def _restart_agents(self, data: dict) -> None:
        inst = self._instance(data)
        karmada = data["karmada"]
        want = set(karmada.spec.pull_members)
        for comp in [c for c in inst.procs if c.startswith("agent-")]:
            _stop(inst.procs.pop(comp))
        for name in want:
            self._spawn_agent(inst, name)


class Supervisor:
    """Watchdog thread around ``ProcessKarmadaOperator.supervise``: the
    always-on Deployment-controller loop the reference gets from
    Kubernetes itself. Polls component liveness every ``interval``
    seconds, restarts dead components under the operator's backoff /
    crash-loop policy, and keeps the Karmada CR's ComponentsHealthy
    condition current. One Supervisor per CR; sweeps and reconciles share
    the operator's lock."""

    def __init__(
        self,
        operator: ProcessKarmadaOperator,
        karmada: Karmada,
        interval: float = 0.5,
    ) -> None:
        import threading

        self.operator = operator
        self.karmada = karmada
        self.interval = interval
        self.restarted_total: list[str] = []  # log of restart events
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Supervisor":
        import threading

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.restarted_total.extend(
                    self.operator.supervise(self.karmada)
                )
            except Exception:  # noqa: BLE001 — the watchdog must survive
                # a failed restart attempt (it retries next sweep; the
                # component's backoff keeps growing)
                pass
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
