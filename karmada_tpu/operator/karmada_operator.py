"""Karmada CR + operator reconciler over the workflow engine.

Ref: operator/pkg/apis/operator/v1alpha1/type.go:32 (Karmada CR with
per-component CommonSettings: image/version, replicas, featureGates,
extraArgs), operator/pkg/controller/karmada (reconciler),
operator/pkg/tasks/init (cert -> namespace -> etcd -> apiserver -> upload
-> karmadaresource -> rbac -> component -> wait pipeline) and tasks/deinit.
In-process the heavyweight phases collapse to component wiring, but the
task graph, phases, skip gates, status conditions, version-skew validation
and the UPGRADE reconcile (spec drift re-runs the pipeline with live
rewiring) keep the reference's shape so a remote installer can reuse the
engine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Optional

from ..api.core import Condition, ObjectMeta, set_condition
from .workflow import Job, Task, WorkflowError

OPERATOR_VERSION = "1.11.0"  # the control-plane version this build ships


@dataclass
class ComponentSpec:
    """Per-component settings (ref: CommonSettings — image/tag, replicas,
    featureGates, extraArgs; type.go:99-150).

    ``enabled``/``version``/``feature_gates`` are enforced by the in-proc
    reconciler (skew validation, component wiring, gate application);
    ``replicas`` and ``extra_args`` are deployment-shape fields a remote
    installer consumes when rendering real component Deployments — the
    in-proc runtime has no pods to scale or flags to pass."""

    enabled: bool = True
    version: str = OPERATOR_VERSION
    replicas: int = 1
    feature_gates: dict[str, bool] = field(default_factory=dict)
    extra_args: dict[str, str] = field(default_factory=dict)
    # jax backend the component's process runs on ("cpu" | "axon,cpu" |
    # "tpu"...). Only the solver sidecar should ever be non-cpu: the
    # accelerator is single-client per machine, and dedicating it to the
    # Score/Assign engine is the deployment shape docs/OPERATIONS.md
    # describes. Enforced by the process operator at spawn time.
    platform: str = "cpu"


@dataclass
class KarmadaComponents:
    scheduler: ComponentSpec = field(default_factory=ComponentSpec)
    # the solver sidecar (karmada_tpu.solver) — the component the
    # accelerator platform policy applies to
    solver: ComponentSpec = field(default_factory=ComponentSpec)
    controller_manager: ComponentSpec = field(default_factory=ComponentSpec)
    webhook: ComponentSpec = field(default_factory=ComponentSpec)
    descheduler: ComponentSpec = field(
        default_factory=lambda: ComponentSpec(enabled=False)
    )
    search: ComponentSpec = field(default_factory=ComponentSpec)
    metrics_adapter: ComponentSpec = field(default_factory=ComponentSpec)
    estimators: ComponentSpec = field(
        default_factory=lambda: ComponentSpec(enabled=False)
    )


@dataclass
class KarmadaSpec:
    version: str = OPERATOR_VERSION  # control-plane version (upgrade axis)
    components: KarmadaComponents = field(default_factory=KarmadaComponents)
    member_clusters: list[str] = field(default_factory=list)
    # pull-mode members whose agents run OUT of process (the process
    # operator spawns one karmada_tpu.bus.agent per name)
    pull_members: list[str] = field(default_factory=list)
    feature_gates: dict[str, bool] = field(default_factory=dict)


@dataclass
class KarmadaStatus:
    conditions: list[Condition] = field(default_factory=list)
    completed_tasks: list[str] = field(default_factory=list)
    failed_task: str = ""
    observed_generation: int = 0
    installed_version: str = ""
    # per-component lifetime restart counts from the process supervisor
    # (crash-loop visibility; the ComponentsHealthy condition carries the
    # CrashLoopBackOff reason + backoff detail)
    component_restarts: dict[str, int] = field(default_factory=dict)


@dataclass
class Karmada:
    KIND = "Karmada"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: KarmadaSpec = field(default_factory=KarmadaSpec)
    status: KarmadaStatus = field(default_factory=KarmadaStatus)


def _minor(version: str) -> tuple[int, int]:
    parts = (version.split("-")[0].lstrip("v").split(".") + ["0", "0"])[:2]
    try:
        return int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"unparseable version {version!r}")


def validate_version_skew(plane_version: str, components: KarmadaComponents) -> None:
    """Components may trail the control plane by at most one minor (the
    kube/karmada upgrade contract the reference's upgrade path enforces)."""
    pmaj, pmin = _minor(plane_version)
    for name in vars(components):
        comp: ComponentSpec = getattr(components, name)
        if not comp.enabled:
            continue
        cmaj, cmin = _minor(comp.version)
        if cmaj != pmaj or not (0 <= pmin - cmin <= 1):
            raise ValueError(
                f"component {name} version {comp.version} violates the "
                f"one-minor skew window against control plane {plane_version}"
            )


class KarmadaOperator:
    """Reconciles Karmada CRs into running ControlPlane instances.

    First reconcile runs the full init pipeline; subsequent reconciles
    diff the spec and apply the delta LIVE (component enable/disable,
    feature gates, member join/unjoin, version bump) — the reference's
    upgrade reconcile re-runs its init tasks idempotently the same way."""

    def __init__(self) -> None:
        self.instances: dict[str, object] = {}
        self._applied_specs: dict[str, KarmadaSpec] = {}

    # -- public ------------------------------------------------------------

    def reconcile(self, karmada: Karmada):
        name = karmada.meta.name
        fresh = name not in self.instances
        job = self._init_job(karmada) if fresh else self._upgrade_job(karmada)
        karmada.status.failed_task = ""
        try:
            job.run()
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=True, reason="Completed"),
            )
            karmada.status.installed_version = karmada.spec.version
            karmada.status.observed_generation = karmada.meta.generation
            self._applied_specs[name] = _spec_copy(karmada.spec)
        except WorkflowError as e:
            karmada.status.failed_task = e.task_name
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=False, reason="TaskFailed",
                          message=str(e)),
            )
            if fresh:
                # a half-built install must not masquerade as an upgradable
                # instance: the retry re-runs the init pipeline from scratch
                self.instances.pop(name, None)
            raise
        finally:
            karmada.status.completed_tasks = list(job.completed)
        return self.instances[karmada.meta.name]

    def deinit(self, karmada: Karmada) -> None:
        """tasks/deinit: tear the instance down (members unjoined first so
        their execution spaces drain, then the plane is dropped)."""
        cp = self.instances.pop(karmada.meta.name, None)
        prev = self._applied_specs.pop(karmada.meta.name, None)
        if prev is not None:
            # applied gates revert to defaults with the plane
            from ..utils.features import DEFAULTS, feature_gate

            reverts = dict(prev.feature_gates)
            for comp_name in vars(prev.components):
                reverts.update(getattr(prev.components, comp_name).feature_gates)
            for gate in reverts:
                if gate in DEFAULTS:
                    feature_gate.set(gate, DEFAULTS[gate])
        if cp is not None:
            for name in list(cp.members.names()):
                cp.unjoin_cluster(name)
        set_condition(
            karmada.status.conditions,
            Condition(type="Ready", status=False, reason="Removed"),
        )

    # -- init pipeline (ref: operator/pkg/tasks/init ordering) -------------

    def _init_job(self, karmada: Karmada) -> Job:
        comps = karmada.spec.components
        job = Job(data={"karmada": karmada, "operator": self})
        job.append_task(Task(name="validate", run=self._validate))
        job.append_task(Task(name="prepare-certs", run=self._prepare_certs))
        job.append_task(Task(name="state-store", run=self._state_store))
        job.append_task(
            Task(
                name="control-plane-components",
                run=self._components,
                tasks=[
                    Task(
                        name="descheduler",
                        skip=lambda d: not comps.descheduler.enabled,
                        run=self._enable_descheduler,
                    ),
                    Task(
                        name="estimators",
                        skip=lambda d: not comps.estimators.enabled,
                        run=self._enable_estimators,
                    ),
                ],
            )
        )
        job.append_task(Task(name="feature-gates", run=self._feature_gates))
        job.append_task(Task(name="join-members", run=self._join_members))
        job.append_task(Task(name="wait-ready", run=self._wait_ready))
        return job

    # -- upgrade pipeline (spec drift -> live delta) -----------------------

    def _upgrade_job(self, karmada: Karmada) -> Job:
        prev = self._applied_specs.get(karmada.meta.name)
        job = Job(data={"karmada": karmada, "operator": self,
                        "control_plane": self.instances[karmada.meta.name],
                        "previous": prev})
        job.append_task(Task(name="validate", run=self._validate))
        job.append_task(
            Task(
                name="upgrade-version",
                skip=lambda d: prev is not None
                and prev.version == karmada.spec.version,
                run=self._upgrade_version,
            )
        )
        job.append_task(
            Task(name="reconcile-components", run=self._reconcile_components)
        )
        job.append_task(Task(name="feature-gates", run=self._feature_gates))
        job.append_task(Task(name="reconcile-members", run=self._reconcile_members))
        job.append_task(Task(name="wait-ready", run=self._wait_ready))
        return job

    # -- tasks -------------------------------------------------------------

    def _validate(self, data: dict) -> None:
        karmada: Karmada = data["karmada"]
        validate_version_skew(karmada.spec.version, karmada.spec.components)

    def _prepare_certs(self, data: dict) -> None:
        # in-proc transport needs no PKI; record the intent for parity with
        # the reference's cert task (operator/pkg/tasks/init/cert.go)
        data["certs"] = {"ca": "in-process", "issued_at": time.time()}

    def _state_store(self, data: dict) -> None:
        from ..controlplane import ControlPlane

        karmada: Karmada = data["karmada"]
        cp = ControlPlane(
            enable_descheduler=False,
            enable_accurate_estimator=karmada.spec.components.estimators.enabled,
        )
        data["control_plane"] = cp
        self.instances[karmada.meta.name] = cp

    def _components(self, data: dict) -> None:
        # controllers are wired by ControlPlane construction; nothing extra
        pass

    def _enable_descheduler(self, data: dict) -> None:
        from ..controllers import Descheduler

        cp = data["control_plane"]
        if getattr(cp, "descheduler", None) is None:
            cp.descheduler = Descheduler(
                cp.store, cp.runtime, cp.members, clock=cp.clock
            )
        # the ticker registration is permanent: re-enable must flip the
        # in-place instance, never construct a second one (double ticks)
        cp.descheduler.active = True

    def _disable_descheduler(self, cp) -> None:
        desch = getattr(cp, "descheduler", None)
        if desch is not None:
            # deactivate in place (cli.cmd_addons pattern): dropping the
            # reference alone would leave the registered ticker reclaiming
            desch.active = False

    def _enable_estimators(self, data: dict) -> None:
        cp = data["control_plane"]
        if hasattr(cp, "enable_accurate_estimators"):
            cp.enable_accurate_estimators()

    def _feature_gates(self, data: dict) -> None:
        """Apply the spec's gates and REVERT gates dropped from the spec to
        their defaults (a removed key must not stay latched). NOTE the gate
        registry is process-global (utils/features singleton): in-proc
        planes under one operator share it, matching the one-process
        deployment shape; a multi-plane operator host runs planes in
        separate processes (the reference's one-binary-set-per-plane)."""
        from ..utils.features import DEFAULTS, feature_gate

        karmada: Karmada = data["karmada"]
        prev: Optional[KarmadaSpec] = data.get("previous")
        def gates_of(spec: KarmadaSpec) -> dict[str, bool]:
            # plane-level gates, overridden by per-component gates (the
            # per-binary --feature-gates flags of the reference collapse
            # onto one in-proc registry; component-specific values win)
            merged = dict(spec.feature_gates)
            for comp_name in vars(spec.components):
                merged.update(getattr(spec.components, comp_name).feature_gates)
            return merged

        want = gates_of(karmada.spec)
        for gate in (gates_of(prev) if prev else {}):
            if gate not in want and gate in DEFAULTS:
                feature_gate.set(gate, DEFAULTS[gate])
        for gate, value in want.items():
            feature_gate.set(gate, value)

    def _join_members(self, data: dict) -> None:
        from ..utils.builders import new_cluster

        karmada: Karmada = data["karmada"]
        cp = data["control_plane"]
        for name in karmada.spec.member_clusters:
            cp.join_cluster(new_cluster(name))

    def _upgrade_version(self, data: dict) -> None:
        """Version bump: the in-proc analogue of rolling the component
        deployments to the new image — the skew window was validated, so
        unpinned components (those that tracked the old plane version)
        follow the plane to the new one."""
        karmada: Karmada = data["karmada"]
        prev: Optional[KarmadaSpec] = data.get("previous")
        for name in vars(karmada.spec.components):
            comp: ComponentSpec = getattr(karmada.spec.components, name)
            if prev is not None:
                prev_comp = getattr(prev.components, name)
                if comp.version == prev_comp.version == prev.version:
                    comp.version = karmada.spec.version

    def _reconcile_components(self, data: dict) -> None:
        karmada: Karmada = data["karmada"]
        prev: Optional[KarmadaSpec] = data.get("previous")
        cp = data["control_plane"]
        comps = karmada.spec.components
        prev_comps = prev.components if prev else KarmadaComponents()
        if comps.descheduler.enabled and not prev_comps.descheduler.enabled:
            self._enable_descheduler(data)
        elif not comps.descheduler.enabled and prev_comps.descheduler.enabled:
            self._disable_descheduler(cp)
        if comps.estimators.enabled and not prev_comps.estimators.enabled:
            self._enable_estimators(data)

    def _reconcile_members(self, data: dict) -> None:
        from ..utils.builders import new_cluster

        karmada: Karmada = data["karmada"]
        cp = data["control_plane"]
        want = set(karmada.spec.member_clusters)
        have = set(cp.members.names())
        for name in sorted(want - have):
            cp.join_cluster(new_cluster(name))
        for name in sorted(have - want):
            cp.unjoin_cluster(name)

    def _wait_ready(self, data: dict) -> None:
        cp = data["control_plane"]
        cp.settle()
        karmada: Karmada = data["karmada"]
        for name in karmada.spec.member_clusters:
            cluster = cp.store.get("Cluster", name)
            ready = cluster is not None and any(
                c.type == "Ready" and c.status for c in cluster.status.conditions
            )
            if not ready:
                raise RuntimeError(f"cluster {name} not ready")


def _spec_copy(spec: KarmadaSpec) -> KarmadaSpec:
    comps = KarmadaComponents(
        **{
            name: replace(
                getattr(spec.components, name),
                feature_gates=dict(getattr(spec.components, name).feature_gates),
                extra_args=dict(getattr(spec.components, name).extra_args),
            )
            for name in vars(spec.components)
        }
    )
    return KarmadaSpec(
        version=spec.version,
        components=comps,
        member_clusters=list(spec.member_clusters),
        pull_members=list(spec.pull_members),
        feature_gates=dict(spec.feature_gates),
    )
