"""Karmada CR + operator reconciler over the workflow engine.

Ref: operator/pkg/apis/operator/v1alpha1/type.go:32 (Karmada CR) and
operator/pkg/controller/karmada (reconciler) + operator/pkg/tasks/init
(cert -> etcd -> apiserver -> CRDs -> components -> wait pipeline) and
tasks/deinit. In-process the heavyweight phases collapse to component
wiring, but the task graph, phases, skip gates and status conditions keep
the reference's shape so a remote installer can reuse the engine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..api.core import Condition, ObjectMeta, set_condition
from .workflow import Job, Task, WorkflowError


@dataclass
class KarmadaComponents:
    scheduler: bool = True
    controller_manager: bool = True
    webhook: bool = True
    descheduler: bool = False
    search: bool = True
    metrics_adapter: bool = True
    estimators: bool = False


@dataclass
class KarmadaSpec:
    components: KarmadaComponents = field(default_factory=KarmadaComponents)
    member_clusters: list[str] = field(default_factory=list)


@dataclass
class KarmadaStatus:
    conditions: list[Condition] = field(default_factory=list)
    completed_tasks: list[str] = field(default_factory=list)


@dataclass
class Karmada:
    KIND = "Karmada"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: KarmadaSpec = field(default_factory=KarmadaSpec)
    status: KarmadaStatus = field(default_factory=KarmadaStatus)


class KarmadaOperator:
    """Reconciles Karmada CRs into running ControlPlane instances."""

    def __init__(self) -> None:
        self.instances: dict[str, object] = {}

    def reconcile(self, karmada: Karmada):
        job = self._init_job(karmada)
        try:
            job.run()
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=True, reason="Completed"),
            )
        except WorkflowError as e:
            set_condition(
                karmada.status.conditions,
                Condition(type="Ready", status=False, reason="TaskFailed",
                          message=str(e)),
            )
            raise
        finally:
            karmada.status.completed_tasks = list(job.completed)
        return self.instances[karmada.meta.name]

    def deinit(self, karmada: Karmada) -> None:
        """tasks/deinit: tear the instance down."""
        cp = self.instances.pop(karmada.meta.name, None)
        if cp is not None:
            for name in list(cp.members.names()):
                cp.unjoin_cluster(name)
        set_condition(
            karmada.status.conditions,
            Condition(type="Ready", status=False, reason="Removed"),
        )

    # -- init pipeline (ref: operator/pkg/tasks/init ordering) -------------

    def _init_job(self, karmada: Karmada) -> Job:
        job = Job(data={"karmada": karmada, "operator": self})
        job.append_task(Task(name="prepare-certs", run=self._prepare_certs))
        job.append_task(Task(name="state-store", run=self._state_store))
        job.append_task(
            Task(
                name="control-plane-components",
                run=self._components,
                tasks=[
                    Task(
                        name="descheduler",
                        skip=lambda d: not karmada.spec.components.descheduler,
                        run=self._enable_descheduler,
                    ),
                ],
            )
        )
        job.append_task(Task(name="join-members", run=self._join_members))
        job.append_task(Task(name="wait-ready", run=self._wait_ready))
        return job

    def _prepare_certs(self, data: dict) -> None:
        # in-proc transport needs no PKI; record the intent for parity with
        # the reference's cert task (operator/pkg/tasks/init/cert.go)
        data["certs"] = {"ca": "in-process", "issued_at": time.time()}

    def _state_store(self, data: dict) -> None:
        from ..controlplane import ControlPlane

        karmada: Karmada = data["karmada"]
        cp = ControlPlane(
            enable_descheduler=False,
            enable_accurate_estimator=karmada.spec.components.estimators,
        )
        data["control_plane"] = cp
        self.instances[karmada.meta.name] = cp

    def _components(self, data: dict) -> None:
        # controllers are wired by ControlPlane construction; nothing extra
        pass

    def _enable_descheduler(self, data: dict) -> None:
        from ..controllers import Descheduler

        cp = data["control_plane"]
        cp.descheduler = Descheduler(cp.store, cp.runtime, cp.members, clock=cp.clock)

    def _join_members(self, data: dict) -> None:
        from ..utils.builders import new_cluster

        karmada: Karmada = data["karmada"]
        cp = data["control_plane"]
        for name in karmada.spec.member_clusters:
            cp.join_cluster(new_cluster(name))

    def _wait_ready(self, data: dict) -> None:
        cp = data["control_plane"]
        cp.settle()
        karmada: Karmada = data["karmada"]
        for name in karmada.spec.member_clusters:
            cluster = cp.store.get("Cluster", name)
            ready = cluster is not None and any(
                c.type == "Ready" and c.status for c in cluster.status.conditions
            )
            if not ready:
                raise RuntimeError(f"cluster {name} not ready")
