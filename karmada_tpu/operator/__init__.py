"""Operator: declarative install/upgrade of a control plane from a CR.

Ref: operator/ (21.5k LoC) — a `Karmada` CR (operator/pkg/apis/operator/
v1alpha1/type.go:32) reconciled through a workflow engine of init/deinit
tasks (operator/pkg/workflow/{job,task}.go, operator/pkg/tasks/{init,deinit}).
Here the artifact being installed is the in-process ControlPlane; the
workflow engine is generic (ordered tasks with sub-tasks, run-data bag,
failure propagation) and the init pipeline mirrors the reference's
certs -> etcd -> apiserver -> components -> wait sequence at the granularity
that exists in-process.
"""

from .workflow import Job, Task, WorkflowError  # noqa: F401
from .karmada_operator import Karmada, KarmadaOperator, KarmadaSpec  # noqa: F401
