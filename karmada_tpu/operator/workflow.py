"""Workflow engine: ordered tasks with nested sub-tasks and a shared
run-data bag (ref: operator/pkg/workflow/job.go + task.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class WorkflowError(Exception):
    def __init__(self, task_name: str, cause: Exception):
        super().__init__(f"task {task_name!r} failed: {cause}")
        self.task_name = task_name
        self.cause = cause


@dataclass
class Task:
    name: str
    run: Optional[Callable[[dict], None]] = None
    # skip gate: returns True to skip this task (and its children)
    skip: Optional[Callable[[dict], bool]] = None
    tasks: list["Task"] = field(default_factory=list)
    run_sub_tasks: bool = True


@dataclass
class Job:
    """Executes tasks depth-first in declaration order; the ``data`` dict is
    the RunData every task shares. Failure aborts the job (the reference's
    workflow halts and surfaces the failed task)."""

    tasks: list[Task] = field(default_factory=list)
    data: dict[str, Any] = field(default_factory=dict)
    completed: list[str] = field(default_factory=list)

    def append_task(self, task: Task) -> None:
        self.tasks.append(task)

    def run(self) -> None:
        for task in self.tasks:
            self._run_task(task)

    def _run_task(self, task: Task) -> None:
        if task.skip is not None and task.skip(self.data):
            return
        if task.run is not None:
            try:
                task.run(self.data)
            except Exception as e:  # noqa: BLE001 — workflow surfaces any failure
                raise WorkflowError(task.name, e) from e
        self.completed.append(task.name)
        if task.run_sub_tasks:
            for sub in task.tasks:
                self._run_task(sub)
