"""Pure-Python oracle of reference scheduling semantics (test baseline)."""

from .divider import (  # noqa: F401
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    MAX_INT32,
    STATIC_WEIGHT,
    STRATEGY_NAMES,
    DivisionProblem,
    UnschedulableError,
    assign_replicas,
    merge_estimates,
    take_by_weight,
)
