"""Pure-Python spread-constraint selection oracle.

Independent re-execution of the reference's SelectClusters stage
(pkg/scheduler/core/spreadconstraint/) for verification: given one
binding's feasible clusters with scores and credited availability, return
the selected cluster indices exactly as the reference would — so the
engine's config-4 placements (SpreadConstraint region+cluster over
synthetic fleets) can be checked end to end, not just for conservation
(VERDICT r3 item 8).

Implemented per the reference semantics:
- group score (group_clusters.go:138-330): Duplicated counts clusters
  covering the full replica count at 1000x weight; Divided walks the
  score-ordered members until cluster-min-groups and
  ceil(replicas/region-min-groups) are both covered.
- selectGroups DFS (select_groups.go:102-224): region combinations whose
  total cluster count reaches the cluster min-groups, path length in
  [minGroups, maxGroups]; paths ranked weight desc / value desc /
  discovery order; a shorter path that is a prefix of the winner is
  preferred.
- region assembly (select_clusters_by_region.go:28-70): the best cluster
  of every chosen region, remainder filled by (score desc, avail desc)
  up to the cluster max-groups.
- cluster-only constraint (select_clusters_by_cluster.go:26-99): top
  max-groups by order with availability swap-repair from the remainder.

This module deliberately shares NO code with karmada_tpu.scheduler.spread /
groups (the engine path): plain dicts and lists, per-binding.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

WEIGHT_UNIT = 1000
INVALID_REPLICAS = -1


def cluster_order(
    candidates: Sequence[int],
    score: dict[int, int],
    credited: dict[int, int],
) -> list[int]:
    """(score desc, credited desc, index asc) — spreadconstraint/util.go."""
    return sorted(
        candidates, key=lambda j: (-score.get(j, 0), -credited.get(j, 0), j)
    )


def group_score(
    members: Sequence[int],
    score: dict[int, int],
    credited: dict[int, int],
    duplicated: bool,
    replicas: int,
    region_min_groups: int,
    cluster_min_groups: int,
) -> int:
    if duplicated:
        valid = [j for j in members if credited.get(j, 0) >= replicas]
        if not valid:
            return 0
        return len(valid) * WEIGHT_UNIT + sum(
            score.get(j, 0) for j in valid
        ) // len(valid)
    target = math.ceil(replicas / max(region_min_groups, 1))
    min_count = max(cluster_min_groups, region_min_groups)
    s_avail = s_score = taken = 0
    for j in members:
        s_avail += credited.get(j, 0)
        s_score += score.get(j, 0)
        taken += 1
        if taken >= min_count and s_avail >= target:
            break
    if s_avail < target:
        return s_avail * WEIGHT_UNIT + s_score // max(len(members), 1)
    return target * WEIGHT_UNIT + s_score // max(taken, 1)


def select_region_groups(
    groups: list[tuple[str, int, int]],  # (name, n_clusters, weight)
    min_groups: int,
    max_groups: int,
    cluster_min: int,
) -> list[str]:
    """DFS + prioritization; returns chosen region names ([] = FitError)."""
    if not groups:
        return []
    if max_groups <= 0:
        max_groups = len(groups)
    # DFS enumeration order: clusters asc, weight desc, name asc
    ordered = sorted(groups, key=lambda g: (g[1], -g[2], g[0]))
    paths: list[tuple[list[tuple[str, int, int]], int, int, int]] = []
    stack: list[tuple[str, int, int]] = []
    seq = [0]

    def dfs(total: int, begin: int) -> None:
        if total >= cluster_min and min_groups <= len(stack) <= max_groups:
            seq[0] += 1
            chosen = sorted(stack, key=lambda g: (-g[2], g[0]))
            paths.append(
                (
                    list(chosen),
                    sum(g[2] for g in chosen),
                    sum(g[1] for g in chosen),
                    seq[0],
                )
            )
            return
        if len(stack) >= max_groups:
            return
        for i in range(begin, len(ordered)):
            stack.append(ordered[i])
            dfs(total + ordered[i][1], i + 1)
            if len(ordered) == min_groups:
                return  # select_groups.go:180-182 early-out
            stack.pop()

    dfs(0, 0)
    if not paths:
        return []
    paths.sort(key=lambda p: (-p[1], -p[2], p[3]))
    best = paths[0]
    for cand in paths[1:]:
        if len(cand[0]) < len(best[0]) and all(
            best[0][i][0] == g[0] for i, g in enumerate(cand[0])
        ):
            best = cand
    return [g[0] for g in best[0]]


def select_spread_clusters(
    candidates: Sequence[int],  # feasible cluster indices
    region_of: dict[int, str],  # cluster index -> region name ("" = none)
    score: dict[int, int],
    credited: dict[int, int],
    constraints: dict[str, tuple[int, int]],  # field -> (min, max)
    replicas: int,
    duplicated: bool,
) -> Optional[list[int]]:
    """Returns the selected cluster indices or None (FitError)."""
    need = INVALID_REPLICAS if duplicated else replicas
    order = cluster_order(candidates, score, credited)

    if "region" in constraints:
        r_min, r_max = constraints["region"]
        c_min, c_max = constraints.get("cluster", (0, 0))
        regions: dict[str, list[int]] = {}
        for j in order:
            name = region_of.get(j, "")
            if name:
                regions.setdefault(name, []).append(j)
        if len(regions) < max(r_min, 1):
            return None
        groups = [
            (
                name,
                len(members),
                group_score(
                    members, score, credited, duplicated, replicas,
                    r_min, c_min,
                ),
            )
            for name, members in regions.items()
        ]
        chosen = select_region_groups(groups, r_min, r_max, c_min)
        if not chosen:
            return None
        selected = [regions[name][0] for name in chosen]
        rest = [j for name in chosen for j in regions[name][1:]]
        want = len(selected) + len(rest)
        if want > c_max:
            want = c_max
        extra = want - len(selected)
        if extra > 0:
            rest.sort(
                key=lambda j: (-score.get(j, 0), -credited.get(j, 0), j)
            )
            selected.extend(rest[:extra])
        return selected

    if "cluster" in constraints:
        c_min, c_max = constraints["cluster"]
        total = len(order)
        if total < max(c_min, 1):
            return None
        cap = c_max if c_max > 0 else total
        keep = list(order[: min(cap, total)])
        rest = list(order[min(cap, total):])
        if need == INVALID_REPLICAS:
            return keep
        idx = len(keep) - 1
        while sum(credited.get(j, 0) for j in keep) < need and idx >= 0:
            if rest:
                b = max(range(len(rest)), key=lambda k: credited.get(rest[k], 0))
                if credited.get(rest[b], 0) > credited.get(keep[idx], 0):
                    keep[idx], rest[b] = rest[b], keep[idx]
                    idx -= 1
                    continue
            idx -= 1
        if sum(credited.get(j, 0) for j in keep) < need:
            return None
        return keep

    # zone/provider-only: unsupported upstream (select_clusters.go:58)
    return None
