"""Numpy preemption oracle: sequential victim selection and drift
rebalance, the reference way (ISSUE 14's identity referent).

``ops.preempt.preempt_select`` claims the plane-wide selection rule as
one sort + prefix-cumsum tensor op. This module IS that rule as a
reference controller would write it: walk candidate victims one at a
time in (priority asc, displacement-weight desc, arrival) order,
maintain per-priority-class UNMET demand explicitly, evict a victim iff
some resource dim it frees still has unmet demand from a class strictly
above its own, and credit the freed capacity to the highest unmet class
first. No shared selection code with the kernel — a drift in the
kernel's sort/scan algebra shows up as an oracle mismatch, not a shared
bug (the ``refimpl/quota_np.py`` / ``refimpl/failover_np.py``
discipline).

``preempt_and_place_np`` composes selection with the per-binding numpy
divider so a whole scarcity wave verifies end to end: demanders re-solve
against availability boosted by the freed per-cluster capacity, exactly
like the engine's same-pass re-entry.

``rebalance_np`` is the continuous-descheduler oracle: per binding,
compute the fresh-solve ideal placement with the one-row numpy divider,
score drift as the L1 replica distance from the resident placement, and
take the top ``budget`` rows (drift desc, arrival asc) — the bounded-
disruption trigger set the controller must match exactly.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from .divider_np import assign_batch_np

MAX_INT32 = 2**31 - 1


def select_victims_np(
    prios: Sequence[int],  # per-binding priority class
    demand: np.ndarray,  # int64[B, R] unmet demand (0 for non-demanders)
    freed: np.ndarray,  # int64[B, R] capacity a victim would free
    victim_ok: Sequence[bool],  # eligible victim
    weights: Sequence[int],  # displacement weight (assigned replicas)
) -> list[bool]:
    """Sequential victim selection: returns the per-row victim flags."""
    demand = np.asarray(demand)
    freed = np.asarray(freed)
    b, r = demand.shape
    # unmet demand per priority class, highest class first
    unmet: dict[int, np.ndarray] = {}
    for i in range(b):
        d = demand[i]
        if d.any():
            q = int(prios[i])
            unmet[q] = unmet.get(q, np.zeros(r, np.int64)) + d
    order = sorted(
        (i for i in range(b) if victim_ok[i]),
        key=lambda i: (int(prios[i]), -int(weights[i]), i),
    )
    victims = [False] * b
    for v in order:
        qv = int(prios[v])
        above = sorted((q for q in unmet if q > qv), reverse=True)
        take = False
        for d in range(r):
            if freed[v, d] <= 0:
                continue
            if any(unmet[q][d] > 0 for q in above):
                take = True
                break
        if not take:
            continue
        victims[v] = True
        # credit the freed capacity to the highest unmet class first,
        # dim by dim (capacity is fungible once freed; crediting top-
        # down mirrors the wave's priority-descending solve order)
        for d in range(r):
            left = int(freed[v, d])
            for q in above:
                if left <= 0:
                    break
                used = min(left, int(unmet[q][d]))
                unmet[q][d] -= used
                left -= used
    return victims


def preempt_and_place_np(
    keys: Sequence[str],
    prios: Sequence[int],
    demand: np.ndarray,
    freed: np.ndarray,
    victim_ok: Sequence[bool],
    weights: Sequence[int],
    *,
    names: Sequence[str],  # cluster column order
    assigned: Mapping[str, Mapping[str, int]],  # key -> victim placement
    requests: Mapping[str, np.ndarray],  # key -> int64[R] per-replica
    base_caps: np.ndarray,  # int64[C, R] snapshot available capacity
    demanders: Sequence[str],  # keys of the rows to re-solve
    candidates: Mapping[str, np.ndarray],  # key -> bool[C] post-filter
    strategies: Mapping[str, int],
    replicas: Mapping[str, int],
    prev: Mapping[str, Mapping[str, int]],
    fresh: Optional[Mapping[str, bool]] = None,
) -> tuple[list[str], dict[str, dict[str, int]]]:
    """The whole scarcity wave, per binding: sequential victim selection,
    per-cluster freed-capacity accumulation, then a one-row numpy divide
    for each demander against availability recomputed over
    ``base_caps + freed``. Returns (victim keys, demander placements by
    key; an empty dict entry = still unschedulable)."""
    flags = select_victims_np(prios, demand, freed, victim_ok, weights)
    col = {nm: j for j, nm in enumerate(names)}
    c = len(names)
    r = np.asarray(base_caps).shape[1]
    freed_caps = np.zeros((c, r), np.int64)
    victim_keys = []
    for i, key in enumerate(keys):
        if not flags[i]:
            continue
        victim_keys.append(key)
        req = np.asarray(requests[key], np.int64)
        for nm, reps in assigned.get(key, {}).items():
            j = col.get(nm)
            if j is not None:
                freed_caps[j] += int(reps) * req
    boosted = np.asarray(base_caps, np.int64) + freed_caps
    out: dict[str, dict[str, int]] = {}
    for key in demanders:
        req = np.asarray(requests[key], np.int64)
        avail = np.full(c, MAX_INT32, np.int64)
        for d in range(r):
            if req[d] > 0:
                avail = np.minimum(
                    avail, np.maximum(boosted[:, d], 0) // req[d]
                )
        prev_row = np.zeros(c, np.int32)
        for nm, reps in prev.get(key, {}).items():
            j = col.get(nm)
            if j is not None:
                prev_row[j] = reps
        assignment, unsched = assign_batch_np(
            np.asarray([strategies[key]], np.int32),
            np.asarray([replicas[key]], np.int32),
            np.asarray(candidates[key], bool)[None, :],
            np.zeros((1, c), np.int32),
            np.minimum(avail, MAX_INT32).astype(np.int32)[None, :],
            prev_row[None, :],
            np.asarray([bool(fresh[key]) if fresh else False]),
        )
        if bool(unsched[0]):
            out[key] = {}
            continue
        out[key] = {
            names[j]: int(assignment[0, j])
            for j in np.flatnonzero(assignment[0] > 0)
        }
    return victim_keys, out


def rebalance_np(
    keys: Sequence[str],
    *,
    names: Sequence[str],
    current: Mapping[str, Mapping[str, int]],  # key -> resident placement
    candidates: Mapping[str, np.ndarray],
    strategies: Mapping[str, int],
    replicas: Mapping[str, int],
    avail: Mapping[str, np.ndarray],  # key -> int32[C] fresh availability
    budget: int,
) -> tuple[dict[str, int], list[str]]:
    """Continuous-descheduler oracle: per-binding fresh-solve ideal via
    the one-row numpy divider (fresh mode — surviving placements
    credited), drift = L1 replica distance from the resident placement,
    trigger set = top ``budget`` rows by (drift desc, arrival asc).
    Returns (drift by key, triggered keys)."""
    col = {nm: j for j, nm in enumerate(names)}
    c = len(names)
    drifts: dict[str, int] = {}
    for key in keys:
        prev_row = np.zeros(c, np.int32)
        for nm, reps in current.get(key, {}).items():
            j = col.get(nm)
            if j is not None:
                prev_row[j] = reps
        assignment, unsched = assign_batch_np(
            np.asarray([strategies[key]], np.int32),
            np.asarray([replicas[key]], np.int32),
            np.asarray(candidates[key], bool)[None, :],
            np.zeros((1, c), np.int32),
            np.asarray(avail[key], np.int32)[None, :],
            prev_row[None, :],
            np.asarray([True]),  # fresh: the rebalance semantics
        )
        if bool(unsched[0]):
            drifts[key] = 0  # nowhere better to go: no drift trigger
            continue
        drifts[key] = int(np.abs(assignment[0] - prev_row).sum())
    ranked = sorted(
        (k for k in keys if drifts.get(k, 0) > 0),
        key=lambda k: (-drifts[k], list(keys).index(k)),
    )
    return drifts, ranked[: max(int(budget), 0)]
