"""Per-binding numpy oracle for the placement-provenance kernels.

The reference derives a binding's diagnostics by walking the
Filter/Score/Select/AssignReplicas pipeline per binding and per cluster
(generic_scheduler.go); this module does exactly that — plain Python
loops with one ``if`` per decision stage per cluster, and a per-binding
Python sort for the candidate summary — sharing NO code with
``ops/explain.py`` (whose mask is a vectorized bit-OR and whose top-k is
a packed-key ``lax.top_k``). tests/test_explain.py asserts the two are
bit-identical across the randomized bucket grid, padded tails and mesh
1/2/4/8, which is the whole point: two independent derivations of "why"
agreeing bit-for-bit.

Stage order (bit positions) comes from ``utils.reasons.STAGE_REASONS`` —
the taxonomy, not the kernel, is the shared contract.
"""

from __future__ import annotations

import numpy as np

from ..utils.reasons import STAGE_REASONS

_BIT = {code: i for i, code in enumerate(STAGE_REASONS)}


def explain_one(
    aff_ok_row,  # bool[C]
    taint_ok_row,  # bool[C]
    api_ok_row,  # bool[C]
    spread_ok_row,  # bool[C]
    avail_row,  # int[C]
    caps_row,  # int[C]
    admitted: bool,
    dynamic: bool,
    replicas: int,
    assignment_row,  # int[C]
    prev_row,  # int[C]
    preempted_row,  # bool[C]
    k: int,
) -> tuple[np.ndarray, list[tuple]]:
    """One binding's exclusion bits + top-k summary, the reference way:
    each cluster walks the stage list in order and collects every stage
    that rejects it (the reference's filter plugins each record their
    own failure; a cluster can fail several)."""
    c = len(aff_ok_row)
    mask = np.zeros(c, np.uint8)
    consults = bool(dynamic) and int(replicas) > 0
    for j in range(c):
        bits = 0
        if not aff_ok_row[j]:
            bits |= 1 << _BIT["AffinityMismatch"]
        if not taint_ok_row[j]:
            bits |= 1 << _BIT["TaintUntolerated"]
        if not api_ok_row[j]:
            bits |= 1 << _BIT["ApiNotEnabled"]
        if consults and int(avail_row[j]) <= 0:
            bits |= 1 << _BIT["NoAvailableReplicas"]
        if consults and int(caps_row[j]) <= 0:
            bits |= 1 << _BIT["QuotaCapExceeded"]
        if not admitted:
            bits |= 1 << _BIT["QuotaExceeded"]
        if not spread_ok_row[j]:
            bits |= 1 << _BIT["SpreadConstraintUnsatisfied"]
        if preempted_row[j]:
            bits |= 1 << _BIT["PreemptedByHigherPriority"]
        mask[j] = bits
    # candidate summary: assigned desc, then availability desc, then
    # index asc — the reference's stable ordering for result rendering
    order = sorted(
        range(c),
        key=lambda j: (-int(assignment_row[j]), -int(avail_row[j]), j),
    )
    topk = [
        (
            j,
            int(avail_row[j]),
            int(prev_row[j]),
            int(assignment_row[j]),
            int(mask[j]),
        )
        for j in order[:k]
    ]
    return mask, topk


def explain_batch_np(
    aff_ok,  # bool[B, C]
    taint_ok,
    api_ok,
    spread_ok,
    avail,
    caps,
    admitted,  # bool[B]
    dynamic,  # bool[B]
    replicas,  # int[B]
    assignment,
    prev,
    preempted,  # bool[B, C]
    k: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Batched driver: loops ``explain_one`` per binding and packs the
    kernel-shaped outputs (uint8[B, C], int32[B, K, 5])."""
    b, c = np.asarray(aff_ok).shape
    masks = np.zeros((b, c), np.uint8)
    topk = np.zeros((b, k, 5), np.int32)
    for i in range(b):
        mask, rows = explain_one(
            np.asarray(aff_ok)[i], np.asarray(taint_ok)[i],
            np.asarray(api_ok)[i], np.asarray(spread_ok)[i],
            np.asarray(avail)[i], np.asarray(caps)[i],
            bool(np.asarray(admitted)[i]), bool(np.asarray(dynamic)[i]),
            int(np.asarray(replicas)[i]), np.asarray(assignment)[i],
            np.asarray(prev)[i], np.asarray(preempted)[i], k,
        )
        masks[i] = mask
        for slot, row in enumerate(rows):
            topk[i, slot] = row
    return masks, topk
