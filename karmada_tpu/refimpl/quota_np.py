"""Numpy quota oracle: sequential per-binding admission (ISSUE 8's
identity referent).

The engine's batched path (``ops.quota.quota_admit`` — one sort + segment
cumsum over the whole wave) claims the FIFO cumulative-admission rule:
inside a wave, bindings are admitted in arrival order per namespace, and a
binding fits iff its inclusive running demand fits the namespace's
remaining quota on every dimension (a denied binding's demand still holds
its place in line). This module IS that rule as the reference would write
it: a plain Python loop over bindings in arrival order, accumulating a
per-namespace running total and comparing dimension by dimension. No
shared admission code with the kernel — a drift in the kernel's sort/scan
algebra shows up as an oracle mismatch, not a shared bug.

``cluster_caps_seq`` is the same treatment for the static-assignment cap
tensor: a per-binding, per-cluster, per-dimension Python loop computing
``min over requested dims of floor(cap / request)`` — the divide kernel's
availability ceiling, derived with none of the kernel's vectorization.

``admit_and_place`` composes admission with the per-binding numpy divider
(refimpl.divider_np) so a whole quota-capped scheduling wave can be
verified end to end: admitted bindings divide against cap-folded
availability; denied bindings keep their previous placement untouched.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from .divider_np import assign_batch_np

MAX_INT32 = 2**31 - 1
UNLIMITED_NP = 2**62


def admit_wave_np(
    ns_ids: Sequence[int],  # per-binding namespace id, -1 = not quota'd
    demand: np.ndarray,  # int64[B, R] delta demand (>= 0)
    remaining: np.ndarray,  # int64[N, R]; UNLIMITED_NP = no cap
) -> tuple[list[bool], np.ndarray]:
    """Sequential FIFO admission: one binding at a time, arrival order.
    Returns (admitted flags, admitted demand per namespace [N, R])."""
    remaining = np.asarray(remaining)
    n, r = remaining.shape
    running = np.zeros((n, r), np.int64)  # inclusive demand seen so far
    used = np.zeros((n, r), np.int64)  # admitted demand only
    admitted: list[bool] = []
    for i, ns in enumerate(ns_ids):
        if ns < 0:
            admitted.append(True)
            continue
        ok = True
        for d in range(r):
            running_d = running[ns, d] + int(demand[i, d])
            if running_d > remaining[ns, d]:
                ok = False
        # the demand holds its place in line whether or not it fit
        for d in range(r):
            running[ns, d] += int(demand[i, d])
        if ok:
            for d in range(r):
                used[ns, d] += int(demand[i, d])
        admitted.append(ok)
    return admitted, used


def cluster_caps_seq(
    caps: np.ndarray,  # int64[N, C, R] static-assignment hard caps
    ns_row: int,  # cap-table row, -1 = uncapped
    request: np.ndarray,  # int64[R] per-replica request
) -> np.ndarray:
    """int32[C]: per-cluster replica ceiling for ONE binding, derived the
    reference way (a loop per cluster per dimension)."""
    c = caps.shape[1]
    out = np.full(c, MAX_INT32, np.int64)
    if ns_row < 0:
        return out.astype(np.int32)
    for j in range(c):
        best = None
        for d in range(request.shape[0]):
            req = int(request[d])
            if req <= 0:
                continue
            cap = int(caps[ns_row, j, d])
            if cap >= UNLIMITED_NP:
                continue
            fit = cap // req
            best = fit if best is None else min(best, fit)
        if best is not None:
            out[j] = min(best, MAX_INT32)
    return out.astype(np.int32)


def admit_and_place(
    keys: Sequence[str],
    ns_ids: Sequence[int],
    demand: np.ndarray,  # int64[B, R] delta demand
    remaining: np.ndarray,  # int64[N, R]
    *,
    names: Sequence[str],  # cluster column order
    placements: Mapping[str, Mapping[str, int]],  # key -> previous clusters
    candidates: Mapping[str, np.ndarray],  # key -> bool[C] post-filter
    strategies: Mapping[str, int],
    replicas: Mapping[str, int],
    static_w: Mapping[str, np.ndarray],
    avail: Mapping[str, np.ndarray],  # key -> int32[C] merged availability
    cap_rows: Optional[Mapping[str, np.ndarray]] = None,  # key -> int32[C]
    fresh: Optional[Mapping[str, bool]] = None,
) -> tuple[dict[str, bool], dict[str, dict[str, int]]]:
    """The whole quota wave, per binding: sequential admission then a
    one-row numpy divide for each admitted binding against availability
    min-folded with its static-assignment cap row. Denied bindings keep
    their previous placement. Returns (admitted by key, placements by
    key)."""
    flags, _used = admit_wave_np(ns_ids, demand, remaining)
    col = {nm: i for i, nm in enumerate(names)}
    out: dict[str, dict[str, int]] = {}
    admitted_by_key: dict[str, bool] = {}
    for i, key in enumerate(keys):
        admitted_by_key[key] = flags[i]
        placed = placements.get(key, {})
        if not flags[i]:
            out[key] = dict(placed)
            continue
        prev_row = np.zeros(len(names), np.int32)
        for nm, rep in placed.items():
            if nm in col:
                prev_row[col[nm]] = rep
        a = np.asarray(avail[key], np.int64)
        if cap_rows is not None and key in cap_rows:
            a = np.minimum(a, np.asarray(cap_rows[key], np.int64))
        assignment, unsched = assign_batch_np(
            np.asarray([strategies[key]], np.int32),
            np.asarray([replicas[key]], np.int32),
            np.asarray(candidates[key], bool)[None, :],
            np.asarray(static_w[key], np.int32)[None, :],
            np.minimum(a, MAX_INT32).astype(np.int32)[None, :],
            prev_row[None, :],
            np.asarray([bool(fresh[key]) if fresh else False]),
        )
        if bool(unsched[0]):
            out[key] = dict(placed)  # unschedulable: placement unchanged
            continue
        out[key] = {
            names[j]: int(assignment[0, j])
            for j in np.flatnonzero(assignment[0] > 0)
        }
    return admitted_by_key, out
