"""Vectorized-numpy host divider: the calibrated CPU baseline.

BASELINE.md frames the target as "faster than the in-tree Go divider", but
no Go toolchain exists in this image and the pure-Python oracle
(refimpl.divider) overstates the speedup by the interpreter tax. This module
is the honest host baseline: the same division semantics
(division_algorithm.go:75-152, binding.go:112-144) written as the best
vectorized numpy program we can produce — batched cohort masks, exact
largest-remainder apportion with the (weight desc, lastReplicas desc, index
asc) order resolved via an argpartition+sort of the top candidates instead
of a full per-row sort. bench.py reports the TPU multiple against BOTH
baselines (vs_numpy_host is the conservative, Go-comparable figure;
vs_python_oracle is the interpreter-relative one).

Semantics are verified against the pure-Python oracle by
tests/test_refimpl_divider.py-style randomized goldens
(tests/test_divider_np.py).
"""

from __future__ import annotations

import numpy as np

from .divider import AGGREGATED, DUPLICATED, DYNAMIC_WEIGHT, STATIC_WEIGHT

MAX_INT32 = 2**31 - 1

#: accumulation dtype of the host baseline. MUST stay in parity with the
#: TPU kernels' wide accumulator (karmada_tpu.ops.dispense.ACC_WIDE) —
#: identical placements require both sides to agree on the overflow-free
#: integer range for weight*replica products and availability cumsums.
#: Declared here (not imported from ops) so this numpy module never pulls
#: jax; tests/test_graftlint_ir.py::test_acc_dtype_parity asserts the two
#: constants resolve to the same numpy dtype.
ACC_NP = np.int64


def _dispense_np(
    num: np.ndarray,  # int64[B] replicas to dispense
    w: np.ndarray,  # int64[B, C] weights (0 = excluded)
    last: np.ndarray,  # int64[B, C] previous replicas (tie-break)
    init: np.ndarray,  # int64[B, C] merged into the result
    k_bound: int,  # >= max(num) — bounds the remainder rank
) -> np.ndarray:
    """Batched TakeByWeight (binding.go:112-144): floors + the remainder
    handed out in (weight desc, last desc, index asc) order."""
    b, c = w.shape
    total = w.sum(axis=1)
    safe_total = np.maximum(total, 1)
    floors = w * num[:, None] // safe_total[:, None]
    remain = num - floors.sum(axis=1)

    # the bonus goes to the `remain` largest (w, last, -idx) keys; remain
    # <= num <= k_bound, so only the top-k keys per row matter. The triple
    # packs exactly into one int64 via mixed-radix arithmetic.
    idx = np.arange(c, dtype=ACC_NP)
    lmax = int(last.max(initial=0)) + 1
    wmax = int(w.max(initial=0))
    assert (wmax + 1) * lmax * c < 2**63, "weights exceed the packed baseline"
    key = (w * lmax + last) * c + (c - 1 - idx)[None, :]
    k = min(k_bound, c)
    if k < c:
        top_idx = np.argpartition(key, c - k, axis=1)[:, c - k :]
    else:
        top_idx = np.broadcast_to(idx[None, :], (b, c))
    top_keys = np.take_along_axis(key, top_idx, axis=1)
    top_sorted = -np.sort(-top_keys, axis=1)  # desc
    pos = np.clip(remain - 1, 0, k - 1).astype(ACC_NP)
    thr = np.take_along_axis(top_sorted, pos[:, None], axis=1)[:, 0]
    bonus = (key >= thr[:, None]) & (remain > 0)[:, None]
    dispensed = np.where(
        (total > 0)[:, None], floors + bonus.astype(ACC_NP), 0
    )
    return init + dispensed


def _aggregated_keep_np(
    w: np.ndarray,  # int64[B, C] availability weights
    is_prev: np.ndarray,  # bool[B, C] previously-scheduled (scale-up credit)
    target: np.ndarray,  # int64[B]
) -> np.ndarray:
    """Minimal prefix of (prev desc, avail desc, idx asc) whose cumulative
    availability covers target (assignment.go:146-173 + the resort)."""
    b, c = w.shape
    idx = np.arange(c, dtype=ACC_NP)
    prev_key = np.where(is_prev, 0, 1)
    order = np.lexsort((idx[None, :].repeat(b, 0), -w, prev_key), axis=1)
    w_sorted = np.take_along_axis(w, order, axis=1)
    cum_before = np.cumsum(w_sorted, axis=1) - w_sorted
    keep_sorted = cum_before < target[:, None]
    keep = np.zeros((b, c), bool)
    np.put_along_axis(keep, order, keep_sorted, axis=1)
    return keep


def assign_batch_np(
    strategy: np.ndarray,  # int32[B]
    replicas: np.ndarray,  # int32[B]
    candidates: np.ndarray,  # bool[B, C]
    static_w: np.ndarray,  # int32[B, C]
    avail: np.ndarray,  # int32[B, C]
    prev: np.ndarray,  # int32[B, C]
    fresh: np.ndarray,  # bool[B]
) -> tuple[np.ndarray, np.ndarray]:
    """Batched AssignReplicas over [B, C] numpy arrays; returns
    (assignment int32[B, C], unschedulable bool[B]). Mirrors
    assignment.go:31-38 dispatch + division_algorithm.go cohorts."""
    b, c = candidates.shape
    strategy = strategy.astype(ACC_NP)
    num = replicas.astype(ACC_NP)
    prev = prev.astype(ACC_NP)
    avail = np.where(candidates, avail, 0).astype(ACC_NP)
    prev_cand = np.where(candidates, prev, 0)
    assigned = prev_cand.sum(axis=1)
    fresh = fresh.astype(bool)

    is_dup = strategy == DUPLICATED
    is_static = strategy == STATIC_WEIGHT
    is_dynamic = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)

    scale_down = is_dynamic & ~fresh & (assigned > num)
    scale_up = is_dynamic & ~fresh & (assigned < num)
    steady_noop = is_dynamic & ~fresh & (assigned == num)
    is_fresh = is_dynamic & fresh

    target_dyn = np.where(scale_up, num - assigned, num)
    w_dyn = np.where(
        is_fresh[:, None],
        avail + prev_cand,
        np.where(scale_down[:, None], prev, avail),
    )
    init_dyn = np.where(scale_up[:, None], prev_cand, 0)

    unsched = is_dynamic & ~steady_noop & (w_dyn.sum(axis=1) < target_dyn)

    if (strategy == AGGREGATED).any():
        keep = _aggregated_keep_np(
            w_dyn, (prev_cand > 0) & scale_up[:, None], target_dyn
        )
        w_dyn = np.where(
            ((strategy == AGGREGATED)[:, None] & keep)
            | (strategy != AGGREGATED)[:, None],
            w_dyn,
            0,
        )

    sw = np.where(candidates, static_w, 0).astype(ACC_NP)
    sw = np.where(
        (sw.sum(axis=1) > 0)[:, None], sw, candidates.astype(ACC_NP)
    )
    last_static = np.where(candidates, prev, 0)

    num_d = np.where(is_static, num, target_dyn)
    w = np.where(is_static[:, None], sw, w_dyn)
    last = np.where(is_static[:, None], last_static, init_dyn)
    init = np.where(is_static[:, None], 0, init_dyn)
    w = np.where((is_dup | steady_noop | unsched)[:, None], 0, w)

    k_bound = max(1, int(num_d.max(initial=0)))
    out = _dispense_np(num_d, w, last, init, k_bound)

    out = np.where(steady_noop[:, None], prev_cand, out)
    out = np.where(
        is_dup[:, None], np.where(candidates, num[:, None], 0), out
    )
    out = np.where(unsched[:, None], 0, out)
    out = np.where((num == 0)[:, None], 0, out)
    return out.astype(np.int32), unsched
