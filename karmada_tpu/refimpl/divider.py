"""Pure-Python oracle of the reference's replica-division semantics.

This module re-executes, step for step, the behavior of the Go divider so the
TPU kernels (karmada_tpu.ops) can be verified to produce *identical
placements* (BASELINE.md "identical-placement check"). It is also the CPU
baseline that bench.py measures the TPU solver against.

Reference semantics implemented (file:line cites into /root/reference):
- Dispenser largest-remainder apportion: pkg/util/helper/binding.go:112-144
- weight ordering (weight desc, lastReplicas desc):
  pkg/util/helper/binding.go:64-80. The reference breaks remaining ties with
  crypto-rand; a random order cannot be reproduced on or off TPU, so this
  build fixes the total order with ascending cluster index (documented
  divergence — any such tie is equally valid under the reference contract).
- static-weight matching: pkg/scheduler/core/division_algorithm.go:38-72
- dynamic strategies (Steady/Fresh, scale up/down, Aggregated prefix):
  pkg/scheduler/core/assignment.go:208-239,
  pkg/scheduler/core/division_algorithm.go:75-152
- available-replica merge across estimators with MaxInt32 sentinel:
  pkg/scheduler/core/util.go:54-104
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

MAX_INT32 = 2**31 - 1

# Strategy identifiers (ref: assignment.go:40-50). Integer codes are shared
# with the tensor kernels (karmada_tpu.ops.divide).
DUPLICATED = 0
STATIC_WEIGHT = 1
DYNAMIC_WEIGHT = 2
AGGREGATED = 3

STRATEGY_NAMES = {
    DUPLICATED: "Duplicated",
    STATIC_WEIGHT: "StaticWeight",
    DYNAMIC_WEIGHT: "DynamicWeight",
    AGGREGATED: "Aggregated",
}


class UnschedulableError(Exception):
    """Ref: framework.UnschedulableError — available < target."""


@dataclass
class DivisionProblem:
    """One binding's division problem over an indexed candidate cluster list.

    All per-cluster vectors are aligned with ``candidates`` (cluster indices
    into the snapshot's canonical order — names are irrelevant to division).
    """

    replicas: int
    strategy: int
    # candidate cluster indices, in snapshot order
    candidates: Sequence[int]
    # static weights per candidate (already rule-matched; 0 = not on the list)
    static_weights: Optional[Sequence[int]] = None
    # estimator availability per candidate (post min-merge + sentinel clamp)
    available: Optional[Sequence[int]] = None
    # previous schedule result (spec.clusters): cluster index -> replicas.
    # NOTE: kept unfiltered — scale-down deliberately weighs the full previous
    # result (division_algorithm.go:110-115 copies spec.Clusters), while the
    # scale direction is decided on the candidates-only sum
    # (assignment.go:120-137 buildScheduledClusters).
    prev: Optional[dict[int, int]] = None
    # Fresh mode (reschedule triggered): assignment.go:109-117
    fresh: bool = False


def take_by_weight(
    num_replicas: int,
    weights: Sequence[tuple[int, int, int]],
    init: Optional[dict[int, int]] = None,
) -> dict[int, int]:
    """Dispenser.TakeByWeight (binding.go:112-144).

    ``weights`` is a list of (cluster_index, weight, last_replicas). Returns
    cluster_index -> replicas, merged with ``init`` (MergeTargetClusters
    semantics: pkg/util/binding.go:76-100 — replica sums by name).
    """
    result: dict[int, int] = dict(init or {})
    if num_replicas == 0 and result:
        return result  # Dispenser.Done()
    total = sum(w for _, w, _ in weights)
    if total == 0:
        return result
    # total order: weight desc, lastReplicas desc, index asc (see module doc)
    order = sorted(weights, key=lambda t: (-t[1], -t[2], t[0]))
    floors = [(idx, w * num_replicas // total) for idx, w, _ in order]
    remain = num_replicas - sum(f for _, f in floors)
    out: dict[int, int] = {}
    for pos, (idx, f) in enumerate(floors):
        out[idx] = f + (1 if pos < remain else 0)
    for idx, r in out.items():
        result[idx] = result.get(idx, 0) + r
    return result


def _spread_replicas_by_target_clusters(
    num_replicas: int,
    tcs: Sequence[tuple[int, int]],
    init: Optional[dict[int, int]],
) -> dict[int, int]:
    """SpreadReplicasByTargetClusters (binding.go:167-172): weights are the
    target-cluster replica counts, lastReplicas looked up from init."""
    init = init or {}
    weights = [(idx, int(avail), init.get(idx, 0)) for idx, avail in tcs]
    return take_by_weight(num_replicas, weights, init)


def assign_replicas(problem: DivisionProblem) -> dict[int, int]:
    """Replica assignment for one binding; returns cluster_index -> replicas
    with zero entries removed (core/util.go:122-130).

    Orchestration mirrors assignment.go: Duplicated broadcast (:176-182),
    static weight (:194-206), dynamic Steady/Fresh dispatch (:208-239).
    """
    p = problem
    if p.strategy == DUPLICATED:
        # zero-replica entries are stripped for every strategy
        # (core/util.go:122-130); the replicas==0 "assign all clusters
        # with no replicas" path (core/common.go:70-74) is the scheduler
        # layer's job, not the divider's.
        return {idx: p.replicas for idx in p.candidates if p.replicas > 0}

    if p.strategy == STATIC_WEIGHT:
        prev = p.prev or {}
        weights = []
        assert p.static_weights is not None
        for idx, w in zip(p.candidates, p.static_weights):
            if w > 0:  # weight<=0 clusters are ignored (division_algorithm.go:55)
                weights.append((idx, int(w), prev.get(idx, 0)))
        if sum(w for _, w, _ in weights) == 0:
            # all-zero weights -> every candidate weight 1 (:63-70)
            weights = [(idx, 1, prev.get(idx, 0)) for idx in p.candidates]
        result = take_by_weight(p.replicas, weights, None)
        return {i: r for i, r in result.items() if r > 0}

    # dynamic strategies (DynamicWeight / Aggregated)
    assert p.available is not None
    avail = {idx: int(a) for idx, a in zip(p.candidates, p.available)}
    prev = dict(p.prev or {})
    cand_set = set(p.candidates)
    # candidates-only previous result (buildScheduledClusters, assignment.go:120-137)
    scheduled = {i: r for i, r in prev.items() if i in cand_set}
    assigned = sum(scheduled.values())

    if p.fresh:
        # dynamicFreshScale (:131-152): availability credited with previous
        # assignment, full recompute, no init.
        credited = {idx: avail[idx] + scheduled.get(idx, 0) for idx in avail}
        target, init, use_sched = p.replicas, None, {}
        ordered = _sort_by_avail(credited, p.candidates)
        return _dynamic_divide(p, target, ordered, init, use_sched, credited)

    if assigned > p.replicas:
        # dynamicScaleDown (:101-117): weights = the FULL previous result
        # (spec.Clusters, not filtered to candidates), no init.
        ordered = _sort_by_avail(prev, list(prev))
        return _dynamic_divide(p, p.replicas, ordered, None, {}, prev)

    if assigned < p.replicas:
        # dynamicScaleUp (:119-128): dispense only the delta over current
        # availability, init/merge with the previous result.
        target = p.replicas - assigned
        ordered = _sort_by_avail(avail, p.candidates)
        return _dynamic_divide(p, target, ordered, scheduled, scheduled, avail)

    return {i: r for i, r in scheduled.items() if r > 0}


def _sort_by_avail(avail: dict[int, int], candidates: Sequence[int]) -> list[int]:
    """TargetClustersList sort: replicas desc (division_algorithm.go:31-36),
    index-asc tiebreak (deterministic stand-in for Go's unstable sort)."""
    return sorted((i for i in candidates), key=lambda i: (-avail.get(i, 0), i))


def _dynamic_divide(
    p: DivisionProblem,
    target: int,
    ordered: list[int],
    init: Optional[dict[int, int]],
    scheduled: dict[int, int],
    avail: dict[int, int],
) -> dict[int, int]:
    """dynamicDivideReplicas (division_algorithm.go:75-99)."""
    available_sum = sum(avail.get(i, 0) for i in ordered)
    if available_sum < target:
        raise UnschedulableError(
            f"clusters available replicas {available_sum} are not enough "
            f"to schedule (target {target})"
        )
    if p.strategy == AGGREGATED:
        # resortAvailableClusters (assignment.go:146-173): previously-used
        # clusters first (stable), then prefix until cumulative >= target.
        prior = [i for i in ordered if scheduled.get(i, 0) > 0]
        rest = [i for i in ordered if scheduled.get(i, 0) <= 0]
        ordered = prior + rest
        cum, cut = 0, len(ordered)
        for pos, i in enumerate(ordered):
            cum += avail.get(i, 0)
            if cum >= target:
                cut = pos + 1
                break
        ordered = ordered[:cut]
    result = _spread_replicas_by_target_clusters(
        target, [(i, avail.get(i, 0)) for i in ordered], init
    )
    return {i: r for i, r in result.items() if r > 0}


# ---------------------------------------------------------------------------
# Availability merge (calAvailableReplicas)
# ---------------------------------------------------------------------------


def merge_estimates(
    replicas: int,
    estimates: Sequence[Sequence[int]],
    num_candidates: int,
) -> list[int]:
    """core/util.go:54-104: start at MaxInt32, take the min across estimators
    (UnauthenticReplica == -1 entries are ignored), clamp the untouched
    sentinel to spec.Replicas. A zero-replica binding short-circuits to the
    sentinel path (non-workloads)."""
    out = [MAX_INT32] * num_candidates
    if replicas != 0:
        for est in estimates:
            for i, v in enumerate(est):
                if v == -1:
                    continue
                if v < out[i]:
                    out[i] = v
    return [replicas if v == MAX_INT32 else v for v in out]
