"""Numpy failover oracle: ordered ClusterAffinities rescheduling replayed
per binding (ISSUE 7 tentpole b's identity referent).

The engine's tensorized path (``ops.masks.first_fit_group`` + one batched
solve in ``TensorScheduler._schedule_chunk_ranked``) claims that selecting
each displaced binding's first FITTING affinity group vectorized and then
solving once is placement-identical to the reference's control flow —
"try group 0, reschedule, on failure try group 1, ..."
(scheduler.go:533-596). This module IS that control flow: a plain Python
loop per binding over its fallback groups, each attempt dividing through
``refimpl.divider_np.assign_batch_np`` on a single row. No shared
selection code with the engine path — the predicate here is "run the
divider and look at its unschedulable flag", so a drift in the engine's
vectorized fit predicate shows up as an oracle mismatch, not a shared bug.

``replay_failover`` additionally consumes a fault-event log
(utils.faultinject ``FaultEvent``/dict rows): killed clusters are evicted
from every binding's previous placements exactly as the taint-manager ->
``evict_binding`` path does (spec.clusters drops the cluster, the
graceful-eviction task masks it via ClusterEviction), so a chaos run's
final placements can be verified from (seeded event log, pre-kill
placements, post-kill capacity snapshot) alone.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from .divider_np import assign_batch_np


def solve_one_ordered(
    term_masks: np.ndarray,  # bool[T, C] ordered affinity-group masks
    base_feasible: np.ndarray,  # bool[C] every non-affinity filter composed
    strategy: int,
    replicas: int,
    static_w: np.ndarray,  # int32[C]
    avail: np.ndarray,  # int32[C] merged estimator availability
    prev: np.ndarray,  # int32[C]
    fresh: bool,
) -> tuple[Optional[np.ndarray], int, str]:
    """One binding through the reference's ordered-group retry loop.
    Returns (assignment int32[C] | None, selected term index, error)."""
    t = term_masks.shape[0]
    last_err = "no affinity group fits"
    for ti in range(t):
        cand = term_masks[ti] & base_feasible
        if not cand.any():
            last_err = "no clusters fit the placement"
            continue
        out, unsched = assign_batch_np(
            np.asarray([strategy], np.int32),
            np.asarray([replicas], np.int32),
            cand[None, :],
            np.asarray(static_w, np.int32)[None, :],
            np.asarray(avail, np.int32)[None, :],
            np.asarray(prev, np.int32)[None, :],
            np.asarray([fresh], bool),
        )
        if bool(unsched[0]):
            last_err = "clusters available replicas are not enough"
            continue
        return out[0], ti, ""
    return None, t - 1, last_err


def replay_failover(
    events: Sequence,  # faultinject FaultEvent / dict rows (cluster kills)
    names: Sequence[str],  # snapshot cluster order (columns)
    placements: Mapping[str, Mapping[str, int]],  # key -> pre-kill clusters
    term_masks: Mapping[str, np.ndarray],  # key -> bool[T, C]
    base_feasible: Mapping[str, np.ndarray],  # key -> bool[C], pre-eviction
    strategies: Mapping[str, int],
    replicas: Mapping[str, int],
    static_w: Mapping[str, np.ndarray],
    avail: Mapping[str, np.ndarray],  # key -> int32[C] at solve time
) -> dict[str, dict[str, int]]:
    """Replay a chaos run's cluster-kill events over pre-kill placements
    and return the expected stable placements, binding by binding.

    Eviction semantics mirror controllers/cluster.py ``evict_binding`` +
    the engine's ClusterEviction filter: a killed cluster leaves
    spec.clusters (prev) AND the candidate set; surviving replicas stay
    credited via prev, and the binding reschedules NON-fresh (scale-up
    cohort: the shortfall tops up from the fallback groups, existing rows
    keep their placements — GracefulEviction's replacement-first shape).
    """
    killed = set()
    for ev in events:
        point = getattr(ev, "point", None) or ev.get("point")
        action = getattr(ev, "action", None) or ev.get("action")
        key = getattr(ev, "key", None) or ev.get("key")
        if point == "cluster.health" and action == "down":
            killed.add(key)
    col = {n: i for i, n in enumerate(names)}
    dead_cols = [col[k] for k in killed if k in col]
    out: dict[str, dict[str, int]] = {}
    for key, placed in placements.items():
        prev_row = np.zeros(len(names), np.int32)
        for n, r in placed.items():
            if n in col and n not in killed:
                prev_row[col[n]] = r
        base = np.asarray(base_feasible[key], bool).copy()
        if dead_cols:
            base[dead_cols] = False  # NoExecute eviction mask
        assignment, _ti, err = solve_one_ordered(
            np.asarray(term_masks[key], bool),
            base,
            int(strategies[key]),
            int(replicas[key]),
            np.asarray(static_w[key], np.int32),
            np.asarray(avail[key], np.int32),
            prev_row,
            fresh=False,
        )
        if assignment is None:
            out[key] = dict(placed)  # unschedulable: placement unchanged
            continue
        out[key] = {
            names[j]: int(assignment[j])
            for j in np.flatnonzero(assignment > 0)
        }
    return out
