"""Standalone store-bus process: the apiserver+etcd role of the deployment.

``python -m karmada_tpu.bus --address 127.0.0.1:0`` hosts ONE authoritative
Store (default admission chain) behind the gRPC store bus. Plane replicas
(``localup serve-plane --connect-bus``), agents, and CLIs are all
StoreReplica clients of this process — killing a plane replica never loses
state, which is what makes active-standby plane HA possible (ref: every
reference binary runs --leader-elect against the shared apiserver,
cmd/scheduler/app/options/options.go:130-165).

Prints ONE JSON line {"bus": port} when serving; SIGTERM checkpoints to
--state-file (etcd persistence analogue) and exits.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="karmada-tpu-bus")
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--state-file", default="")
    p.add_argument(
        "--checkpoint-interval", type=float, default=0.0,
        help="seconds between periodic store checkpoints (0 = only on exit)",
    )
    p.add_argument(
        "--metrics-port", default=None,
        help="serve /metrics + /healthz + /debug/traces on this port or HOST:PORT "
        "(0 = ephemeral, reported in the startup JSON line; default: "
        "$KARMADA_TPU_METRICS_PORT, empty = disabled)",
    )
    args = p.parse_args(argv)
    # chaos: arm deterministic fault injection from the environment
    # (KARMADA_TPU_FAULT_SPEC; disarmed when empty — zero overhead)
    from ..utils.faultinject import arm_from_env
    from ..utils.tracing import register_peers_from_env, tracer

    arm_from_env()
    # cross-process tracing: handler spans export as proc="bus"
    tracer.set_process("bus")
    register_peers_from_env()

    import os

    from ..utils import Store
    from ..utils.metrics import serve_process_metrics
    from ..webhook import default_admission_chain
    from .service import StoreBusServer

    chain = default_admission_chain()
    store = Store(
        admission=chain.admit, delete_admission=chain.admit_delete
    )
    if args.state_file and os.path.exists(args.state_file):
        restored = store.restore(args.state_file)
        print(f"# restored {restored} objects from {args.state_file}",
              file=sys.stderr)
    metrics = serve_process_metrics(args.metrics_port)
    bus = StoreBusServer(store, args.address)
    port = bus.start()
    endpoints = {"bus": port}
    if metrics is not None:
        endpoints["metrics"] = metrics.port
    print(json.dumps(endpoints), flush=True)

    stop = [False]

    def on_term(signum, frame):
        stop[0] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    last_ckpt = time.time()
    last_rv = -1
    try:
        while not stop[0]:
            time.sleep(0.05)
            if (
                args.state_file
                and args.checkpoint_interval > 0
                and time.time() - last_ckpt >= args.checkpoint_interval
            ):
                if store.rv != last_rv:
                    store.checkpoint(args.state_file)
                    last_rv = store.rv
                last_ckpt = time.time()
    finally:
        if args.state_file:
            saved = store.checkpoint(args.state_file)
            print(f"# checkpointed {saved} objects to {args.state_file}",
                  file=sys.stderr)
        if metrics is not None:
            metrics.stop()
        bus.stop()


if __name__ == "__main__":
    main()
