"""Networked store watch bus (gRPC watch/apply surface + agent replica)."""

from .service import (  # noqa: F401
    StoreBusServer,
    StoreReplica,
    decode_object,
    encode_object,
    kind_registry,
)
