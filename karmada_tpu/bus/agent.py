"""Out-of-process pull-mode agent: ``cmd/agent`` run over the store bus.

Ref: cmd/agent/app/agent.go — the reference agent is a separate process
INSIDE the member cluster that talks to the control plane over the
network: it pulls Works for its execution namespace, applies them into the
local cluster, reflects status back, and keeps the cluster Lease renewed
so the control plane's lease-freshness health check holds.

This module is that process for the TPU-native plane: the network channel
is the store bus (bus.service) — a ``StoreReplica`` mirrors the plane's
state over the gRPC watch stream, and every agent write (Work status,
Lease renewal) rounds-trip through the primary via the bus Apply RPC. The
agent logic itself is the SAME ``KarmadaAgent`` controller that runs
in-process for locally-joined Pull members (controllers/remedy.py) —
``ReplicaStoreFacade`` gives it the Store surface over the replica.

Run: ``python -m karmada_tpu.bus.agent --target host:port --cluster name``
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from ..estimator.accurate import NodeState
from ..utils.member import MemberCluster
from ..utils.worker import Runtime


class ReplicaStoreFacade:
    """The Store surface a controller needs, over a ``StoreReplica``:
    reads and watches hit the local mirror (always cheap, never a network
    round-trip); writes go through the primary and become visible locally
    only via the echoed watch event — the replica can never diverge from
    the primary's admission decisions."""

    def __init__(self, replica) -> None:
        self._replica = replica

    # -- reads (mirror) ----------------------------------------------------

    def get(self, kind: str, key: str):
        return self._replica.store.get(kind, key)

    def list(self, kind: str, namespace: Optional[str] = None):
        return self._replica.store.list(kind, namespace)

    def watch(self, kind: str, fn, replay: bool = True):
        return self._replica.store.watch(kind, fn, replay=replay)

    # -- writes (primary, over the bus) ------------------------------------

    def apply(self, obj, *, expected_rv=None):
        return self._replica.apply(obj, expected_rv=expected_rv)

    def apply_many(self, objs):
        """Batched write-through (Store.apply_many contract): one
        ApplyBatch RPC per KARMADA_TPU_BUS_BATCH ops instead of one
        round-trip per object — the controllers' per-drain write sets
        ride this over the bus."""
        return self._replica.apply_many(objs)

    def delete(self, kind: str, key: str, force: bool = False):
        return self._replica.delete(kind, key, force=force)

    def delete_many(self, keys):
        return self._replica.delete_many(keys)


def _default_member(name: str) -> MemberCluster:
    """The member cluster this agent lives in. In this simulated world the
    'cluster' is a MemberCluster object local to the agent process — the
    same runtime seam every in-proc test drives."""
    member = MemberCluster(name)
    member.nodes = [
        NodeState(
            name=f"{name}-node-{i}",
            allocatable={"cpu": 8000, "memory": 32 << 30, "pods": 110},
        )
        for i in range(2)
    ]
    return member


def _simulate_kubelet(member: MemberCluster) -> None:
    """Bring applied workloads 'up': any replica-bearing resource without a
    ready status reports all replicas ready — the stand-in for kubelets
    starting pods, so health interpretation returns Healthy and the plane
    sees the propagation complete."""
    for obj in member.list():
        reps = obj.spec.get("replicas") if isinstance(obj.spec, dict) else None
        if reps is None:
            continue
        st = obj.status or {}
        if st.get("readyReplicas") != reps:
            member.set_workload_status(
                f"{obj.api_version}/{obj.kind}",
                obj.meta.namespace,
                obj.meta.name,
                {
                    "replicas": reps,
                    "readyReplicas": reps,
                    "updatedReplicas": reps,
                    "availableReplicas": reps,
                },
            )


def agent_main(
    target: str,
    cluster_name: str,
    *,
    loop_interval: float = 0.05,
    lease_interval: float = 0.5,
    simulate_ready: bool = True,
    max_seconds: Optional[float] = None,
    member: Optional[MemberCluster] = None,
    root_ca: Optional[bytes] = None,
    client_cert: Optional[bytes] = None,
    client_key: Optional[bytes] = None,
    leader_elect: bool = False,
    identity: str = "",
) -> None:
    from ..controllers.remedy import KarmadaAgent
    from ..interpreter import default_interpreter
    from .service import StoreReplica

    replica = StoreReplica(
        target,
        root_ca=root_ca,
        client_cert=client_cert,
        client_key=client_key,
    )
    replica.start()
    if not replica.wait_synced(10.0):
        print(f"agent {cluster_name}: bus sync timeout", file=sys.stderr)
        sys.exit(2)
    store = ReplicaStoreFacade(replica)
    runtime = Runtime()
    member = member or _default_member(cluster_name)
    agent = KarmadaAgent(store, runtime, member, default_interpreter())

    # HA agents: N replicas per member cluster, one active (the reference
    # agent's --leader-elect over a Lease resource lock). Standbys keep
    # their replica synced and queues filling; on takeover the first
    # settle drains the backlog and rebuilds member state from Works.
    elector = None
    if leader_elect:
        from ..utils.leaderelect import LeaderElector

        ident = identity or f"{cluster_name}-{os.getpid()}"
        elector = LeaderElector(
            store,
            name=f"karmada-agent-{cluster_name}",
            identity=ident,
            lease_duration=max(4 * lease_interval, 2.0),
            renew_deadline=max(2 * lease_interval, 1.0),
            on_started_leading=lambda: print(
                f"agent {cluster_name}: leading as {ident}", flush=True
            ),
            on_stopped_leading=lambda: print(
                f"agent {cluster_name}: lost leadership ({ident})",
                flush=True,
            ),
        )
    print(f"agent {cluster_name}: synced, serving", flush=True)

    start = time.time()
    last_tick = 0.0
    try:
        while max_seconds is None or time.time() - start < max_seconds:
            now = time.time()
            tick = now - last_tick >= lease_interval
            if tick:
                last_tick = now
            if elector is not None and tick:
                elector.tick()
            if elector is None or elector.is_leader:
                if tick and simulate_ready:
                    _simulate_kubelet(member)
                runtime.run_until_settled(tick=tick)
            time.sleep(loop_interval)
    finally:
        if elector is not None:
            elector.release()
        replica.close()
    # agent object kept alive by the loop above; reference it so linters
    # don't flag the construction as unused
    del agent


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target", required=True, help="bus host:port")
    p.add_argument("--cluster", required=True, help="member cluster name")
    p.add_argument("--loop-interval", type=float, default=0.05)
    p.add_argument("--lease-interval", type=float, default=0.5)
    p.add_argument("--max-seconds", type=float, default=None)
    p.add_argument(
        "--no-simulate-ready", action="store_true",
        help="do not mark applied workloads ready (failure-injection runs)",
    )
    p.add_argument(
        "--leader-elect", action="store_true",
        help="run as one of N HA replicas for this cluster; only the Lease "
        "holder syncs (reference agent's --leader-elect)",
    )
    p.add_argument(
        "--leader-elect-identity", default="",
        help="lease holder identity (default: <cluster>-<pid>)",
    )
    args = p.parse_args(argv)
    # chaos: arm deterministic fault injection from the environment — the
    # agent's bus channel (StoreReplica Apply/Delete/Watch) carries the
    # bus.rpc/bus.watch injection points
    from ..utils.faultinject import arm_from_env
    from ..utils.tracing import register_peers_from_env, tracer

    arm_from_env()
    # cross-process tracing: the agent's bus.rpc client spans export as
    # proc="agent"
    tracer.set_process("agent")
    register_peers_from_env()
    agent_main(
        args.target,
        args.cluster,
        loop_interval=args.loop_interval,
        lease_interval=args.lease_interval,
        simulate_ready=not args.no_simulate_ready,
        max_seconds=args.max_seconds,
        leader_elect=args.leader_elect,
        identity=args.leader_elect_identity,
    )


if __name__ == "__main__":
    main()
