"""Networked store watch bus: the plane's watch/apply surface over gRPC.

Ref: the reference's control plane is nine binaries around a shared
API server whose informer/watch channel carries all state
(pkg/util/fedinformer; the agent consumes it over DCN). This runtime's
Store is in-proc; the bus exports the same two primitives over the wire —
a server-streamed Watch (replay + live events, the informer list-then-
watch contract) and Apply/Delete write-through — so agents and
out-of-process controllers can run a `StoreReplica`: a local Store mirror
fed by the stream whose writes round-trip to the primary.

Objects travel as canonical JSON of the API dataclasses (utils/codec);
decode resolves classes from the kind registry below. Unknown kinds
degrade to generic Resource manifests rather than failing the stream
(forward compatibility across component versions).

Columnar channel (ISSUE 11): the per-object Apply/Event round-trips were
the measured whole-plane ceiling (BENCH_OBS_r02: 24.0 s of bus.rpc +
8.3 s of bus.apply in a 35.1 s plane-self window), so the wire protocol
is batched end to end — ``ApplyBatch`` carries a write SET per RPC with
per-op resourceVersion/CAS results, and ``WatchBatch`` streams coalesced
``EventFrame`` messages flushed by count (KARMADA_TPU_BUS_BATCH) or a
few-ms timer (KARMADA_TPU_BUS_FLUSH_MS). Both negotiate per connection
exactly like the estimator batch protocol: an old server answers
UNIMPLEMENTED, the client pins the unary fallback, and a wire failure
resets the pin so the reconnected channel re-probes.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from ..api.core import Resource
from ..utils import Store
from ..utils.codec import from_jsonable, to_jsonable
from ..utils.metrics import (
    bus_batch_size,
    bus_event_age_seconds,
    bus_events,
    bus_queue_depth,
    bus_subscribers,
)
from ..utils.store import ConflictError, Event as StoreEvent
from .proto import storebus_batch_pb2 as bpb
from .proto import storebus_pb2 as pb

SERVICE_NAME = "karmada_tpu.bus.StoreBus"

BUS_BATCH_ENV = "KARMADA_TPU_BUS_BATCH"
BUS_FLUSH_MS_ENV = "KARMADA_TPU_BUS_FLUSH_MS"


def bus_batch_max() -> int:
    """Max ops per ApplyBatch / events per watch frame; 0 disables the
    batched protocol entirely (the mixed-version escape hatch, mirroring
    KARMADA_TPU_ESTIMATOR_BATCH)."""
    raw = os.environ.get(BUS_BATCH_ENV, "").strip()
    try:
        return int(raw) if raw else 4096
    except ValueError:
        return 4096


def bus_flush_ms() -> float:
    """Watch-frame coalescing window: after the first queued event, the
    stream waits up to this long for more before flushing the frame."""
    raw = os.environ.get(BUS_FLUSH_MS_ENV, "").strip()
    try:
        return float(raw) if raw else 2.0
    except ValueError:
        return 2.0


#: gRPC message-size ceiling for the bus channel (both directions). The
#: grpc default of 4 MiB was sized for per-object messages; a batched
#: write set / replay frame legitimately reaches tens of MiB. Producers
#: still chunk against BATCH_BYTE_BUDGET so a healthy batch stays far
#: below this hard cap.
MAX_MESSAGE_BYTES = 128 << 20
#: soft per-message byte budget: apply_many/delete_many cut a batch and
#: watch streams flush a frame once the accumulated object JSON crosses
#: it — count (KARMADA_TPU_BUS_BATCH) bounds the common case, this
#: bounds the pathological one (few huge manifests)
BATCH_BYTE_BUDGET = 16 << 20

_CHANNEL_OPTIONS = [
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
]


def _kind_registry() -> dict[str, type]:
    """kind string -> dataclass, collected from every API surface that
    stores objects (the scheme registry analogue)."""
    registry: dict[str, type] = {}

    def scan(module) -> None:
        import dataclasses

        for name in dir(module):
            cls = getattr(module, name)
            if (
                isinstance(cls, type)
                and dataclasses.is_dataclass(cls)
                and isinstance(getattr(cls, "KIND", None), str)
            ):
                registry[cls.KIND] = cls

    from ..api import autoscaling, cluster, core, networking, policy, work
    from ..controllers import extras
    from ..interpreter import declarative
    from ..search import registry as search_registry

    for mod in (
        core, cluster, policy, work, autoscaling, networking, extras,
        declarative, search_registry,
    ):
        scan(mod)
    registry["Resource"] = Resource
    return registry


_REGISTRY: Optional[dict[str, type]] = None


def kind_registry() -> dict[str, type]:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _kind_registry()
    return _REGISTRY


def encode_object(obj) -> str:
    return json.dumps(to_jsonable(obj), sort_keys=True, separators=(",", ":"))


def decode_object(kind: str, object_json: str):
    cls = kind_registry().get(kind, Resource)
    doc = json.loads(object_json)
    if isinstance(doc, dict) and ("apiVersion" in doc or "api_version" in doc):
        # multi-version seam: a legacy-versioned payload (e.g.
        # work.karmada.io/v1alpha1 bindings) upgrades to the hub shape
        # before decode, so old clients keep working against a hub store
        from ..api.versioning import maybe_upgrade

        doc = maybe_upgrade(kind, doc)
    return from_jsonable(cls, doc)


class StoreBusServer:
    """Serves one Store's watch/apply surface (mTLS contract identical to
    the estimator/solver servers)."""

    def __init__(
        self,
        store: Store,
        address: str = "127.0.0.1:0",
        *,
        server_cert: Optional[bytes] = None,
        server_key: Optional[bytes] = None,
        client_ca: Optional[bytes] = None,
        max_workers: int = 8,
        enable_batch: bool = True,
    ):
        self.store = store
        # (queue, kind filter, dead flag) per subscriber; dead[0] is set when
        # the queue overflows and forces the stream closed
        self._subscribers: list[tuple[queue.Queue, frozenset, list]] = []
        self._lock = threading.Lock()
        store.watch_all(self._fan_out)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.so_reuseport", 0)] + _CHANNEL_OPTIONS,
        )

        from ..utils.tracing import decode_trace_metadata, tracer

        def _ctx(context):
            return decode_trace_metadata(context.invocation_metadata())

        def _subscribe(kinds):
            q: queue.Queue = queue.Queue(maxsize=100_000)
            dead = [False]  # set when the subscriber overflows (too slow)
            # register BEFORE replay so writes landing mid-replay re-deliver
            # (clients dedup on resource_version); the store lock inside
            # list() snapshots each kind
            with self._lock:
                self._subscribers.append((q, kinds, dead))
                bus_subscribers.set(len(self._subscribers))
            return q, dead

        def _unsubscribe(q):
            with self._lock:
                self._subscribers = [
                    s for s in self._subscribers if s[0] is not q
                ]
                bus_subscribers.set(len(self._subscribers))

        def _replay_kinds(kinds):
            # WorkloadTemplates replay FIRST: the Works that follow carry
            # template refs, and a consumer reconciling a replayed Work
            # must find its template already mirrored (alphabetical order
            # would replay "Work" before "WorkloadTemplate")
            names = sorted(
                self.store.kinds(),
                key=lambda k: (k != "WorkloadTemplate", k),
            )
            for kind in names:
                if kinds and kind not in kinds:
                    continue
                yield kind

        def watch(request: pb.WatchRequest, context):
            kinds = frozenset(request.kinds)
            q, dead = _subscribe(kinds)
            # the replay-to-bookmark window is the costly, attributable
            # part of a Watch (the live tail is unbounded by design —
            # GL007's stream exemption). MANUAL span, not a context
            # manager: a generator suspends mid-replay with the handler
            # thread going on to serve other RPCs, so a stack-pushed span
            # would adopt their spans as children
            sp = tracer.server_open_manual(
                "bus.watch", _ctx(context), kinds=len(kinds)
            )
            try:
                replayed = 0
                if request.replay:
                    for kind in _replay_kinds(kinds):
                        for obj in self.store.list(kind):
                            replayed += 1
                            yield pb.Event(
                                type="Added",
                                kind=kind,
                                key=obj.meta.namespaced_name,
                                resource_version=obj.meta.resource_version,
                                object_json=encode_object(obj),
                            )
                sp.attrs["replayed"] = replayed
            finally:
                tracer.close_manual(sp)
            # the Bookmark marks the replay boundary: clients report
            # synced only after it (the list-then-watch initial-sync
            # contract)
            yield pb.Event(type="Bookmark")
            try:
                while context.is_active() and not dead[0]:
                    try:
                        queued_at, fields = q.get(timeout=0.5)
                    except queue.Empty:
                        continue
                    # queue AGE: how long the event sat behind this
                    # subscriber's backlog before the stream drained it —
                    # the per-subscriber half of the backpressure signal
                    # (depth is sampled at fan-out)
                    bus_event_age_seconds.observe(
                        time.monotonic() - queued_at
                    )
                    yield pb.Event(
                        type=fields[0], kind=fields[1], key=fields[2],
                        resource_version=fields[3], object_json=fields[4],
                    )
                # dead: fall through — closing the stream forces the client
                # to reconnect and re-list, healing the dropped-event gap
            finally:
                _unsubscribe(q)

        def watch_batch(request: pb.WatchRequest, context):
            """Batched watch: coalesced EventFrames instead of one gRPC
            message per event. Frames flush at ``bus_batch_max()`` events
            or after ``bus_flush_ms()`` of quiet following the first
            queued event — latency bounded by the timer, throughput by
            the frame size. Event AGE stays per-event (each queue entry
            carries its own enqueue stamp) so coalescing cannot fake a
            low queue age."""
            kinds = frozenset(request.kinds)
            flush_max = max(bus_batch_max(), 1)
            flush_s = max(bus_flush_ms(), 0.0) / 1000.0
            q, dead = _subscribe(kinds)
            sp = tracer.server_open_manual(
                "bus.watch", _ctx(context), kinds=len(kinds), batch=True
            )
            try:
                replayed = 0
                if request.replay:
                    frame: list = []
                    frame_bytes = 0
                    for kind in _replay_kinds(kinds):
                        for obj in self.store.list(kind):
                            replayed += 1
                            doc = encode_object(obj)
                            frame.append(bpb.FrameEvent(
                                type="Added",
                                kind=kind,
                                key=obj.meta.namespaced_name,
                                resource_version=obj.meta.resource_version,
                                object_json=doc,
                            ))
                            frame_bytes += len(doc)
                            if (
                                len(frame) >= flush_max
                                or frame_bytes >= BATCH_BYTE_BUDGET
                            ):
                                bus_batch_size.observe(len(frame))
                                yield bpb.EventFrame(events=frame)
                                frame = []
                                frame_bytes = 0
                    if frame:
                        bus_batch_size.observe(len(frame))
                        yield bpb.EventFrame(events=frame)
                sp.attrs["replayed"] = replayed
            finally:
                tracer.close_manual(sp)
            yield bpb.EventFrame(bookmark=True)
            try:
                while context.is_active() and not dead[0]:
                    try:
                        entry = q.get(timeout=0.5)
                    except queue.Empty:
                        continue
                    entries = [entry]
                    nbytes = len(entry[1][4])
                    flush_at = time.monotonic() + flush_s
                    while (
                        len(entries) < flush_max
                        and nbytes < BATCH_BYTE_BUDGET
                    ):
                        wait = flush_at - time.monotonic()
                        if wait <= 0:
                            # timer expired: drain whatever is already
                            # queued without blocking, then flush
                            try:
                                while (
                                    len(entries) < flush_max
                                    and nbytes < BATCH_BYTE_BUDGET
                                ):
                                    e = q.get_nowait()
                                    entries.append(e)
                                    nbytes += len(e[1][4])
                            except queue.Empty:
                                pass
                            break
                        try:
                            e = q.get(timeout=wait)
                            entries.append(e)
                            nbytes += len(e[1][4])
                        except queue.Empty:
                            break
                    now = time.monotonic()
                    events = []
                    for queued_at, fields in entries:
                        # per-EVENT age (satellite: a frame of N events
                        # records N observations, not 1)
                        bus_event_age_seconds.observe(now - queued_at)
                        events.append(bpb.FrameEvent(
                            type=fields[0], kind=fields[1], key=fields[2],
                            resource_version=fields[3],
                            object_json=fields[4],
                        ))
                    bus_batch_size.observe(len(events))
                    yield bpb.EventFrame(events=events)
            finally:
                _unsubscribe(q)

        def apply(request: pb.ApplyRequest, context):
            with tracer.server_span(
                "bus.apply", _ctx(context), kind=request.kind,
            ) as sp:
                try:
                    obj = decode_object(request.kind, request.object_json)
                    applied = self.store.apply(
                        obj,
                        expected_rv=(
                            request.expected_rv
                            if request.conditional
                            else None
                        ),
                    )
                    return pb.ApplyResponse(
                        resource_version=applied.meta.resource_version
                    )
                except ConflictError as e:
                    # typed over the wire — a CAS loser must see a 409,
                    # not a 500 (and never by pattern-matching error text)
                    sp.attrs["error"] = "conflict"
                    return pb.ApplyResponse(error=str(e), conflict=True)
                except Exception as e:  # noqa: BLE001 — wire surface
                    sp.attrs["error"] = type(e).__name__
                    return pb.ApplyResponse(error=str(e))

        def delete(request: pb.DeleteRequest, context):
            with tracer.server_span(
                "bus.delete", _ctx(context), kind=request.kind,
            ) as sp:
                try:
                    gone = self.store.delete(
                        request.kind, request.key, force=request.force
                    )
                    return pb.DeleteResponse(deleted=gone is not None)
                except Exception as e:  # noqa: BLE001
                    sp.attrs["error"] = type(e).__name__
                    return pb.DeleteResponse(error=str(e))

        def apply_batch(request: "bpb.ApplyBatchRequest", context):
            """One write SET per RPC. Plain applies commit through the
            store's batched path (one lock sweep + one delivery sweep);
            CAS-conditional ops and deletes run individually IN op order
            so a conflict surfaces on exactly the conflicting op while
            the rest of the batch commits (the reference's controller
            writebacks are independent per-object patches)."""
            ops = request.ops
            bus_batch_size.observe(len(ops))
            with tracer.server_span(
                "bus.apply_batch", _ctx(context), ops=len(ops),
            ) as sp:
                results = [None] * len(ops)
                plain: list[tuple[int, object]] = []
                errors = 0

                def flush_plain():
                    if not plain:
                        return
                    objs = [obj for _, obj in plain]
                    failed = {
                        id(obj): exc
                        for obj, exc in self.store.apply_many(objs)
                    }
                    for i, obj in plain:
                        exc = failed.get(id(obj))
                        if exc is not None:
                            results[i] = bpb.BatchResult(error=str(exc))
                        else:
                            results[i] = bpb.BatchResult(
                                resource_version=obj.meta.resource_version
                            )
                    plain.clear()

                for i, op in enumerate(ops):
                    try:
                        if op.delete:
                            flush_plain()
                            gone = self.store.delete(
                                op.kind, op.key, force=op.force
                            )
                            results[i] = bpb.BatchResult(
                                deleted=gone is not None
                            )
                        elif op.conditional:
                            flush_plain()
                            applied = self.store.apply(
                                decode_object(op.kind, op.object_json),
                                expected_rv=op.expected_rv,
                            )
                            results[i] = bpb.BatchResult(
                                resource_version=(
                                    applied.meta.resource_version
                                )
                            )
                        else:
                            plain.append(
                                (i, decode_object(op.kind, op.object_json))
                            )
                    except ConflictError as e:
                        results[i] = bpb.BatchResult(
                            error=str(e), conflict=True
                        )
                    except Exception as e:  # noqa: BLE001 — wire surface
                        results[i] = bpb.BatchResult(error=str(e))
                flush_plain()
                errors = sum(1 for r in results if r.error)
                if errors:
                    sp.attrs["errors"] = errors
                return bpb.ApplyBatchResponse(results=results)

        handlers = {
            "Watch": grpc.unary_stream_rpc_method_handler(
                watch,
                request_deserializer=pb.WatchRequest.FromString,
                response_serializer=pb.Event.SerializeToString,
            ),
            "Apply": grpc.unary_unary_rpc_method_handler(
                apply,
                request_deserializer=pb.ApplyRequest.FromString,
                response_serializer=pb.ApplyResponse.SerializeToString,
            ),
            "Delete": grpc.unary_unary_rpc_method_handler(
                delete,
                request_deserializer=pb.DeleteRequest.FromString,
                response_serializer=pb.DeleteResponse.SerializeToString,
            ),
        }
        # the batched protocol ships behind a registration toggle: an
        # old-server shape (enable_batch=False, the mixed-version tests)
        # leaves ApplyBatch/WatchBatch unregistered so clients get
        # UNIMPLEMENTED and negotiate the unary fallback per connection
        if enable_batch:
            handlers["ApplyBatch"] = grpc.unary_unary_rpc_method_handler(
                apply_batch,
                request_deserializer=bpb.ApplyBatchRequest.FromString,
                response_serializer=bpb.ApplyBatchResponse.SerializeToString,
            )
            handlers["WatchBatch"] = grpc.unary_stream_rpc_method_handler(
                watch_batch,
                request_deserializer=pb.WatchRequest.FromString,
                response_serializer=bpb.EventFrame.SerializeToString,
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        if bool(server_cert) != bool(server_key) or (
            client_ca and not (server_cert and server_key)
        ):
            raise ValueError(
                "incomplete server TLS config: server_cert and server_key are "
                "both required (and client_ca implies them)"
            )
        if server_cert and server_key:
            creds = grpc.ssl_server_credentials(
                [(server_key, server_cert)],
                root_certificates=client_ca,
                require_client_auth=client_ca is not None,
            )
            self.port = self._server.add_secure_port(address, creds)
        else:
            self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"store bus failed to bind {address}")

    def _fan_out(self, event: StoreEvent) -> None:
        with self._lock:
            subs = [
                s for s in self._subscribers
                if not s[1] or event.kind in s[1]
            ]
        if not subs:
            return  # no interested subscriber: stay off the write path
        # encode ONCE per event; queues carry (enqueue stamp, field tuple)
        # and each stream mode builds its own wire message — the stamp is
        # per event so frame coalescing cannot fake a low queue age
        fields = (
            event.type,
            event.kind,
            event.key,
            getattr(event.obj.meta, "resource_version", 0),
            encode_object(event.obj),
        )
        now = time.monotonic()
        depth = 0
        dropped = 0
        for q, _, dead in subs:
            try:
                q.put_nowait((now, fields))
                depth = max(depth, q.qsize())
            except queue.Full:
                # slow subscriber: close its stream so it reconnects and
                # re-lists — silently dropping would leave it stale forever
                dead[0] = True
                dropped += 1
        bus_events.inc(len(subs) - dropped, result="delivered")
        if dropped:
            bus_events.inc(dropped, result="dropped")
        bus_queue_depth.set(depth)

    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self.store.unwatch_all(self._fan_out)
        self._server.stop(grace)


class StoreReplica:
    """Agent-side mirror: a local Store kept consistent by the bus stream;
    writes round-trip through the primary (never applied locally first —
    the echo from the stream is the commit signal, so the replica can never
    diverge from the primary's admission decisions)."""

    def __init__(
        self,
        target: str,
        *,
        kinds: tuple[str, ...] = (),
        root_ca: Optional[bytes] = None,
        client_cert: Optional[bytes] = None,
        client_key: Optional[bytes] = None,
        timeout_seconds: float = 10.0,
    ):
        if (client_cert or client_key) and not (root_ca and client_cert and client_key):
            raise ValueError(
                "incomplete client TLS config: client_cert/client_key require "
                "each other and root_ca"
            )
        if root_ca is not None:
            creds = grpc.ssl_channel_credentials(
                root_certificates=root_ca,
                private_key=client_key,
                certificate_chain=client_cert,
            )
            self._channel = grpc.secure_channel(
                target, creds, options=_CHANNEL_OPTIONS
            )
        else:
            self._channel = grpc.insecure_channel(
                target, options=_CHANNEL_OPTIONS
            )
        self._target = target
        self.store = Store()
        self.kinds = kinds
        self._watch = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Watch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.Event.FromString,
        )
        self._apply = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Apply",
            request_serializer=pb.ApplyRequest.SerializeToString,
            response_deserializer=pb.ApplyResponse.FromString,
        )
        self._delete = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Delete",
            request_serializer=pb.DeleteRequest.SerializeToString,
            response_deserializer=pb.DeleteResponse.FromString,
        )
        self._apply_batch = self._channel.unary_unary(
            f"/{SERVICE_NAME}/ApplyBatch",
            request_serializer=bpb.ApplyBatchRequest.SerializeToString,
            response_deserializer=bpb.ApplyBatchResponse.FromString,
        )
        self._watch_batch = self._channel.unary_stream(
            f"/{SERVICE_NAME}/WatchBatch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=bpb.EventFrame.FromString,
        )
        # batched-protocol negotiation, one pin per RPC surface: None
        # until the first call, False after an UNIMPLEMENTED answer (old
        # server), True after a batched success. A WIRE failure resets
        # the pin to None so the transparently-reconnected channel
        # re-probes before reuse (the returning server may be a
        # different build) — the estimator-channel contract verbatim.
        self.supports_batch: Optional[bool] = None
        self._watch_supports_batch: Optional[bool] = None
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # unified channel resilience (utils.backoff): write-through RPCs
        # carry ONE overall deadline budget with decorrelated-jitter
        # retries; consecutive transport failures open the breaker so a
        # dead bus fast-fails writers (backpressure — the worker queue
        # parks the key) instead of stacking full timeouts
        from ..utils.backoff import default_breaker, default_policy

        self.timeout = timeout_seconds
        # short reset window: the bus is the replica's lifeline and the
        # half-open probe costs one RPC — a restarted bus must re-admit
        # writers within ~a second, not a scrape interval
        self.breaker = default_breaker(f"bus@{target}", reset_default=1.0)
        # env-derived and constant for this replica's lifetime: built once
        # (the write-through path runs per mirrored store write)
        self._policy = default_policy(
            attempt_timeout=timeout_seconds / 2, max_attempts=3
        )
        self._policy_once = default_policy(
            attempt_timeout=timeout_seconds, max_attempts=1
        )

    # -- replication -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import random

        from ..utils.backoff import BackoffPolicy
        from ..utils.faultinject import apply_fault, fault_point

        # reconnect schedule: decorrelated jitter, but capped LOW — the
        # watch stream is how an agent finds out about the whole world,
        # so the de-stampeding must not cost seconds of staleness after
        # a bus restart (the old fixed loop re-listed every 200 ms)
        policy = BackoffPolicy(base=0.05, cap=0.5)
        rng = random.Random()
        sleeps = policy.sleeps(rng)
        while not self._stop.is_set():
            use_batch = (
                bus_batch_max() > 0
                and self._watch_supports_batch is not False
            )
            try:
                apply_fault(
                    fault_point("bus.watch", "Watch"), "bus.watch", "Watch"
                )
                req = pb.WatchRequest(kinds=list(self.kinds), replay=True)
                if use_batch:
                    # frames drain WHOLE: every event of a frame applies
                    # before the loop returns to the wire, so a consumer
                    # settling the runtime sees the coalesced burst as
                    # one enqueue wave rather than N stream wakeups
                    for frame in self._watch_batch(req):
                        if self._stop.is_set():
                            return
                        self._watch_supports_batch = True
                        for ev in frame.events:
                            self._apply_event(ev)
                        if frame.bookmark:
                            self._synced.set()
                            sleeps = policy.sleeps(rng)
                else:
                    for ev in self._watch(req):
                        if self._stop.is_set():
                            return
                        if ev.type == "Bookmark":
                            # replay fully consumed: NOW synced
                            self._synced.set()
                            # healthy stream: reset reconnect schedule
                            sleeps = policy.sleeps(rng)
                            continue
                        self._apply_event(ev)
            except grpc.RpcError as exc:
                if self._stop.is_set():
                    return
                if (
                    use_batch
                    and exc.code() == grpc.StatusCode.UNIMPLEMENTED
                ):
                    # old server: pin the unary fallback for this
                    # connection and retry immediately (the server
                    # ANSWERED — no backoff, the channel is healthy)
                    self._watch_supports_batch = False
                    continue
                # wire failure: reset the negotiation pin so the
                # reconnected channel re-probes (the returning server
                # may be a different build)
                self._watch_supports_batch = None
                self._synced.clear()
                # decorrelated-jitter reconnect (was a fixed 200 ms loop:
                # a partitioned bus saw every replica re-list in lockstep)
                self._stop.wait(next(sleeps))

    def _apply_event(self, ev: pb.Event) -> None:
        if ev.type == "Deleted":
            self.store.delete(ev.kind, ev.key, force=True)
            return
        obj = decode_object(ev.kind, ev.object_json)
        current = self.store.get(ev.kind, ev.key)
        if (
            current is not None
            and current.meta.resource_version >= ev.resource_version
        ):
            return  # replay duplicate after reconnect
        # the replica mirrors the PRIMARY's resource versions so controllers
        # comparing rvs across restarts agree with the source of truth. The
        # local counter is aligned BEFORE apply so the watch event this
        # apply delivers already carries the primary rv (the stream thread
        # is the store's only writer)
        self.store.advance_rv(ev.resource_version)
        self.store.apply(obj)
        obj.meta.resource_version = ev.resource_version

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._synced.wait(timeout)

    # -- write-through -----------------------------------------------------

    def _resilient(self, method: str, stub, req, *, retry: bool = True):
        """One write-through RPC under the unified policy: overall
        deadline budget = ``self.timeout``, decorrelated-jitter retries on
        transport errors only (admission rejections come back in the
        response body and never retry), breaker fast-fail when the bus is
        down — THE backpressure signal: the caller's worker queue parks
        the key instead of this thread stacking timeouts. ``retry=False``
        is for conditional writes: retrying one after a commit-then-
        timeout would surface the caller's OWN committed write as a false
        ConflictError, so those get one bounded attempt."""
        from ..utils.backoff import Deadline, call_with_resilience
        from ..utils.faultinject import apply_fault, fault_point
        from ..utils.tracing import trace_metadata, tracer

        def attempt(timeout: float):
            # one client span per wire ATTEMPT (retries open fresh spans,
            # so a retried write's server spans each re-parent under the
            # attempt that carried them)
            with tracer.span(
                "bus.rpc", remote=True, peer=self._target, method=method,
            ):
                md = trace_metadata(tracer.current_context())
                apply_fault(
                    fault_point("bus.rpc", method), "bus.rpc", method
                )
                try:
                    return stub(req, timeout=timeout, metadata=md)
                except grpc.RpcError:
                    # wire failure on the UNARY path also resets the
                    # batch negotiation pin: a replica pinned to the
                    # unary fallback by an old server must re-probe
                    # after the reconnect (the returning server may be
                    # a batch-capable build)
                    self.supports_batch = None
                    raise

        return call_with_resilience(
            attempt,
            channel="bus",
            policy=self._policy if retry else self._policy_once,
            breaker=self.breaker,
            deadline=Deadline(self.timeout),
            retryable=(grpc.RpcError,),
        )

    _UNSUPPORTED = object()  # sentinel: server answered UNIMPLEMENTED

    def _resilient_batch(self, req, n_ops: int, *, retry: bool = True):
        """One ApplyBatch RPC under the unified policy: ONE Deadline
        budget for the whole batch (not per op), retries only when every
        op is an idempotent unconditional apply/delete (a CAS op inside
        the batch makes the whole RPC retry-once — re-running a
        committed conditional write would surface the caller's OWN
        commit as a false conflict). UNIMPLEMENTED is a NEGOTIATION
        answer, not a failure: the attempt returns the sentinel so the
        breaker records a healthy channel and the caller falls back."""
        from ..utils.backoff import Deadline, call_with_resilience
        from ..utils.faultinject import apply_fault, fault_point
        from ..utils.tracing import trace_metadata, tracer

        def attempt(timeout: float):
            # the client span carries the batch size: the stitched
            # channel table's events-per-message column keys on it
            with tracer.span(
                "bus.rpc", remote=True, peer=self._target,
                method="ApplyBatch", batch=n_ops,
            ):
                md = trace_metadata(tracer.current_context())
                # PR 7 seam: the injection point fires once per BATCH
                # attempt (the batch is the wire unit now)
                apply_fault(
                    fault_point("bus.rpc", "ApplyBatch"),
                    "bus.rpc", "ApplyBatch",
                )
                try:
                    return self._apply_batch(
                        req, timeout=timeout, metadata=md
                    )
                except grpc.RpcError as exc:
                    if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                        self.supports_batch = False
                        return self._UNSUPPORTED
                    self.supports_batch = None  # wire failure: re-probe
                    raise

        return call_with_resilience(
            attempt,
            channel="bus",
            policy=self._policy if retry else self._policy_once,
            breaker=self.breaker,
            deadline=Deadline(self.timeout),
            retryable=(grpc.RpcError,),
        )

    @staticmethod
    def _op_for(obj, expected_rv=None) -> "bpb.BatchOp":
        kind = type(obj).KIND if hasattr(type(obj), "KIND") else "Resource"
        return bpb.BatchOp(
            kind=kind,
            object_json=encode_object(obj),
            conditional=expected_rv is not None,
            expected_rv=expected_rv or 0,
        )

    def apply_many(self, objs, *, expected_rvs=None) -> list:
        """Batched write-through: ships the whole write set as ApplyBatch
        RPCs of at most ``bus_batch_max()`` ops each. Returns ``[(obj,
        exc), ...]`` for per-object failures (the Store.apply_many
        contract — one rejected object must not void the batch).
        ``expected_rvs`` (aligned with ``objs``, None entries
        unconditional) carries CAS preconditions; conflicts come back as
        ConflictError on exactly the conflicting object. Old servers
        negotiate the per-object unary fallback transparently.

        Unlike the in-proc ``Store.apply_many``, the primary's new
        resource_version is NOT stamped onto the caller's objects —
        ``StoreReplica.apply`` semantics: the caller's object is often
        THE replica-mirror object (facade writers mutate in place), and
        pre-stamping it would make ``_apply_event``'s replay dedup
        swallow the write's own echo — the commit signal every watching
        controller converges on."""
        objs = list(objs)
        if not objs:
            return []
        rvs = list(expected_rvs) if expected_rvs is not None else [None] * len(objs)
        batch_max = bus_batch_max()
        errors: list = []
        # index of the first object NOT yet committed batched: an
        # UNIMPLEMENTED answer mid-set (server replaced by an old build
        # between chunks) must fall back for the REMAINDER only —
        # replaying committed chunks unary would duplicate writes and
        # surface the caller's own committed CAS ops as false conflicts
        pending_from = 0
        if batch_max > 0 and self.supports_batch is not False:
            i = 0
            while i < len(objs):
                # cut a batch on COUNT (the env knob) or accumulated
                # object-JSON BYTES (so a few huge manifests cannot
                # push one RPC toward the transport's message cap)
                chunk: list = []
                chunk_rvs: list = []
                ops: list = []
                nbytes = 0
                while (
                    i < len(objs)
                    and len(ops) < batch_max
                    and (not ops or nbytes < BATCH_BYTE_BUDGET)
                ):
                    op = self._op_for(objs[i], rvs[i])
                    ops.append(op)
                    chunk.append(objs[i])
                    chunk_rvs.append(rvs[i])
                    nbytes += len(op.object_json)
                    i += 1
                resp = self._resilient_batch(
                    bpb.ApplyBatchRequest(ops=ops), len(ops),
                    retry=all(rv is None for rv in chunk_rvs),
                )
                if resp is self._UNSUPPORTED:
                    break  # negotiated: the rest goes unary
                self.supports_batch = True
                pending_from = i
                for obj, res in zip(chunk, resp.results):
                    if res.error:
                        errors.append((
                            obj,
                            ConflictError(res.error)
                            if res.conflict
                            else RuntimeError(res.error),
                        ))
            else:
                return errors
        # unary fallback (old server or batching disabled by env) for the
        # not-yet-committed remainder
        for obj, rv in zip(objs[pending_from:], rvs[pending_from:]):
            try:
                self.apply(obj, expected_rv=rv)
            except Exception as exc:  # noqa: BLE001 — per-object verdict
                errors.append((obj, exc))
        return errors

    def delete_many(self, keys) -> list:
        """Batched deletes: ``keys`` is an iterable of (kind, key) or
        (kind, key, force) tuples; returns per-key failures as
        ``[((kind, key), exc), ...]``."""
        keys = [k if len(k) == 3 else (k[0], k[1], False) for k in keys]
        if not keys:
            return []
        batch_max = bus_batch_max()
        errors: list = []
        pending_from = 0  # first key not yet committed batched
        if batch_max > 0 and self.supports_batch is not False:
            for start in range(0, len(keys), batch_max):
                chunk = keys[start:start + batch_max]
                req = bpb.ApplyBatchRequest(ops=[
                    bpb.BatchOp(
                        kind=kind, key=key, delete=True, force=force
                    )
                    for kind, key, force in chunk
                ])
                resp = self._resilient_batch(req, len(chunk))
                if resp is self._UNSUPPORTED:
                    break  # negotiated: the rest goes unary
                self.supports_batch = True
                pending_from = start + len(chunk)
                for (kind, key, _f), res in zip(chunk, resp.results):
                    if res.error:
                        errors.append(
                            ((kind, key), RuntimeError(res.error))
                        )
            else:
                return errors
        for kind, key, force in keys[pending_from:]:
            try:
                self.delete(kind, key, force=force)
            except Exception as exc:  # noqa: BLE001
                errors.append(((kind, key), exc))
        return errors

    def apply(self, obj, *, expected_rv=None) -> int:
        kind = type(obj).KIND if hasattr(type(obj), "KIND") else "Resource"
        resp = self._resilient(
            "Apply",
            self._apply,
            pb.ApplyRequest(
                kind=kind,
                object_json=encode_object(obj),
                conditional=expected_rv is not None,
                expected_rv=expected_rv or 0,
            ),
            retry=expected_rv is None,
        )
        if resp.error:
            if resp.conflict:
                raise ConflictError(resp.error)
            raise RuntimeError(resp.error)
        return resp.resource_version

    def delete(self, kind: str, key: str, force: bool = False) -> bool:
        resp = self._resilient(
            "Delete",
            self._delete,
            pb.DeleteRequest(kind=kind, key=key, force=force),
        )
        if resp.error:
            raise RuntimeError(resp.error)
        return resp.deleted

    def close(self) -> None:
        self._stop.set()
        self._channel.close()
        if self._thread is not None:
            self._thread.join(timeout=2)
