"""Networked store watch bus: the plane's watch/apply surface over gRPC.

Ref: the reference's control plane is nine binaries around a shared
API server whose informer/watch channel carries all state
(pkg/util/fedinformer; the agent consumes it over DCN). This runtime's
Store is in-proc; the bus exports the same two primitives over the wire —
a server-streamed Watch (replay + live events, the informer list-then-
watch contract) and Apply/Delete write-through — so agents and
out-of-process controllers can run a `StoreReplica`: a local Store mirror
fed by the stream whose writes round-trip to the primary.

Objects travel as canonical JSON of the API dataclasses (utils/codec);
decode resolves classes from the kind registry below. Unknown kinds
degrade to generic Resource manifests rather than failing the stream
(forward compatibility across component versions).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from ..api.core import Resource
from ..utils import Store
from ..utils.codec import from_jsonable, to_jsonable
from ..utils.metrics import (
    bus_event_age_seconds,
    bus_events,
    bus_queue_depth,
    bus_subscribers,
)
from ..utils.store import ConflictError, Event as StoreEvent
from .proto import storebus_pb2 as pb

SERVICE_NAME = "karmada_tpu.bus.StoreBus"


def _kind_registry() -> dict[str, type]:
    """kind string -> dataclass, collected from every API surface that
    stores objects (the scheme registry analogue)."""
    registry: dict[str, type] = {}

    def scan(module) -> None:
        import dataclasses

        for name in dir(module):
            cls = getattr(module, name)
            if (
                isinstance(cls, type)
                and dataclasses.is_dataclass(cls)
                and isinstance(getattr(cls, "KIND", None), str)
            ):
                registry[cls.KIND] = cls

    from ..api import autoscaling, cluster, core, networking, policy, work
    from ..controllers import extras
    from ..interpreter import declarative
    from ..search import registry as search_registry

    for mod in (
        core, cluster, policy, work, autoscaling, networking, extras,
        declarative, search_registry,
    ):
        scan(mod)
    registry["Resource"] = Resource
    return registry


_REGISTRY: Optional[dict[str, type]] = None


def kind_registry() -> dict[str, type]:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _kind_registry()
    return _REGISTRY


def encode_object(obj) -> str:
    return json.dumps(to_jsonable(obj), sort_keys=True, separators=(",", ":"))


def decode_object(kind: str, object_json: str):
    cls = kind_registry().get(kind, Resource)
    doc = json.loads(object_json)
    if isinstance(doc, dict) and ("apiVersion" in doc or "api_version" in doc):
        # multi-version seam: a legacy-versioned payload (e.g.
        # work.karmada.io/v1alpha1 bindings) upgrades to the hub shape
        # before decode, so old clients keep working against a hub store
        from ..api.versioning import maybe_upgrade

        doc = maybe_upgrade(kind, doc)
    return from_jsonable(cls, doc)


class StoreBusServer:
    """Serves one Store's watch/apply surface (mTLS contract identical to
    the estimator/solver servers)."""

    def __init__(
        self,
        store: Store,
        address: str = "127.0.0.1:0",
        *,
        server_cert: Optional[bytes] = None,
        server_key: Optional[bytes] = None,
        client_ca: Optional[bytes] = None,
        max_workers: int = 8,
    ):
        self.store = store
        # (queue, kind filter, dead flag) per subscriber; dead[0] is set when
        # the queue overflows and forces the stream closed
        self._subscribers: list[tuple[queue.Queue, frozenset, list]] = []
        self._lock = threading.Lock()
        store.watch_all(self._fan_out)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.so_reuseport", 0)],
        )

        from ..utils.tracing import decode_trace_metadata, tracer

        def _ctx(context):
            return decode_trace_metadata(context.invocation_metadata())

        def watch(request: pb.WatchRequest, context):
            kinds = frozenset(request.kinds)
            q: queue.Queue = queue.Queue(maxsize=100_000)
            dead = [False]  # set when the subscriber overflows (too slow)
            # register BEFORE replay so writes landing mid-replay re-deliver
            # (clients dedup on resource_version); the store lock inside
            # list() snapshots each kind
            with self._lock:
                self._subscribers.append((q, kinds, dead))
                bus_subscribers.set(len(self._subscribers))
            # the replay-to-bookmark window is the costly, attributable
            # part of a Watch (the live tail is unbounded by design —
            # GL007's stream exemption). MANUAL span, not a context
            # manager: a generator suspends mid-replay with the handler
            # thread going on to serve other RPCs, so a stack-pushed span
            # would adopt their spans as children
            sp = tracer.server_open_manual(
                "bus.watch", _ctx(context), kinds=len(kinds)
            )
            try:
                replayed = 0
                if request.replay:
                    for kind in sorted(self.store.kinds()):
                        if kinds and kind not in kinds:
                            continue
                        for obj in self.store.list(kind):
                            replayed += 1
                            yield pb.Event(
                                type="Added",
                                kind=kind,
                                key=obj.meta.namespaced_name,
                                resource_version=obj.meta.resource_version,
                                object_json=encode_object(obj),
                            )
                sp.attrs["replayed"] = replayed
            finally:
                tracer.close_manual(sp)
            # the Bookmark marks the replay boundary: clients report
            # synced only after it (the list-then-watch initial-sync
            # contract)
            yield pb.Event(type="Bookmark")
            try:
                while context.is_active() and not dead[0]:
                    try:
                        queued_at, ev = q.get(timeout=0.5)
                    except queue.Empty:
                        continue
                    # queue AGE: how long the event sat behind this
                    # subscriber's backlog before the stream drained it —
                    # the per-subscriber half of the backpressure signal
                    # (depth is sampled at fan-out)
                    bus_event_age_seconds.observe(
                        time.monotonic() - queued_at
                    )
                    yield ev
                # dead: fall through — closing the stream forces the client
                # to reconnect and re-list, healing the dropped-event gap
            finally:
                with self._lock:
                    self._subscribers = [
                        s for s in self._subscribers if s[0] is not q
                    ]
                    bus_subscribers.set(len(self._subscribers))

        def apply(request: pb.ApplyRequest, context):
            with tracer.server_span(
                "bus.apply", _ctx(context), kind=request.kind,
            ) as sp:
                try:
                    obj = decode_object(request.kind, request.object_json)
                    applied = self.store.apply(
                        obj,
                        expected_rv=(
                            request.expected_rv
                            if request.conditional
                            else None
                        ),
                    )
                    return pb.ApplyResponse(
                        resource_version=applied.meta.resource_version
                    )
                except ConflictError as e:
                    # typed over the wire — a CAS loser must see a 409,
                    # not a 500 (and never by pattern-matching error text)
                    sp.attrs["error"] = "conflict"
                    return pb.ApplyResponse(error=str(e), conflict=True)
                except Exception as e:  # noqa: BLE001 — wire surface
                    sp.attrs["error"] = type(e).__name__
                    return pb.ApplyResponse(error=str(e))

        def delete(request: pb.DeleteRequest, context):
            with tracer.server_span(
                "bus.delete", _ctx(context), kind=request.kind,
            ) as sp:
                try:
                    gone = self.store.delete(
                        request.kind, request.key, force=request.force
                    )
                    return pb.DeleteResponse(deleted=gone is not None)
                except Exception as e:  # noqa: BLE001
                    sp.attrs["error"] = type(e).__name__
                    return pb.DeleteResponse(error=str(e))

        handlers = {
            "Watch": grpc.unary_stream_rpc_method_handler(
                watch,
                request_deserializer=pb.WatchRequest.FromString,
                response_serializer=pb.Event.SerializeToString,
            ),
            "Apply": grpc.unary_unary_rpc_method_handler(
                apply,
                request_deserializer=pb.ApplyRequest.FromString,
                response_serializer=pb.ApplyResponse.SerializeToString,
            ),
            "Delete": grpc.unary_unary_rpc_method_handler(
                delete,
                request_deserializer=pb.DeleteRequest.FromString,
                response_serializer=pb.DeleteResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        if bool(server_cert) != bool(server_key) or (
            client_ca and not (server_cert and server_key)
        ):
            raise ValueError(
                "incomplete server TLS config: server_cert and server_key are "
                "both required (and client_ca implies them)"
            )
        if server_cert and server_key:
            creds = grpc.ssl_server_credentials(
                [(server_key, server_cert)],
                root_certificates=client_ca,
                require_client_auth=client_ca is not None,
            )
            self.port = self._server.add_secure_port(address, creds)
        else:
            self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"store bus failed to bind {address}")

    def _fan_out(self, event: StoreEvent) -> None:
        with self._lock:
            subs = [
                s for s in self._subscribers
                if not s[1] or event.kind in s[1]
            ]
        if not subs:
            return  # no interested subscriber: stay off the write path
        msg = pb.Event(
            type=event.type,
            kind=event.kind,
            key=event.key,
            resource_version=getattr(event.obj.meta, "resource_version", 0),
            object_json=encode_object(event.obj),
        )
        now = time.monotonic()
        depth = 0
        dropped = 0
        for q, _, dead in subs:
            try:
                q.put_nowait((now, msg))
                depth = max(depth, q.qsize())
            except queue.Full:
                # slow subscriber: close its stream so it reconnects and
                # re-lists — silently dropping would leave it stale forever
                dead[0] = True
                dropped += 1
        bus_events.inc(len(subs) - dropped, result="delivered")
        if dropped:
            bus_events.inc(dropped, result="dropped")
        bus_queue_depth.set(depth)

    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self.store.unwatch_all(self._fan_out)
        self._server.stop(grace)


class StoreReplica:
    """Agent-side mirror: a local Store kept consistent by the bus stream;
    writes round-trip through the primary (never applied locally first —
    the echo from the stream is the commit signal, so the replica can never
    diverge from the primary's admission decisions)."""

    def __init__(
        self,
        target: str,
        *,
        kinds: tuple[str, ...] = (),
        root_ca: Optional[bytes] = None,
        client_cert: Optional[bytes] = None,
        client_key: Optional[bytes] = None,
        timeout_seconds: float = 10.0,
    ):
        if (client_cert or client_key) and not (root_ca and client_cert and client_key):
            raise ValueError(
                "incomplete client TLS config: client_cert/client_key require "
                "each other and root_ca"
            )
        if root_ca is not None:
            creds = grpc.ssl_channel_credentials(
                root_certificates=root_ca,
                private_key=client_key,
                certificate_chain=client_cert,
            )
            self._channel = grpc.secure_channel(target, creds)
        else:
            self._channel = grpc.insecure_channel(target)
        self._target = target
        self.store = Store()
        self.kinds = kinds
        self._watch = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Watch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.Event.FromString,
        )
        self._apply = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Apply",
            request_serializer=pb.ApplyRequest.SerializeToString,
            response_deserializer=pb.ApplyResponse.FromString,
        )
        self._delete = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Delete",
            request_serializer=pb.DeleteRequest.SerializeToString,
            response_deserializer=pb.DeleteResponse.FromString,
        )
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # unified channel resilience (utils.backoff): write-through RPCs
        # carry ONE overall deadline budget with decorrelated-jitter
        # retries; consecutive transport failures open the breaker so a
        # dead bus fast-fails writers (backpressure — the worker queue
        # parks the key) instead of stacking full timeouts
        from ..utils.backoff import default_breaker, default_policy

        self.timeout = timeout_seconds
        # short reset window: the bus is the replica's lifeline and the
        # half-open probe costs one RPC — a restarted bus must re-admit
        # writers within ~a second, not a scrape interval
        self.breaker = default_breaker(f"bus@{target}", reset_default=1.0)
        # env-derived and constant for this replica's lifetime: built once
        # (the write-through path runs per mirrored store write)
        self._policy = default_policy(
            attempt_timeout=timeout_seconds / 2, max_attempts=3
        )
        self._policy_once = default_policy(
            attempt_timeout=timeout_seconds, max_attempts=1
        )

    # -- replication -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import random

        from ..utils.backoff import BackoffPolicy
        from ..utils.faultinject import apply_fault, fault_point

        # reconnect schedule: decorrelated jitter, but capped LOW — the
        # watch stream is how an agent finds out about the whole world,
        # so the de-stampeding must not cost seconds of staleness after
        # a bus restart (the old fixed loop re-listed every 200 ms)
        policy = BackoffPolicy(base=0.05, cap=0.5)
        rng = random.Random()
        sleeps = policy.sleeps(rng)
        while not self._stop.is_set():
            try:
                apply_fault(
                    fault_point("bus.watch", "Watch"), "bus.watch", "Watch"
                )
                stream = self._watch(
                    pb.WatchRequest(kinds=list(self.kinds), replay=True)
                )
                for ev in stream:
                    if self._stop.is_set():
                        return
                    if ev.type == "Bookmark":
                        # replay fully consumed: NOW the mirror is synced
                        self._synced.set()
                        # healthy stream: reset the reconnect schedule
                        sleeps = policy.sleeps(rng)
                        continue
                    self._apply_event(ev)
            except grpc.RpcError:
                if self._stop.is_set():
                    return
                self._synced.clear()
                # decorrelated-jitter reconnect (was a fixed 200 ms loop:
                # a partitioned bus saw every replica re-list in lockstep)
                self._stop.wait(next(sleeps))

    def _apply_event(self, ev: pb.Event) -> None:
        if ev.type == "Deleted":
            self.store.delete(ev.kind, ev.key, force=True)
            return
        obj = decode_object(ev.kind, ev.object_json)
        current = self.store.get(ev.kind, ev.key)
        if (
            current is not None
            and current.meta.resource_version >= ev.resource_version
        ):
            return  # replay duplicate after reconnect
        # the replica mirrors the PRIMARY's resource versions so controllers
        # comparing rvs across restarts agree with the source of truth. The
        # local counter is aligned BEFORE apply so the watch event this
        # apply delivers already carries the primary rv (the stream thread
        # is the store's only writer)
        self.store.advance_rv(ev.resource_version)
        self.store.apply(obj)
        obj.meta.resource_version = ev.resource_version

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._synced.wait(timeout)

    # -- write-through -----------------------------------------------------

    def _resilient(self, method: str, stub, req, *, retry: bool = True):
        """One write-through RPC under the unified policy: overall
        deadline budget = ``self.timeout``, decorrelated-jitter retries on
        transport errors only (admission rejections come back in the
        response body and never retry), breaker fast-fail when the bus is
        down — THE backpressure signal: the caller's worker queue parks
        the key instead of this thread stacking timeouts. ``retry=False``
        is for conditional writes: retrying one after a commit-then-
        timeout would surface the caller's OWN committed write as a false
        ConflictError, so those get one bounded attempt."""
        from ..utils.backoff import Deadline, call_with_resilience
        from ..utils.faultinject import apply_fault, fault_point
        from ..utils.tracing import trace_metadata, tracer

        def attempt(timeout: float):
            # one client span per wire ATTEMPT (retries open fresh spans,
            # so a retried write's server spans each re-parent under the
            # attempt that carried them)
            with tracer.span(
                "bus.rpc", remote=True, peer=self._target, method=method,
            ):
                md = trace_metadata(tracer.current_context())
                apply_fault(
                    fault_point("bus.rpc", method), "bus.rpc", method
                )
                return stub(req, timeout=timeout, metadata=md)

        return call_with_resilience(
            attempt,
            channel="bus",
            policy=self._policy if retry else self._policy_once,
            breaker=self.breaker,
            deadline=Deadline(self.timeout),
            retryable=(grpc.RpcError,),
        )

    def apply(self, obj, *, expected_rv=None) -> int:
        kind = type(obj).KIND if hasattr(type(obj), "KIND") else "Resource"
        resp = self._resilient(
            "Apply",
            self._apply,
            pb.ApplyRequest(
                kind=kind,
                object_json=encode_object(obj),
                conditional=expected_rv is not None,
                expected_rv=expected_rv or 0,
            ),
            retry=expected_rv is None,
        )
        if resp.error:
            if resp.conflict:
                raise ConflictError(resp.error)
            raise RuntimeError(resp.error)
        return resp.resource_version

    def delete(self, kind: str, key: str, force: bool = False) -> bool:
        resp = self._resilient(
            "Delete",
            self._delete,
            pb.DeleteRequest(kind=kind, key=key, force=force),
        )
        if resp.error:
            raise RuntimeError(resp.error)
        return resp.deleted

    def close(self) -> None:
        self._stop.set()
        self._channel.close()
        if self._thread is not None:
            self._thread.join(timeout=2)
