"""Generated protobuf messages for the store watch bus."""

from . import storebus_pb2  # noqa: F401
