"""Messages for storebus_batch.proto, built without protoc.

grpc_tools/protoc are not in the image (see estimator_batch_pb2.py for the
precedent), so the FileDescriptorProto is constructed programmatically and
registered in the default pool — byte-for-byte the wire format protoc
would emit for karmada_tpu/bus/proto/storebus_batch.proto, which remains
the human-readable contract. KEEP THE TWO IN SYNC.

The columnar bus protocol (ISSUE 11): ``ApplyBatch`` carries many
write-through operations per RPC (per-op resourceVersion/CAS results
back), and ``WatchBatch`` streams ``EventFrame`` messages — coalesced
watch events flushed by count or a few-ms timer — instead of one gRPC
message per event. Both are negotiated per connection exactly like the
estimator batch protocol: old servers answer UNIMPLEMENTED and the client
pins the unary fallback until the channel reconnects.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_PKG = "karmada_tpu.bus"
_FILE = "karmada_tpu/bus/proto/storebus_batch.proto"

_F = descriptor_pb2.FieldDescriptorProto


def _message(fdp, name: str, *fields):
    msg = fdp.message_type.add()
    msg.name = name
    for number, fname, ftype, repeated in fields:
        f = msg.field.add()
        f.name = fname
        f.number = number
        f.label = _F.LABEL_REPEATED if repeated else _F.LABEL_OPTIONAL
        if isinstance(ftype, str):  # message-typed field
            f.type = _F.TYPE_MESSAGE
            f.type_name = f".{_PKG}.{ftype}"
        else:
            f.type = ftype
    return msg


def _build() -> "descriptor_pool.DescriptorPool":
    pool = descriptor_pool.Default()
    try:  # already registered (re-import through a second path)
        pool.FindFileByName(_FILE)
        return pool
    except KeyError:
        pass
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = _FILE
    fdp.package = _PKG
    fdp.syntax = "proto3"
    # one write-through operation: an apply (optionally CAS-conditional)
    # or, with delete=true, a delete of (kind, key). A batch MUST NOT
    # carry two ops for the same (kind, key): per-op results are keyed by
    # position and the server does not define cross-op ordering within
    # one batch (producers flush deduplicated write sets).
    _message(
        fdp, "BatchOp",
        (1, "kind", _F.TYPE_STRING, False),
        (2, "object_json", _F.TYPE_STRING, False),
        (3, "conditional", _F.TYPE_BOOL, False),
        (4, "expected_rv", _F.TYPE_UINT64, False),
        (5, "delete", _F.TYPE_BOOL, False),
        (6, "key", _F.TYPE_STRING, False),
        (7, "force", _F.TYPE_BOOL, False),
    )
    _message(
        fdp, "ApplyBatchRequest",
        (1, "ops", "BatchOp", True),
    )
    # positionally aligned with the request ops; CAS losers come back as
    # conflict=true on exactly the conflicting op (the rest of the batch
    # commits — the reference's controller writebacks are independent
    # per-object patches)
    _message(
        fdp, "BatchResult",
        (1, "resource_version", _F.TYPE_UINT64, False),
        (2, "error", _F.TYPE_STRING, False),
        (3, "conflict", _F.TYPE_BOOL, False),
        (4, "deleted", _F.TYPE_BOOL, False),
    )
    _message(
        fdp, "ApplyBatchResponse",
        (1, "results", "BatchResult", True),
    )
    # one coalesced watch frame: same fields as storebus.proto Event,
    # self-contained so the batch file has no cross-file descriptor
    # dependency. bookmark=true marks the replay boundary (the frame may
    # carry the tail of the replay in the same message).
    _message(
        fdp, "FrameEvent",
        (1, "type", _F.TYPE_STRING, False),
        (2, "kind", _F.TYPE_STRING, False),
        (3, "key", _F.TYPE_STRING, False),
        (4, "resource_version", _F.TYPE_UINT64, False),
        (5, "object_json", _F.TYPE_STRING, False),
    )
    _message(
        fdp, "EventFrame",
        (1, "events", "FrameEvent", True),
        (2, "bookmark", _F.TYPE_BOOL, False),
    )
    pool.Add(fdp)
    return pool


def _cls(pool, name: str):
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"{_PKG}.{name}")
    )


_pool = _build()

BatchOp = _cls(_pool, "BatchOp")
ApplyBatchRequest = _cls(_pool, "ApplyBatchRequest")
BatchResult = _cls(_pool, "BatchResult")
ApplyBatchResponse = _cls(_pool, "ApplyBatchResponse")
FrameEvent = _cls(_pool, "FrameEvent")
EventFrame = _cls(_pool, "EventFrame")
