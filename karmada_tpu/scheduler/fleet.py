"""Device-resident fleet scheduling: the informer->cache analogue.

Ref: pkg/scheduler/cache/cache.go:42-62 — the reference keeps a cluster
cache fed by informers so each scheduling attempt touches only deltas.
This module is that idea taken device-side: per-binding state (placement
slot, request profile slot, previous assignment sites, replicas, flags)
lives in HBM between scheduling passes, and each pass is

    host delta scatter  ->  ONE fused XLA dispatch  ->  ONE compact fetch.

Why this exists: round 1's engine packed every BindingProblem from scratch
per pass (Python loops over sparse entries + per-chunk np.pad + per-chunk
device syncs), which capped the engine at ~4k bindings/s while the kernel
alone did 100k x 5k in 0.74 s. The fleet table removes all per-pass O(B)
host packing for unchanged bindings and all but one device round-trip.

Tunnel-aware design (measured on the v5e tunnel: ~25-30 MB/s transfers,
~100 ms fixed cost per round-trip):

- all per-row state is gathered ON DEVICE from resident arrays (`rows` is
  the only per-pass index upload, and the all-rows storm case keeps even
  that cached on device);
- placement/taint/static-weight masks are interned per unique placement
  and gathered per chunk with plain [B]-index row gathers (re-probed on
  the current backend across U=2..3500: compiles cleanly and runs at
  bandwidth; the historical one-hot-matmul workaround for a scan-gather
  compile hang remains in ops.estimate.gather_profile_rows for other
  callers);
- DELTA FETCH: the device keeps every row's previous (site << 8 | count)
  entry vector resident; a pass ships home only the rows whose vector
  CHANGED (plus one meta word per row), against a host-side mirror of the
  entry table. A steady rebalance storm re-divides all 100k bindings on
  device but fetches ~0.2 MB; a full availability-drift churn pass ships
  only the ~half of rows whose placements actually moved.
- per-row entry vectors are compacted from the dense assignment by ONE
  ascending single-operand sort (the packed word orders by site) — measured
  0.29s at 100k x 5k on the v5e vs 1.8s for gather-based position search
  and 2.5s for scatter compaction; the dispense itself finds its
  largest-remainder bonus threshold by binary search instead of top_k
  (lax.top_k measured SLOWER than a full sort on this backend);
- feasible bitsets ride a second, lazily-fetched output only when the
  batch contains Duplicated or zero-replica bindings.

Eligibility: a binding rides the fleet path when its placement has a single
affinity term, no spread-constraint selection (or the static-weight ignore
rule, select_clusters.go:63-78), no eviction tasks, <= K_PREV previous
sites, and (for Divided strategies) replicas <= MAX_REPLICAS_FAST so the
per-row entry-vector bound holds. Everything else takes the general host
path — the two paths are differentially fuzz-tested for identical
placements.
"""

from __future__ import annotations

import logging

from functools import partial
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.divide import AGGREGATED, DUPLICATED as S_DUPLICATED, _divide_batch
from ..ops.estimate import MAX_INT32, merge_estimates
from ..ops.explain import explain_pass as _explain_pass
from ..ops.preempt import preempt_select as _preempt_select
from ..ops.quota import (
    quota_admit as _quota_admit,
    quota_cluster_caps as _quota_cluster_caps,
)

log = logging.getLogger("karmada_tpu")

#: trace-key prefix -> kernel family, for the per-bucket compile counter
#: (karmada_tpu_kernel_compiles_total) every _mark_trace feeds
_TRACE_KERNELS = {
    "L": "fleet_solve",
    "A": "fleet_pass",
    "E": "fleet_entries",
    "B": "fleet_bits",
    "S": "state_scatter",
    "G": "meta_gather",
}

K_PREV = 32  # max previous-assignment sites on the fast path (small fleets
# legitimately spread one binding over dozens of clusters; rows beyond this
# take the general host path)
MAX_REPLICAS_FAST = 128  # divided-strategy replica cap (bounds the entry vector)
MAX_SLOTS = 8192  # unique placements/gvks/profiles FLOOR before slot
# eviction engages. Sizing (bitpacked layout): a slot costs two packed
# mask planes (2*ceil(C/8) uint8) + an int32 static-weight row (4C) ~
# 21 KB at C=5000; plain row gathers make the per-pass cost independent
# of U. The EFFECTIVE cap scales with the cluster count up to
# CP_TABLE_MAX_BYTES (a 5k-cluster fleet carries the MAX_SLOTS_HARD
# 65536 uniques within ~1.4 GB), and crossing 3/4 of it first evicts
# slots no live row references — only a fleet whose LIVE rows reference
# more uniques than the budget allows falls back to a rebuild per call.
CP_TABLE_MAX_BYTES = 1536 << 20  # device cp-table budget (HBM)
MAX_SLOTS_HARD = 65536  # interning-dict / host-staging sanity bound
E_ROUND = 1 << 18  # entry-buffer quantum (bounds trace churn)


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _cap_round(v: int) -> int:
    """Entry-buffer quantization: powers of two (floor 1024) up to the
    quantum, then QUARTER-OCTAVE buckets (5/8, 6/8, 7/8, 8/8 of the next
    power of two). Fixed-size quanta broke at scale: a 32M-entry churn
    demand drifting ±1% per pass landed in a different 256k-multiple each
    time, recompiling the (minutes-long at 1M rows) solve every pass;
    quarter-octaves bound the overshoot at 25% with 4 traces per octave."""
    v = max(v, 1)
    if v <= E_ROUND:
        return _pow2(max(v, 1024))
    p = _pow2(v)  # v in (p/2, p]
    for frac in (5, 6, 7):
        if v * 8 <= p * frac:
            return p * frac // 8
    return p


def _slot_cap(n: int) -> int:
    """Device slot-table capacity: pow2 up to 8192, then multiples of 4096
    — pow2 beyond that wastes up to half the (hundreds-of-MB) cp table,
    while the coarse quantum keeps the solve's trace count bounded."""
    return _pow2(max(n, 16)) if n <= 8192 else -(-n // 4096) * 4096


def _pack21(stream, e_cap: int):
    """Pack int32 values < 2^21 (site<<8|count with site < 2^13) into a
    21-bit little-endian bitstream: 2.625 bytes/entry instead of 3 — the
    churn wire is tunnel-bandwidth-bound, so every bit shipped is pass
    latency. Each output byte draws from at most two adjacent fields
    (field width 21 > 8), so two static gathers + shifts produce it."""
    nb = (e_cap * 21 + 7) // 8
    # index math as traced iota, NOT host numpy: numpy arrays close over
    # the trace as dense HLO literals — three nb-length constants made the
    # serialized module ~24 B per e_cap entry (123 MB at the 100k tier's
    # 5M-entry cap, 1.3 GB at the 1M tier — HTTP 413 on the tunnel's
    # remote-compile endpoint). As iota the module is ~0.1 MB at any cap.
    idx = jnp.arange(nb, dtype=jnp.int64) * 8
    k1 = (idx // 21).astype(jnp.int32)
    off = (idx - 21 * k1).astype(jnp.int32)
    s_ext = jnp.concatenate([stream, jnp.zeros((1,), jnp.int32)])
    lo = s_ext[k1] >> off
    hi = s_ext[jnp.minimum(k1 + 1, e_cap)] << (21 - off)
    return ((lo | hi) & 0xFF).astype(jnp.uint8)


def _entry_wire(stream, e_cap: int, pack21: bool):
    """The entry stream's byte-wire serialization (shared by both solve
    kernels so the format cannot drift): 21-bit packed (+3 pad bytes for
    the host's 4-byte-window decoder) or plain 3-byte entries."""
    if pack21:
        return jnp.concatenate(
            [_pack21(stream, e_cap), jnp.zeros((3,), jnp.uint8)]
        )
    return jnp.stack(
        [stream & 0xFF, (stream >> 8) & 0xFF, (stream >> 16) & 0xFF],
        axis=-1,
    ).astype(jnp.uint8).reshape(-1)


# --------------------------------------------------------------------------
# fused solve
# --------------------------------------------------------------------------


def _unpack_bits(bits_u8, c: int):
    """uint8[B, W8] (little bit order) -> bool[B, C]: the device-side
    inverse of np.packbits(bitorder='little'). Pure shifts/compares — the
    cost is one [B, C] elementwise pass, bought back eightfold in gather
    bandwidth."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    x = (bits_u8[:, :, None] >> shifts[None, None, :]) & jnp.uint8(1)
    return x.reshape(bits_u8.shape[0], -1)[:, :c] != 0


def _row_masks(cp_bits, cp_static, gvk_bits, incomplete_en, cpc, gvc, psc,
               pcc, vc, chunk: int, c: int):
    """Per-chunk previous-assignment scatter + THE feasibility algebra,
    shared by every kernel that needs it (_fleet_solve, _fleet_pass,
    _fleet_bits) so the mask expression cannot drift between the solve
    and the lazily-computed feasibility bitsets. Returns (prev, static_w,
    feasible); callers apply their own sharding constraints.

    The affinity and taint planes ship BITPACKED (uint8, 8 clusters per
    byte): the per-row cp gather was the second-largest term of the 1M
    steady pass (60 KB/row as int32 planes -> 21 KB packed+static,
    measured 0.57 s -> ~0.2 s over 245 chunks), and the slot table's HBM
    footprint drops ~3x with it."""
    prev = (
        jnp.zeros((chunk, c), jnp.int32)
        .at[jnp.arange(chunk)[:, None], psc]
        .add(pcc)
    )
    prev_mask = prev > 0
    # plain [B]-index row gathers: re-probed on the current backend at
    # U in {2..3500} x W in {5k, 15k} — compiles fine and runs at
    # bandwidth vs 0.29s+ for the one-hot matmul at heterogeneous U (the
    # matmul workaround predates this backend; ops.estimate.
    # gather_profile_rows keeps it for other callers)
    bits = cp_bits[cpc]  # [chunk, 2*W8] u8
    w8 = bits.shape[1] // 2
    aff_ok = _unpack_bits(bits[:, :w8], c)  # affinity & spread-field
    taint_ok = _unpack_bits(bits[:, w8:], c)
    static_w = cp_static[cpc]  # [chunk, C] i32
    gvk_ok = _unpack_bits(gvk_bits[gvc], c)
    feasible = (
        aff_ok
        & (gvk_ok | (prev_mask & incomplete_en[None, :]))
        & (taint_ok | prev_mask)  # taints (leniency)
        & vc[:, None]
    )
    return prev, static_w, feasible


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_chunks", "k_out", "k_res", "e_cap", "wide", "fast",
        "has_aggregated", "all_rows", "mesh", "shard_c",
        "pack21",
    ),
    donate_argnames=("prev_entries",),
)
def _fleet_solve(
    cp_bits,  # uint8[U, 2*W8]: bitpacked [aff&spread_field | taint]
    cp_static,  # int32[U, C]: static weights
    gvk_bits,  # uint8[G, W8] bitpacked enablement masks
    prof_table,  # int32[P, C] general availability (-1 = no answer)
    incomplete_en,  # bool[C] — ~CompleteAPIEnablements
    rows,  # int32[n_pad] table rows (-1 = padding)
    cp_idx, gvk_idx, prof_idx,  # int32[cap]
    replicas, strategy,  # int32[cap]
    fresh,  # bool[cap]
    prev_sites, prev_counts,  # int32[cap, K_PREV]
    prev_entries,  # int32[cap, k_res] — last pass's entry rows (delta
    # base). DONATED: the updated resident aliases this buffer, so the
    # persistent entry base never double-buffers in HBM and a settle
    # drain re-uses the same device allocation pass after pass.
    *,
    chunk: int,
    n_chunks: int,
    k_out: int,
    k_res: int,  # resident entry width >= k_out (stable across batches)
    e_cap: int,
    wide: bool,
    fast: Optional[tuple],
    has_aggregated: bool,
    all_rows: bool,
    mesh=None,  # jax.sharding.Mesh with axes ("b", "c") — None = single-device
    shard_c: bool = False,  # also shard the cluster axis over mesh axis "c"
    pack21: bool = False,  # 21-bit entry packing (site < 2^13)
):
    c = cp_static.shape[1]
    c_ax = "c" if (mesh is not None and shard_c) else None

    def shard(a, *axes):
        # sharding constraints on the per-chunk working set: GSPMD
        # partitions the row (and optionally cluster) axis across the mesh;
        # the dispense sorts along a sharded cluster axis induce c-axis
        # all-gathers — the same collective structure as
        # parallel.solver.make_sharded_step, proven placement-identical by
        # tests/test_parallel_graft.py
        if mesh is None:
            return a
        return lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(*axes))
        )

    valid = rows >= 0
    r = jnp.maximum(rows, 0)
    # compact per-pass state ([n_pad]), gathered outside the scan
    cp = cp_idx[r]
    gv = gvk_idx[r]
    pf = prof_idx[r]
    reps = jnp.where(valid, replicas[r], 0)
    st = strategy[r]
    fr = fresh[r] & valid
    ps = prev_sites[r]
    pc = jnp.where(valid[:, None], prev_counts[r], 0)

    def body(carry, i):
        sl = lambda a: lax.dynamic_slice_in_dim(a, i * chunk, chunk, axis=0)
        cpc, gvc, pfc = sl(cp), sl(gv), sl(pf)
        repsc, stc, frc, vc = sl(reps), sl(st), sl(fr), sl(valid)
        psc, pcc = sl(ps), sl(pc)
        repsc, stc, frc, vc = (
            shard(repsc, "b"), shard(stc, "b"), shard(frc, "b"),
            shard(vc, "b"),
        )
        cpc, gvc, pfc = shard(cpc, "b"), shard(gvc, "b"), shard(pfc, "b")
        psc, pcc = shard(psc, "b", None), shard(pcc, "b", None)
        # mask composition — same algebra as TensorScheduler._pack_chunk,
        # via the shared helper every feasibility consumer uses
        prev, static_w, feasible = _row_masks(
            cp_bits, cp_static, gvk_bits, incomplete_en, cpc, gvc, psc,
            pcc, vc, chunk, c,
        )
        prev = shard(prev, "b", c_ax)
        feasible = shard(feasible, "b", c_ax)
        general = prof_table[pfc]
        avail = shard(merge_estimates(repsc, (general,)), "b", c_ax)
        assignment, unsched = _divide_batch(
            stc, repsc, feasible, static_w, avail, prev, frc,
            has_aggregated, wide, fast,
        )
        # Duplicated rows are represented by the feasible bitset (their
        # count is just `replicas` everywhere feasible); zero their
        # dense rows so the entry stream carries only Divided placements
        assignment = shard(
            jnp.where((stc == S_DUPLICATED)[:, None], 0, assignment),
            "b", c_ax,
        )
        # compact each row's placed sites (<= k_out of them: every placed
        # site holds >= 1 of <= max-replicas <= k_out replicas): the packed
        # (site << 8 | count) word sorts by site, so one ascending
        # single-operand sort + a static prefix slice IS the per-row entry
        # vector. Measured on the v5e at C=5k: sort 0.29s vs 1.8s for
        # binary-search position extraction (batched gathers) and 2.5s for
        # scatter compaction.
        selected = assignment > 0
        n_placed = selected.sum(axis=1).astype(jnp.int32)
        idxs = jnp.arange(c, dtype=jnp.int32)[None, :]
        packed_full = jnp.where(
            selected, (idxs << 8) | assignment, jnp.int32(2**31 - 1)
        )
        srt = lax.sort(packed_full, is_stable=False)[:, :k_out]
        entries = shard(jnp.where(srt == 2**31 - 1, 0, srt), "b", None)
        has_cand = feasible.any(axis=1)
        return carry, (entries, n_placed.astype(jnp.int32), unsched, has_cand)

    _, outs = lax.scan(body, 0, jnp.arange(n_chunks))
    entries = outs[0].reshape(-1, k_out)  # [n_pad, k_out]
    n_placed = outs[1].reshape(-1)
    unsched = outs[2].reshape(-1)
    has_cand = outs[3].reshape(-1)

    # delta detection: a row whose entry vector is identical to last pass's
    # ships nothing — the host already holds its entries. Steady storms
    # fetch ~zero bytes; the changed bit rides the meta word. The all-rows
    # storm (rows == iota) reads and writes the resident base as contiguous
    # slices — the general row gather/scatter costs ~0.17s/pass at 100k.
    # The resident base is k_res wide (grow-only across batches) so a
    # straggler batch with a smaller per-batch k_out neither wipes the base
    # nor leaves stale columns: its vectors are zero-padded to k_res.
    if k_res > k_out:
        entries = jnp.pad(entries, ((0, 0), (0, k_res - k_out)))
    if all_rows:
        # int32 offsets: the SPMD partitioner mixes the shard-offset
        # arithmetic (s32) with the slice start, and an x64-default s64
        # start fails HLO verification on the row-sharded resident
        z32 = jnp.int32(0)
        pe = lax.dynamic_slice_in_dim(
            prev_entries, z32, entries.shape[0], 0
        )
        changed = (entries != pe).any(axis=1) & valid
        new_resident = lax.dynamic_update_slice_in_dim(
            prev_entries, entries, z32, 0
        )
    else:
        changed = (entries != prev_entries[r]).any(axis=1) & valid
        new_resident = prev_entries.at[
            jnp.where(valid, r, prev_entries.shape[0])
        ].set(entries, mode="drop")
    # pin the updated resident to the layout it was allocated with
    # (row-sharded under a mesh): donation aliases input->output only
    # when the shardings agree, so the constraint is what keeps the
    # persistent base buffer-stable across passes
    new_resident = shard(new_resident, "b", None)

    # compact changed rows' (site, count) pairs into one row-major entry
    # stream; zero entries are the padding the per-row vectors carry.
    # The compaction is a GLOBAL prefix scan — replicate its inputs
    # explicitly: without the constraint, the resident's row sharding
    # back-propagates into the cumsum/scatter and the partitioned scan
    # emits a corrupt stream (observed on the CPU SPMD partitioner:
    # changed-entry totals beyond the theoretical bound)
    entries_w = shard(entries, None, None)
    changed_w = shard(changed, None)
    valid_e = ((entries_w > 0) & changed_w[:, None]).reshape(-1)
    offs = jnp.cumsum(valid_e.astype(jnp.int32)) - valid_e
    total = offs[-1] + valid_e[-1].astype(jnp.int32)
    packed = entries_w.reshape(-1)
    write = jnp.where(valid_e & (offs < e_cap), offs, e_cap)
    buf = jnp.zeros((e_cap + 1,), jnp.int32).at[write].set(packed)
    stream = buf[:e_cap]

    # one metadata word per row:
    # n_placed | unsched<<8 | has_cand<<9 | changed<<10
    meta = (
        n_placed
        | (unsched.astype(jnp.int32) << 8)
        | (has_cand.astype(jnp.int32) << 9)
        | (changed_w.astype(jnp.int32) << 10)
    )
    c_total = cp_static.shape[1]
    if c_total <= 0xFFFF:
        # byte wire: transfer bytes are the pass's budget, and a packed
        # entry fits 3 bytes when the site index fits 16 bits (counts are
        # <= MAX_REPLICAS_FAST < 256, meta words < 2^11). Bytes are
        # decomposed with shifts, not bitcasts, so the layout is
        # endianness-independent.
        total_u8 = jnp.stack(
            [(total >> s) & 0xFF for s in (0, 8, 16, 24)]
        ).astype(jnp.uint8)
        meta_u8 = jnp.stack(
            [meta & 0xFF, (meta >> 8) & 0xFF], axis=-1
        ).astype(jnp.uint8).reshape(-1)
        e_u8 = _entry_wire(stream, e_cap, pack21)
        flat = jnp.concatenate([total_u8, meta_u8, e_u8])
    else:
        flat = jnp.concatenate([total[None], meta, stream])
    return flat, new_resident


# --------------------------------------------------------------------------
# two-phase solve: pass kernel (A) + changed-rows entry kernel (B)
# --------------------------------------------------------------------------
#
# The single-dispatch _fleet_solve above compacts EVERY row's entry vector
# with a full-width [chunk, C] sort each pass — measured ~0.29s of the
# ~0.41s kernel at 100k x 5k, paid even when a steady pass changes nothing.
# The two-phase form keeps the DENSE assignment resident (uint8[cap, C])
# and splits the pass:
#
#   A: solve + diff against the dense resident + update it; wire home is
#      4B changed-count + a changed-row BITMASK (n/8 bytes) + the changed
#      rows' meta words (tuned cap). No sort, no entry stream: a steady
#      100k pass ships ~13 KB and runs no compaction at all.
#   B: only when rows changed — gather exactly the changed rows from the
#      dense resident and sort-compact THEM into the entry stream. The
#      entry cap is sized EXACTLY from the changed metas the host already
#      holds (sum of n_placed), so the overflow->rerun double dispatch of
#      the tuned single-phase path is structurally impossible here.
#
# The legacy single-dispatch path remains for tables whose dense mirror
# would not fit the HBM budget (cap x C bytes), e.g. the 1M-binding tier.

#: dense-resident budget: above this, FleetTable uses the legacy
#: entry-resident single-dispatch path (a 1M x 5k table's 5.2 GB mirror
#: plus the solve working set over-commits a 16 GB part in practice —
#: measured RESOURCE_EXHAUSTED on the v5e). Override via
#: KARMADA_TPU_DENSE_BUDGET (bytes) on larger parts.
def _dense_budget() -> int:
    import os

    raw = os.environ.get("KARMADA_TPU_DENSE_BUDGET", "")
    try:
        # 6 GiB default: a v5e chip carries 16 GB HBM and the dense
        # resident is the only O(rows x clusters) tenant — at 6 GiB the
        # 1M x 5k tier rides the dense+delta path (steady 4.5s -> 2.3s,
        # churn 15s -> 12s measured) and tables beyond it (>1.2M rows at
        # 5k clusters) fall back to the entry-resident legacy path.
        return int(raw) if raw else 6 << 30
    except ValueError:
        import sys

        print(
            f"# KARMADA_TPU_DENSE_BUDGET={raw!r} is not an integer byte "
            "count; using the 6 GiB default",
            file=sys.stderr,
        )
        return 6 << 30


DENSE_RESIDENT_MAX_BYTES = _dense_budget()
M_ROUND = 1 << 15  # changed-meta buffer quantum (bounds trace churn)
D_ROUND = 1 << 16  # cell-delta buffer quantum (bounds trace churn)
D_FLOOR = 8192  # cell-delta floor: 24 KB of wire on every steady pass
SHRINK_SUSTAIN = 5  # passes a frozen shrink must stay desired to compile


def d_round(v: int) -> int:
    v = max(v, 1)
    return -(-v // D_ROUND) * D_ROUND if v > D_FLOOR else D_FLOOR


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_chunks", "wide", "fast", "has_aggregated",
        "all_rows", "m_cap", "d_cap", "mesh", "shard_c",
    ),
    donate_argnames=("res_dense", "res_meta"),
)
def _fleet_pass(
    cp_bits,  # uint8[U, 2*W8]: bitpacked [aff&spread_field | taint]
    cp_static,  # int32[U, C]: static weights
    gvk_bits,  # uint8[G, W8] bitpacked enablement masks
    prof_table,  # int32[P, C] general availability (-1 = no answer)
    incomplete_en,  # bool[C] — ~CompleteAPIEnablements
    rows,  # int32[n_pad] table rows (-1 = padding)
    cp_idx, gvk_idx, prof_idx,  # int32[cap]
    replicas, strategy,  # int32[cap]
    fresh,  # bool[cap]
    prev_sites, prev_counts,  # int32[cap, K_PREV]
    res_dense,  # uint8[cap, C] last pass's dense assignment (donated)
    res_meta,  # int32[cap] last pass's meta words (donated)
    *,
    chunk: int,
    n_chunks: int,
    wide: bool,
    fast: Optional[tuple],
    has_aggregated: bool,
    all_rows: bool,
    m_cap: int,
    d_cap: int = 0,
    mesh=None,
    shard_c: bool = False,
):
    """Phase A: divide every row, diff against the dense resident, ship the
    changed bitmask + changed metas — and, when ``d_cap`` > 0, the CELL
    deltas of changed rows (site<<9 | newcount+1, site-ascending per row)
    so a typical churn pass (a few cells move per changed row) needs no
    phase B at all. Returns (flat_wire_u8, changed_rowbuf, new_res_dense,
    new_res_meta); feasibility bitsets are _fleet_bits' separate, lazily
    dispatched job."""
    c = cp_static.shape[1]
    cap = res_dense.shape[0]
    c_ax = "c" if (mesh is not None and shard_c) else None
    # per-row delta slots: 62 exact + the 63 overflow sentinel fit the
    # meta word's 6 spare bits; rows with more changed cells fall back to
    # a full-row phase B fetch
    d_slots = min(64, c)

    def shard(a, *axes):
        if mesh is None:
            return a
        return lax.with_sharding_constraint(a, NamedSharding(mesh, P(*axes)))

    valid = rows >= 0
    r = jnp.maximum(rows, 0)
    cp = cp_idx[r]
    gv = gvk_idx[r]
    pf = prof_idx[r]
    reps = jnp.where(valid, replicas[r], 0)
    st = strategy[r]
    fr = fresh[r] & valid
    ps = prev_sites[r]
    pc = jnp.where(valid[:, None], prev_counts[r], 0)

    def body(carry, i):
        rd, rm = carry
        sl = lambda a: lax.dynamic_slice_in_dim(a, i * chunk, chunk, axis=0)
        cpc, gvc, pfc = sl(cp), sl(gv), sl(pf)
        repsc, stc, frc, vc = sl(reps), sl(st), sl(fr), sl(valid)
        psc, pcc = sl(ps), sl(pc)
        rc = sl(r)
        repsc, stc, frc, vc = (
            shard(repsc, "b"), shard(stc, "b"), shard(frc, "b"),
            shard(vc, "b"),
        )
        cpc, gvc, pfc = shard(cpc, "b"), shard(gvc, "b"), shard(pfc, "b")
        psc, pcc = shard(psc, "b", None), shard(pcc, "b", None)
        prev, static_w, feasible = _row_masks(
            cp_bits, cp_static, gvk_bits, incomplete_en, cpc, gvc, psc,
            pcc, vc, chunk, c,
        )
        prev = shard(prev, "b", c_ax)
        feasible = shard(feasible, "b", c_ax)
        general = prof_table[pfc]
        avail = shard(merge_estimates(repsc, (general,)), "b", c_ax)
        assignment, unsched = _divide_batch(
            stc, repsc, feasible, static_w, avail, prev, frc,
            has_aggregated, wide, fast,
        )
        # Duplicated rows ride the feasibility bitset; their dense rows are
        # zero so the resident diff ignores them (meta carries their state)
        assignment = shard(
            jnp.where((stc == S_DUPLICATED)[:, None], 0, assignment),
            "b", c_ax,
        )
        dense8 = assignment.astype(jnp.uint8)  # counts <= MAX_REPLICAS_FAST
        n_placed = (assignment > 0).sum(axis=1).astype(jnp.int32)
        has_cand = feasible.any(axis=1)
        meta = (
            n_placed
            | (unsched.astype(jnp.int32) << 8)
            | (has_cand.astype(jnp.int32) << 9)
        )
        # diff + in-place resident update. all_rows reads/writes contiguous
        # slices; partial batches use row gather/scatter (few rows: the
        # per-row scatter overhead is what made this form wrong for the
        # 100k storm, which is exactly the all_rows case)
        if all_rows:
            # int32 shard-safe offsets (see _fleet_solve: the partitioner
            # rejects s64 starts on the row-sharded residents)
            off = (i * chunk).astype(jnp.int32)
            z32 = jnp.int32(0)
            old_d = lax.dynamic_slice(rd, (off, z32), (chunk, c))
            old_m = lax.dynamic_slice_in_dim(rm, off, chunk, 0)
            rd = lax.dynamic_update_slice(rd, dense8, (off, z32))
            rm = lax.dynamic_update_slice_in_dim(rm, meta, off, 0)
        else:
            old_d = rd[rc]
            old_m = rm[rc]
            safe_r = jnp.where(vc, rc, cap)
            rd = rd.at[safe_r].set(dense8, mode="drop")
            rm = rm.at[safe_r].set(meta, mode="drop")
        cell_changed = (dense8 != old_d) & vc[:, None]
        dcount = cell_changed.sum(axis=1).astype(jnp.int32)
        changed = (cell_changed.any(axis=1) | (meta != old_m)) & vc
        if d_cap:
            # per-row delta compaction via sort, skipped entirely on
            # steady chunks (the sort over [chunk, C] is the only
            # non-trivial cost and a steady pass has no changed cells)
            idxs32 = jnp.arange(c, dtype=jnp.int32)[None, :]

            def _deltas(op):
                d8, chm = op
                dp = jnp.where(
                    chm,
                    (idxs32 << 9) | (d8.astype(jnp.int32) + 1),
                    jnp.int32(2**31 - 1),
                )
                srt = lax.sort(dp, is_stable=False)[:, :d_slots]
                return jnp.where(srt == 2**31 - 1, 0, srt)

            deltas = lax.cond(
                cell_changed.any(),
                _deltas,
                lambda op: jnp.zeros((chunk, d_slots), jnp.int32),
                (dense8, cell_changed),
            )
        else:
            deltas = jnp.zeros((chunk, 0), jnp.int32)
        return (rd, rm), (changed, meta, dcount, deltas)

    (res_dense, res_meta), outs = lax.scan(
        body, (res_dense, res_meta), jnp.arange(n_chunks)
    )
    # pin the updated residents to their allocation layout (row-sharded
    # under a mesh): matching in/out shardings keep the donation aliased,
    # so the dense grid never double-buffers across passes
    res_dense = shard(res_dense, "b", c_ax)
    res_meta = shard(res_meta, "b")
    # the wire build below is GLOBAL prefix-scan + scatter compaction:
    # replicate its inputs explicitly so the residents' row sharding
    # cannot back-propagate into the cumsums (the CPU SPMD partitioner
    # emits corrupt streams for sharded global scans — see _fleet_solve)
    changed = shard(outs[0].reshape(-1), None)  # bool[n_pad]
    meta = shard(outs[1].reshape(-1), None)
    dcounts = shard(outs[2].reshape(-1), None)

    # wire: [4B total][bitmask n_pad/8 B][m_cap x 2B changed metas in row
    # order][4B dtotal][d_cap x 3B cell deltas] (delta section only when
    # d_cap > 0). n_pad is a multiple of 256, so the bitmask packs evenly.
    # The wire meta word carries state (n_placed | flags, 10 bits) plus
    # min(dcount, 63) in the 6 spare bits; res_meta stores STATE ONLY —
    # dcount is pass-relative and must not trip the next pass's meta diff.
    wire_meta = meta | (jnp.minimum(dcounts, 63) << 10)
    cnt = jnp.cumsum(changed.astype(jnp.int32)) - changed
    total = cnt[-1] + changed[-1].astype(jnp.int32)
    write = jnp.where(changed & (cnt < m_cap), cnt, m_cap)
    mbuf = jnp.zeros((m_cap + 1,), jnp.int32).at[write].set(wire_meta)
    mstream = mbuf[:m_cap]
    # changed TABLE rows, compacted in the same bitmask order — stays on
    # device so a speculative phase B can consume it without waiting for
    # the host to decode the bitmask (saves one tunnel round-trip per
    # churn pass)
    rowbuf = (
        jnp.full((m_cap + 1,), -1, jnp.int32).at[write].set(r)[:m_cap]
    )
    w32 = changed.reshape(-1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :]
    words = (w32 << shifts).sum(axis=-1, dtype=jnp.uint32)
    mask_u8 = jnp.stack(
        [(words >> s) & 0xFF for s in (0, 8, 16, 24)], axis=-1
    ).astype(jnp.uint8).reshape(-1)
    total_u8 = jnp.stack(
        [(total >> s) & 0xFF for s in (0, 8, 16, 24)]
    ).astype(jnp.uint8)
    meta_u8 = jnp.stack(
        [mstream & 0xFF, (mstream >> 8) & 0xFF], axis=-1
    ).astype(jnp.uint8).reshape(-1)
    parts = [total_u8, mask_u8, meta_u8]
    if d_cap:
        # cell-delta stream: deltas of changed rows whose dcount fits the
        # meta field (<= 62), compacted in bitmask row order; overflow
        # rows (sentinel 63) ship via phase B instead
        deltas_all = shard(
            outs[3].reshape(changed.shape[0], -1), None, None
        )
        contrib = changed & (dcounts <= 62)
        rowv = jnp.where(contrib[:, None], deltas_all, 0).reshape(-1)
        validv = rowv != 0
        doffs = jnp.cumsum(validv.astype(jnp.int32)) - validv
        dtotal = doffs[-1] + validv[-1].astype(jnp.int32)
        dwrite = jnp.where(validv & (doffs < d_cap), doffs, d_cap)
        dbuf = jnp.zeros((d_cap + 1,), jnp.int32).at[dwrite].set(rowv)
        dstream = dbuf[:d_cap]
        dtotal_u8 = jnp.stack(
            [(dtotal >> s) & 0xFF for s in (0, 8, 16, 24)]
        ).astype(jnp.uint8)
        d_u8 = jnp.stack(
            [dstream & 0xFF, (dstream >> 8) & 0xFF, (dstream >> 16) & 0xFF],
            axis=-1,
        ).astype(jnp.uint8).reshape(-1)
        parts += [dtotal_u8, d_u8]
    flat = jnp.concatenate(parts)
    return flat, rowbuf, res_dense, res_meta


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_chunks", "k_out", "e_cap", "byte_wire", "pack21",
        "mesh",
    ),
)
def _fleet_entries(
    res_dense,  # uint8[cap, C] — the dense resident phase A just updated
    rows,  # int32[m_pad] changed table rows (-1 = padding)
    *,
    chunk: int,
    n_chunks: int,
    k_out: int,
    e_cap: int,  # exact-or-larger (host sums changed n_placed): no overflow
    byte_wire: bool,
    pack21: bool = False,
    mesh=None,  # the resident's mesh: gathers cross shards; scans replicate
):
    """Phase B: sort-compact ONLY the changed rows' dense vectors into the
    row-major (site << 8 | count) entry stream. Runs at the changed-row
    count, not the table size."""
    cap, c = res_dense.shape
    idxs = jnp.arange(c, dtype=jnp.int32)[None, :]

    def body(carry, i):
        rc = lax.dynamic_slice_in_dim(rows, i * chunk, chunk, 0)
        vc = rc >= 0
        dense = res_dense[jnp.maximum(rc, 0)].astype(jnp.int32)
        dense = jnp.where(vc[:, None], dense, 0)
        packed_full = jnp.where(
            dense > 0, (idxs << 8) | dense, jnp.int32(2**31 - 1)
        )
        srt = lax.sort(packed_full, is_stable=False)[:, :k_out]
        return carry, jnp.where(srt == 2**31 - 1, 0, srt)

    _, ents = lax.scan(body, 0, jnp.arange(n_chunks))
    # replicate before the global compaction scan: the dense resident
    # input is row-sharded on mesh engines, and a sharded cumsum is
    # exactly the CPU-SPMD corruption _fleet_solve guards against
    if mesh is not None:
        ents = lax.with_sharding_constraint(
            ents, NamedSharding(mesh, P())
        )
    entries = ents.reshape(-1, k_out)  # [m_pad, k_out]
    valid_e = (entries > 0).reshape(-1)
    offs = jnp.cumsum(valid_e.astype(jnp.int32)) - valid_e
    total = offs[-1] + valid_e[-1].astype(jnp.int32)
    packed = entries.reshape(-1)
    write = jnp.where(valid_e & (offs < e_cap), offs, e_cap)
    buf = jnp.zeros((e_cap + 1,), jnp.int32).at[write].set(packed)
    stream = buf[:e_cap]
    if byte_wire:
        total_u8 = jnp.stack(
            [(total >> s) & 0xFF for s in (0, 8, 16, 24)]
        ).astype(jnp.uint8)
        e_u8 = _entry_wire(stream, e_cap, pack21)
        return jnp.concatenate([total_u8, e_u8])
    return jnp.concatenate([total[None], stream])


def _decode_entry_wire(raw2, cap_used: int, byte_wire: bool, pack21: bool):
    """(total, stream) from a phase-B entry wire buffer."""
    from .. import native

    if byte_wire:
        total2 = native.le32(raw2)
        stream = (
            native.decode21(raw2[4:], cap_used)
            if pack21
            else native.decode3(raw2[4:])
        )
        return total2, stream
    return int(raw2[0]), raw2[1:]


@partial(jax.jit, static_argnames=("chunk", "n_chunks"))
def _fleet_bits(
    cp_bits, cp_static, gvk_bits, prof_table, incomplete_en, rows,
    cp_idx, gvk_idx, prof_idx, replicas, strategy, fresh,
    prev_sites, prev_counts, *, chunk: int, n_chunks: int,
):
    """Feasibility bitsets as their own lazily-DISPATCHED kernel: only
    Duplicated / zero-replica rows ever read them (their result IS the
    feasible set), and computing + packing them inside every solve pass
    cost a Duplicated-bearing 100k storm ~0.6 s/pass whether or not any
    result was examined. The mask expression is the solve kernels'
    feasibility verbatim; inputs are the pass-time device arrays (JAX
    arrays are immutable, so a batch holding these refs stays consistent
    even after later passes rebuild the live tables)."""
    c = cp_static.shape[1]
    valid = rows >= 0
    r = jnp.maximum(rows, 0)
    cp = cp_idx[r]
    gv = gvk_idx[r]
    ps = prev_sites[r]
    pc = jnp.where(valid[:, None], prev_counts[r], 0)

    def body(carry, i):
        sl = lambda a: lax.dynamic_slice_in_dim(a, i * chunk, chunk, axis=0)
        cpc, gvc, vc = sl(cp), sl(gv), sl(valid)
        psc, pcc = sl(ps), sl(pc)
        _, _, feasible = _row_masks(
            cp_bits, cp_static, gvk_bits, incomplete_en, cpc, gvc, psc, pcc, vc,
            chunk, c,
        )
        pad = (-c) % 32
        f = jnp.pad(feasible, ((0, 0), (0, pad)))
        w32 = f.reshape(chunk, -1, 32).astype(jnp.uint32)
        shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :]
        return carry, (w32 << shifts).sum(axis=-1, dtype=jnp.uint32)

    _, out = lax.scan(body, 0, jnp.arange(n_chunks))
    return out.reshape(-1, out.shape[-1])


@jax.jit
def _gather_meta(res_meta, rows):
    """Changed-meta fallback when phase A's tuned meta buffer overflows:
    one cheap gather instead of a full-solve rerun."""
    m = jnp.where(rows >= 0, res_meta[jnp.maximum(rows, 0)], 0)
    return jnp.stack(
        [m & 0xFF, (m >> 8) & 0xFF], axis=-1
    ).astype(jnp.uint8).reshape(-1)


# row_coupled: the graftlint-dep delta-safety declarations (IR006-
# checked against the traced jaxprs, see tools/graftlint/dep.py). The
# solve/pass/entries kernels compact globally across the resident cap
# axis (coupled); bits/meta are per-row — bits' scan windowing keeps the
# analyzer's verdict 'unproven', so neither is delta_safe yet.
_fleet_solve.row_coupled = True
_fleet_pass.row_coupled = True
_fleet_entries.row_coupled = True
_fleet_bits.row_coupled = False
_gather_meta.row_coupled = False


#: THE solve-family kernel registry: prewarm's manifest replay
#: (scheduler/prewarm._jit_registry) and the graftlint IR tier's
#: entry-point registry (tools/graftlint/ir.py) both resolve kernels
#: through this mapping, so a kernel added here is automatically
#: replayable at boot and IR-audited in tier-1. prewarm._KERNELS (the
#: jax-free load-time filter) mirrors these names and is asserted against
#: this dict at replay time; graftlint IR004 fails on any drift.
FLEET_KERNELS = {
    "fleet_solve": _fleet_solve,
    "fleet_pass": _fleet_pass,
    "fleet_entries": _fleet_entries,
    "fleet_bits": _fleet_bits,
    # quota plane (ops.quota): dispatched engine-side (TensorScheduler's
    # admission wrapper + cap fold), registered here so prewarm replay and
    # the graftlint IR tier see them like every other solve-family kernel
    "quota_admit": _quota_admit,
    "quota_cluster_caps": _quota_cluster_caps,
    # provenance plane (ops.explain): the armed-only per-pass "why"
    # dispatch, engine-side like the quota kernels — registered so
    # prewarm replay and the graftlint IR tier audit it with the rest
    "explain_pass": _explain_pass,
    # scarcity plane (ops.preempt): the armed-only plane-wide victim
    # selection, engine-side like quota/explain — same registration
    # contract (prewarm replay + graftlint IR audit)
    "preempt_select": _preempt_select,
}


#: solve-path kernels the delta pass drives with PARTIAL batches — the
#: runtime consumes the dep-lint tier's jaxpr row-dependence certification
#: (tools/graftlint/dep.delta_safe_registry) instead of re-declaring
#: independence here. row_coupled kernels (quota_admit's FIFO segments,
#: preempt_select's plane-wide cumsum) are NOT in this list: their waves
#: force a scoped full pass (see TensorScheduler.schedule).
_DELTA_SAFE_REQUIRED = (
    "divide_replicas", "take_by_weight_batch", "general_estimate",
)

_DELTA_CERT: Optional[bool] = None


def delta_certified() -> bool:
    """True when the dep-lint tier proves every kernel the delta solve
    dispatches row-independent (``delta_safe``: declared uncoupled AND
    jaxpr-analyzed "independent"). Cached per process — the registry
    traces every entry spec once. Fail-closed: an import failure, a
    missing registry row, or a coupled/unproven verdict DISARMS the
    delta path rather than risk a partial dispatch of a row-coupled
    kernel silently dropping cross-row effects."""
    global _DELTA_CERT
    if _DELTA_CERT is None:
        try:
            from tools.graftlint.dep import delta_safe_registry

            rows = {r["name"]: r for r in delta_safe_registry()}
            _DELTA_CERT = all(
                rows[k]["delta_safe"] for k in _DELTA_SAFE_REQUIRED
            )
        except Exception:  # noqa: BLE001 — certification is a gate, not
            # a dependency: anything short of a positive verdict disarms
            _DELTA_CERT = False
        if not _DELTA_CERT:
            log.warning(
                "delta solve disarmed: dep-lint certification of %s "
                "did not prove row-independence",
                ", ".join(_DELTA_SAFE_REQUIRED),
            )
    return _DELTA_CERT


# --------------------------------------------------------------------------
# results
# --------------------------------------------------------------------------


class _FleetBatch:
    """Shared per-pass outputs (results hold views).

    Entry data lives in the table's persistent host entry array (rows
    updated in place for CHANGED rows only — the delta-fetch base); the
    feasibility bitsets are a lazily-fetched device output. Views are valid
    until the next schedule() pass on the same engine — consumers patch
    results synchronously (scheduler_controller). A generation counter
    captured at construction ENFORCES that window: decoding a result after
    a later pass (or a compaction) has rewritten the mirror raises instead
    of silently yielding another pass's — or another binding's — entries."""

    __slots__ = (
        "names", "host_entries", "rows", "_bits_dev", "_bits_np",
        "_table", "_gen",
    )

    def __init__(self, names, host_entries, rows, bits_dev, table, gen):
        self.names = names
        self.host_entries = host_entries  # int32[cap, k_out] (site<<8|count)
        self.rows = rows  # int32[n] table row per result position
        # device uint32[n_pad, W], a zero-arg thunk that DISPATCHES the
        # bitset kernel over this pass's captured inputs (the lazy form —
        # only Duplicated/zero-replica results ever need it), or None
        self._bits_dev = bits_dev
        self._bits_np = None
        self._table = table
        self._gen = gen

    def entries_for(self, pos: int) -> np.ndarray:
        if self._table is not None and self._table._result_gen != self._gen:
            raise RuntimeError(
                "stale FleetResult: a later schedule() pass (or table "
                "compaction) has rewritten the entry mirror; decode "
                "results before re-scheduling"
            )
        return self.host_entries[self.rows[pos]]

    def feasible_names(self, pos: int) -> tuple:
        if self._bits_np is None:
            bits_dev = (
                self._bits_dev() if callable(self._bits_dev)
                else self._bits_dev
            )
            # force little-endian word layout before the byte view so the
            # bit positions are host-endianness-independent (the entry
            # stream is decoded with shifts for the same reason)
            self._bits_np = np.ascontiguousarray(
                np.asarray(bits_dev).astype("<u4", copy=False)
            )
        row = self._bits_np[pos]
        idx = np.nonzero(
            np.unpackbits(row.view(np.uint8), bitorder="little")
        )[0]
        names = self.names
        return tuple(names[j] for j in idx if j < len(names))


class FleetResult:
    """Lazy ScheduleResult-compatible view over a fleet batch.

    `clusters`/`feasible` materialize on first access: the scheduling data
    already sits in host numpy arrays; building 100k Python dicts eagerly
    would cost more than the whole device pass."""

    __slots__ = (
        "key", "affinity_name", "error",
        "_batch", "_pos", "_n", "_dup_replicas", "_zero",
        "_clusters", "_feasible",
    )

    def __init__(self, key, affinity_name, error, batch, pos, n,
                 dup_replicas, zero):
        self.key = key
        self.affinity_name = affinity_name
        self.error = error
        self._batch = batch
        self._pos = pos
        self._n = n
        self._dup_replicas = dup_replicas  # Duplicated row: count everywhere
        self._zero = zero  # zero-replica (non-workload) row
        self._clusters = None
        self._feasible = None

    @property
    def success(self) -> bool:
        return not self.error

    @property
    def clusters(self) -> dict:
        if self._clusters is None:
            if not self.success:
                self._clusters = {}
            elif self._dup_replicas is not None:
                self._clusters = {
                    n: self._dup_replicas
                    for n in self._batch.feasible_names(self._pos)
                }
            else:
                b = self._batch
                names = b.names
                self._clusters = {
                    names[int(e) >> 8]: int(e) & 0xFF
                    for e in b.entries_for(self._pos)[: self._n]
                }
        return self._clusters

    @property
    def feasible(self) -> tuple:
        if self._feasible is None:
            self._feasible = (
                self._batch.feasible_names(self._pos)
                if (self._zero and self.success)
                else ()
            )
        return self._feasible


class _FleetResultList:
    """Column-oriented result container: the scheduling data lives in the
    fetched numpy arrays; per-binding `FleetResult` views materialize on
    access (and are cached for identity stability). Building 100k Python
    objects eagerly would cost more host time than the whole device pass —
    consumers that iterate pay the same total, but batch callers that
    sample (bench verification, partial write-backs) don't pay for rows
    they never touch."""

    __slots__ = (
        "_problems", "_terms", "_batches", "_slice_rows", "_n_placed",
        "_unsched", "_has_cand", "_is_dup", "_cache",
    )

    def __init__(self, problems, terms, batches, slice_rows, n_placed,
                 unsched, has_cand, is_dup):
        self._problems = problems
        self._terms = terms
        self._batches = batches
        self._slice_rows = slice_rows
        self._n_placed = n_placed
        self._unsched = unsched
        self._has_cand = has_cand
        self._is_dup = is_dup
        self._cache: dict[int, FleetResult] = {}

    def __len__(self) -> int:
        return len(self._problems)

    def _make(self, i: int) -> FleetResult:
        res = self._cache.get(i)
        if res is not None:
            return res
        p = self._problems[i]
        if not self._has_cand[i]:
            err = "no clusters fit the placement"
        elif self._unsched[i]:
            err = "clusters available replicas are not enough"
        else:
            err = ""
        dup = (
            p.replicas
            if (self._is_dup[i] and p.replicas > 0 and not err)
            else None
        )
        res = FleetResult(
            p.key, self._terms[i], err,
            self._batches[i // self._slice_rows], i % self._slice_rows,
            int(self._n_placed[i]), dup, p.replicas == 0,
        )
        self._cache[i] = res
        return res

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._make(j) for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self._make(i)

    def __iter__(self):
        for i in range(len(self)):
            yield self._make(i)


# --------------------------------------------------------------------------
# the table
# --------------------------------------------------------------------------

_STATE_FIELDS = (
    "cp_idx", "gvk_idx", "prof_idx", "replicas", "strategy", "fresh",
    "prev_sites", "prev_counts",
)


@jax.jit
def _scatter_rows(state, rows, vals):
    return tuple(a.at[rows].set(v) for a, v in zip(state, vals))


# data-dependent row placement: writes land at ``rows``, so one update
# moves another slot's data — cross-row by construction (IR006-proven)
_scatter_rows.row_coupled = True


class FleetTable:
    """Device-resident binding table bound to one TensorScheduler."""

    def __init__(self, engine):
        self.engine = engine
        # floor to a power of two (>= 256): the dense wire packs the
        # changed bitmask in 32-bit words and phase B divides the meta
        # buffer by the chunk, so n_pad must stay pow2-aligned — the
        # engine's chunk_size is a perf knob, not a semantic one
        self.chunk = 1 << max(engine.chunk_size, 256).bit_length() - 1
        # engine-level mesh, validated ONCE against the table's quanta:
        # chunk/cap/n_pad are all pow2 (>= 256), so any pow2 "b" extent
        # up to the chunk divides every bucket this table will ever pad
        # to — the mesh-divisible-bucket contract. A non-pow2 or oversized
        # extent falls back to single-device for the whole table (loudly:
        # silently dropping chips would fake a scaling number).
        mesh = getattr(engine, "mesh", None)
        if mesh is not None:
            b_sz = mesh.shape.get("b", 1)
            if b_sz & (b_sz - 1) or b_sz > self.chunk:
                log.warning(
                    "fleet mesh disabled: binding axis %d is not a power "
                    "of two dividing the %d-row chunk quantum; the solve "
                    "runs single-device", b_sz, self.chunk,
                )
                mesh = None
        self._mesh = mesh
        self.cap = 0
        self.n_rows = 0
        self._key_row: dict[str, int] = {}
        self._problems: list = []
        self._fps: list = []
        self._terms: list = []  # affinity term name per row
        self._row_last_used: list[int] = []  # pass counter per row
        self._pass = 0
        # interning slots
        self._cp_slot: dict[int, int] = {}
        self._cp_pl: list = []  # slot -> (placement, compiled) pinned
        self._cp_uploaded = 0  # slots currently valid on the device table
        self._cp_remapped = False  # slot ids changed: full upload needed
        self._gvk_slot: dict[str, int] = {}
        self._gvk_list: list[str] = []
        self._prof_slot: dict[bytes, int] = {}
        self._profiles: list[np.ndarray] = []
        # cap-namespace id per interned profile (-1 = uncapped): profiles
        # of bindings in namespaces with static-assignment quotas intern
        # per (request vector, cap ns) so the profile table row carries
        # the cap-folded availability — the quota ceiling reaches the
        # divide kernel with no kernel-signature change. Stable for the
        # table's lifetime: the engine drops the table on cap changes.
        self._prof_ns: list[int] = []
        # requests-tuple -> profile slot memo over _prof_slot: skips the
        # per-row dim-vector build (zeros + dim_index loop + tobytes) that
        # dominates bulk onboarding (a restart's first wave packs EVERY
        # row). Keyed per snapshot object — dims can change across swaps
        self._req_slot: dict[tuple, int] = {}
        self._req_slot_snap = None
        # host staging
        self._st: dict[str, np.ndarray] = {}
        # device
        self._dev_state: Optional[tuple] = None
        self._dev_tables: Optional[tuple] = None
        self._all_rows_dev = None
        self._all_rows_n = -1
        self._dirty: set[int] = set()
        self._tables_dirty = True
        self._avail_max = 0
        self._static_max = 0
        self._snapshot_gen = getattr(engine, "_snapshot_gen", 0)
        # last observed entry total: tunes the fetched buffer well below the
        # worst-case sum(replicas) bound (mean placed clusters per binding is
        # far under max replicas); overflow falls back to the safe bound
        self._last_total: Optional[int] = None  # None = no pass observed yet
        self._e_cap_cur: Optional[int] = None
        # delta-fetch base: device-resident [cap, k_out] per-row entry
        # vectors from the last pass + the host mirror results read from.
        # None = next pass reports every row changed and refills both.
        self._resident_entries = None
        self._host_entries: Optional[np.ndarray] = None
        self._k_res = 1  # running max entry width (grow-only)
        # mesh layout (canonical shape tuple) the residents were born on:
        # rides every resident-bearing trace key, and a layout change
        # reallocates the residents (next pass re-reports every row)
        self._resident_mesh = None
        # two-phase dense path (see _fleet_pass/_fleet_entries): the dense
        # assignment + meta words live on device; _host_meta mirrors the
        # meta resident so results decode without a full per-pass fetch
        self._res_dense = None  # uint8[cap, C] device
        self._res_meta = None  # int32[cap] device
        self._host_meta: Optional[np.ndarray] = None
        self._m_cap_cur: Optional[int] = None
        self._last_changed: Optional[int] = None
        # cell-delta wire (phase A tail): tuned like m_cap; _delta_live
        # records that the last churn pass folded via deltas, which turns
        # the speculative full-row phase B dispatch off (wasted device
        # sort + wire when deltas carry the pass)
        self._d_cap_cur: Optional[int] = None
        self._last_dtotal: Optional[int] = None
        self._delta_live = False
        # (target, consecutive passes desired) for a frozen shrink — see
        # the shrink-to-seen-only block in _solve_dense / _solve_legacy
        self._shrink_desire: tuple = (None, 0)
        self._e_shrink_desire: tuple = (None, 0)
        # O(1) batch reuse: (problems_list, compiled_list, rows) of the
        # last scheduled batch — the engine's batch-identity fast path
        # re-passes the SAME list objects, so identity means the row
        # mapping is already current (cleared on growth/compaction).
        # _reuse_pass stands in for the per-row last-used bumps the
        # skipped upserts would have done (consumed by _compact).
        self._reuse: Optional[tuple] = None
        self._reuse_pass = 0
        # mirror staleness fence for the delta solve: _mirror_epoch bumps
        # whenever a resident/mirror pair is (re)allocated zeroed, and
        # _reuse_epoch records the epoch whose mirrors fully cover the
        # reuse rows (synced at the end of every full pass). A delta pass
        # only replays untouched rows when the epochs agree — a realloc
        # between the covering pass and now means the mirrors no longer
        # hold those rows' results.
        self._mirror_epoch = 0
        self._reuse_epoch = -1
        # bumped whenever _host_entries is rewritten (each pass, and on
        # compaction remaps); _FleetBatch captures it so stale result
        # views fail loudly instead of decoding another pass's entries
        self._result_gen = 0
        # per-phase wall times of the last pass (bench breakdown surface)
        self.last_breakdown: dict[str, float] = {}
        # rows (re)packed by the current pass (_pack_row increments):
        # the packed-vs-replayed split the history ring records per wave
        self._packed_this_pass = 0
        # host->device bytes of the current pass (state upload/scatter +
        # row indices), reset by _sync_device; surfaces as upload_mb
        self._last_upload_bytes = 0
        # trace-signature ledger: every distinct static-arg combination we
        # dispatch is one XLA trace — and on the async tunnel a fresh trace's
        # remote compile does NOT block at dispatch; it surfaces at the next
        # blocking fetch. Warmup loops poll ``new_trace_last_pass`` until a
        # pass introduces no unseen signature, so timed windows only ever run
        # already-compiled traces.
        self._seen_traces: set = set()
        self.new_trace_last_pass = False
        # durable ledger (scheduler.prewarm): fresh solve-family traces are
        # persisted with their full compile inputs so a future process can
        # AOT-prewarm them before its first pass. Seeding the in-memory
        # ledger from the manifest is gated on the manifest having been
        # REPLAYED in this process (prewarm.warmup) — otherwise the first
        # pass would claim new_trace=False while a compile still runs.
        from .prewarm import prewarm_on_rebuild

        # the engine resolved its manifest once at construction (including
        # the env-default fallback); re-resolving None here would resurrect
        # an inherited KARMADA_TPU_TRACE_MANIFEST after an explicit
        # trace_manifest="" opt-out
        self._manifest = getattr(engine, "trace_manifest", None)
        if self._manifest is not None:
            # warmed_keys() is empty before replay, and excludes records
            # whose compile FAILED during replay — those traces would
            # still compile at first dispatch, so seeding them would fake
            # a warm pass
            self._seen_traces |= self._manifest.warmed_keys()
        prewarm_on_rebuild(self._manifest)

    @property
    def shrink_pending(self) -> bool:
        """A sustained-shrink desire is accumulating: within SHRINK_SUSTAIN
        passes a smaller cap pair may compile a fresh trace. Bench warm
        loops poll this alongside ``new_trace_last_pass`` — breaking warmup
        while a desire is pending parks the compile inside the timed
        window (an 18s dispatch stall on the 1M tier)."""
        return bool(self._shrink_desire[1] or self._e_shrink_desire[1])

    def exhaustion_summary(self) -> str:
        """One line of WHY this table reports slots_exhausted — printed by
        the engine before a rebuild (a rebuild costs a full repack +
        re-trace; the slot-rotation bench observed one with the slot count
        apparently under the cap, and this breadcrumb is how the next
        occurrence gets root-caused)."""
        return (
            f"slots={len(self._cp_pl)} max={self._max_slots()} "
            f"gvk={len(self._gvk_list)} profiles={len(self._profiles)} "
            f"rows={self.n_rows} cap={self.cap}"
        )

    def _mark_trace(self, *key) -> bool:
        """Record a dispatched trace signature; flips the per-pass
        new-trace flag when the signature is unseen (a compile will run).
        Returns True for a fresh signature so dispatch sites can persist
        the compile record to the trace manifest. Every fresh signature
        also feeds the per-bucket compile counter — the metric face of
        the compile-lifecycle subsystem (manifest-seeded signatures never
        pass through here, so prewarmed traces don't count as serving-
        path compiles)."""
        if key not in self._seen_traces:
            self._seen_traces.add(key)
            self.new_trace_last_pass = True
            from ..utils.metrics import kernel_compiles

            bucket = "x".join(
                str(v) for v in key[1:] if isinstance(v, (int, bool))
            )[:64]
            kernel_compiles.inc(
                kernel=_TRACE_KERNELS.get(key[0], str(key[0])),
                bucket=bucket,
            )
            return True
        return False

    def _record_trace(self, kernel: str, key, arrays, **statics) -> None:
        """Persist a fresh trace's compile inputs (shapes + statics) to
        the manifest. A meshed dispatch records its mesh as the canonical
        SHAPE tuple (parallel.mesh.mesh_shape) — the Mesh object is not
        serializable but its shape is the compile identity, and replay
        rebuilds a live mesh over the booting process's devices (a boot
        that cannot host the recorded shape counts the record failed and
        never seeds the ledger from it). Best-effort: manifest failures
        must never reach the scheduling path."""
        if self._manifest is None:
            return
        if statics.get("mesh") is not None:
            from ..parallel.mesh import mesh_shape

            statics = {**statics, "mesh": mesh_shape(statics["mesh"])}
        try:
            self._manifest.record(kernel, key, arrays, statics)
        except Exception as exc:  # noqa: BLE001 — manifest failures must
            # never abort a scheduling wave (durability is optional, the
            # placement is not) — but they are LOGGED, never swallowed:
            # an unrecorded trace costs the NEXT boot a full compile.
            # Class name only at warning (orchestrators scrape merged
            # stdout/stderr for JSON lines; reprs can be multi-line)
            log.warning(
                "trace manifest record of %s failed (%s); next boot "
                "re-compiles this trace", kernel, type(exc).__name__,
            )
            log.debug("manifest record failure detail", exc_info=exc)

    # -- rows --------------------------------------------------------------

    COMPACT_IDLE_PASSES = 4  # rows unused this many passes are evictable

    def _compact(self) -> bool:
        """Drop rows whose keys haven't been scheduled recently (deleted
        bindings leave stale rows behind — without eviction a create/delete
        churn workload grows the table and its pinned problems without
        bound). Returns True if at least half the rows were reclaimed."""
        cutoff = self._pass - self.COMPACT_IDLE_PASSES
        lu = np.fromiter(self._row_last_used, np.int64, self.n_rows)
        if self._reuse is not None:
            # the batch-reuse fast path skips upsert (and with it the
            # per-row last-used bump): its rows were live at _reuse_pass
            lu[self._reuse[2]] = getattr(self, "_reuse_pass", self._pass)
        keep = np.flatnonzero(lu >= cutoff).tolist()
        if len(keep) * 2 > self.n_rows:
            return False
        for k in ("_problems", "_fps", "_terms"):
            setattr(self, k, [getattr(self, k)[r] for r in keep])
        self._row_last_used = lu[keep].tolist()  # reuse bump persists
        idx = np.asarray(keep, np.int64)
        for name, arr in self._st.items():
            arr[: len(keep)] = arr[idx]
        self._key_row = {p.key: i for i, p in enumerate(self._problems)}
        self.n_rows = len(keep)
        self._dirty.clear()
        self._dev_state = None  # full re-upload with the compacted layout
        self._all_rows_n = -1
        # row ids were remapped: the delta base is meaningless now, and so
        # is any result view still pointing at the old row layout
        self._resident_entries = None
        self._reset_dense()
        self._reuse = None  # row ids remapped
        self._result_gen += 1
        return True

    def _reset_dense(self) -> None:
        """Invalidate the dense-path residents (row remap / growth / path
        switch). The next dense pass reallocates zeroed residents and a
        zeroed host meta mirror — a consistent pair, so every row whose
        current result is nonzero re-reports as changed and refills the
        mirrors. The host ENTRY mirror must reset with them: after a row
        remap its runs belong to other bindings, and the cell-delta fold
        MERGES into existing runs (a full-row phase-B fold rewrites rows
        wholesale and would mask the staleness, but a delta-carried pass
        diffing against zeroed residents emits insert-only deltas — merged
        into a stale run, stale sites would survive)."""
        self._res_dense = None
        self._res_meta = None
        self._host_meta = None
        self._host_entries = None
        self._mirror_epoch += 1

    def _grow(self, need: int) -> None:
        new_cap = max(self.chunk, _pow2(need))
        st = {
            "cp_idx": np.zeros(new_cap, np.int32),
            "gvk_idx": np.zeros(new_cap, np.int32),
            "prof_idx": np.zeros(new_cap, np.int32),
            "replicas": np.zeros(new_cap, np.int32),
            "strategy": np.zeros(new_cap, np.int32),
            "fresh": np.zeros(new_cap, bool),
            "prev_sites": np.zeros((new_cap, K_PREV), np.int32),
            "prev_counts": np.zeros((new_cap, K_PREV), np.int32),
        }
        for k, a in self._st.items():
            st[k][: self.cap] = a
        self._st = st
        self.cap = new_cap
        self._dev_state = None  # full re-upload
        self._reset_dense()  # cap changed: residents reallocate zeroed
        self._reuse = None

    @staticmethod
    def _fingerprint(p, compiled) -> tuple:
        # DERIVED placements (spread selections interned by core.schedule)
        # carry their candidate set in the compiled object, so the row must
        # re-pack whenever the derived object changes — its identity IS the
        # selection content (interned per (base, mask)). Plain placements
        # key on the Placement object: their compiled masks recompile IN
        # PLACE at the same slot on snapshot swaps.
        return (
            id(p.placement),
            id(compiled) if getattr(compiled, "derived", False) else None,
            p.replicas, p.gvk, p.fresh,
            tuple(p.requests.items()), tuple(p.prev.items()),
        )

    def upsert(self, problem, compiled) -> int:
        row = self._key_row.get(problem.key)
        if row is not None:
            self._row_last_used[row] = self._pass
            # O(1) fast path: same problem object AND same compiled
            # identity class (the stored fingerprint's derived-id element
            # pins derived selections; None for plain placements)
            if self._problems[row] is problem and self._fps[row][1] == (
                id(compiled) if getattr(compiled, "derived", False) else None
            ):
                return row
            fp = self._fingerprint(problem, compiled)
            if fp == self._fps[row]:
                self._problems[row] = problem
                return row
        else:
            if self.n_rows + 1 > self.cap:
                self._grow(self.n_rows + 1)
            row = self.n_rows
            self.n_rows = row + 1
            self._key_row[problem.key] = row
            self._problems.append(problem)
            self._fps.append(None)
            self._terms.append("")
            self._row_last_used.append(self._pass)
        self._pack_row(row, problem, compiled)
        return row

    def _pack_row(self, row: int, problem, compiled) -> None:
        self._packed_this_pass += 1
        snap = self.engine.snapshot
        st = self._st
        # placement slot
        slot = self._cp_slot.get(id(compiled))
        if slot is None:
            slot = len(self._cp_pl)
            self._cp_slot[id(compiled)] = slot
            self._cp_pl.append((problem.placement, compiled))
            self._static_max = max(
                self._static_max, int(compiled.static_weights.max(initial=0))
            )
            self._tables_dirty = True
        st["cp_idx"][row] = slot
        # gvk slot
        gslot = self._gvk_slot.get(problem.gvk)
        if gslot is None:
            gslot = len(self._gvk_list)
            self._gvk_slot[problem.gvk] = gslot
            self._gvk_list.append(problem.gvk)
            self._tables_dirty = True
        st["gvk_idx"][row] = gslot
        # request profile slot (pods-dim adjustment applied BEFORE interning,
        # mirroring _pack_chunk: each replica occupies a pod). The identity
        # check (not ==) on the memo's snapshot pins the dims mapping the
        # cached slots were built under AND keeps the object alive, so a
        # recycled id can never alias a stale entry
        if self._req_slot_snap is not snap:
            self._req_slot = {}
            self._req_slot_snap = snap
        quota = getattr(self.engine, "quota", None)
        qns = (
            quota.cap_index.get(problem.namespace, -1)
            if quota is not None and quota.cap_index
            else -1
        )
        rkey = (tuple(problem.requests.items()), problem.replicas > 0, qns)
        pslot = self._req_slot.get(rkey)
        if pslot is None:
            vec = np.zeros(len(snap.dims), np.int64)
            for d, q in problem.requests.items():
                j = snap.dim_index(d)
                if j is not None:
                    vec[j] = q
            pods = snap.dim_index("pods")
            if pods is not None and problem.replicas > 0:
                vec[pods] = max(vec[pods], 1)
            pkey = vec.tobytes() + qns.to_bytes(4, "little", signed=True)
            pslot = self._prof_slot.get(pkey)
            if pslot is None:
                pslot = len(self._profiles)
                self._prof_slot[pkey] = pslot
                self._profiles.append(vec)
                self._prof_ns.append(qns)
                self._tables_dirty = True
            self._req_slot[rkey] = pslot
        st["prof_idx"][row] = pslot
        st["replicas"][row] = problem.replicas
        st["strategy"][row] = compiled.strategy
        st["fresh"][row] = problem.fresh
        sites = np.zeros(K_PREV, np.int32)
        cnts = np.zeros(K_PREV, np.int32)
        k = 0
        for name, reps_prev in problem.prev.items():
            j = snap.index.get(name)
            if j is not None:
                sites[k] = j
                cnts[k] = reps_prev
                k += 1
        st["prev_sites"][row] = sites
        st["prev_counts"][row] = cnts
        self._fps[row] = self._fingerprint(problem, compiled)
        self._terms[row] = compiled.terms[0][0]
        self._dirty.add(row)

    def _compact_slots(self, aggressive: bool = False) -> None:
        """Drop placement slots no live row references. The cheap sweep
        drops DERIVED slots only (selection drift interns new variants
        every availability change); ``aggressive`` (under cap pressure)
        also drops unreferenced PLAIN slots — create/delete churn over a
        heterogeneous fleet retires placements whose rows compaction
        already reclaimed, and re-interning a returning placement is one
        cached compile + one slot append. Triggers a full table rebuild +
        state re-upload, so it runs only under pressure."""
        used = set(
            int(s) for s in np.unique(self._st["cp_idx"][: self.n_rows])
        )
        keep = [
            i
            for i, (pl, cp) in enumerate(self._cp_pl)
            if i in used
            or (not aggressive and not getattr(cp, "derived", False))
        ]
        if len(keep) == len(self._cp_pl):
            return
        remap = np.full(len(self._cp_pl), -1, np.int32)
        for new_i, old_i in enumerate(keep):
            remap[old_i] = new_i
        self._cp_pl = [self._cp_pl[i] for i in keep]
        self._cp_slot = {id(cp): i for i, (pl, cp) in enumerate(self._cp_pl)}
        self._static_max = max(
            (int(cp.static_weights.max(initial=0)) for _, cp in self._cp_pl),
            default=0,
        )
        self._st["cp_idx"][: self.n_rows] = remap[
            self._st["cp_idx"][: self.n_rows]
        ]
        self._tables_dirty = True
        self._cp_remapped = True  # device cp rows are stale: full upload
        self._dev_state = None  # cp_idx remapped: full re-upload

    def _max_slots(self) -> int:
        """Effective unique-placement cap: MAX_SLOTS floor, scaled up to
        the CP_TABLE_MAX_BYTES device budget. Per-slot bytes under the
        bitpacked layout: two packed mask planes (2*ceil(C/8) uint8) plus
        the int32 static-weight row (4C) — the pre-bitpack formula (12C)
        understated capacity ~2.8x. Snapped DOWN to _slot_cap's own
        quantization grid so the device capacity the cap implies actually
        fits the budget (a raw quotient would let the allocated table
        overshoot its quantum)."""
        c = max(1, self.engine.snapshot.num_clusters)
        per_slot = 2 * ((c + 7) // 8) + 4 * c
        by_budget = max(1, CP_TABLE_MAX_BYTES // per_slot)
        if by_budget > 8192:
            # _slot_cap quantizes device capacity in 4096-slot multiples
            # above 8192 — snap to ITS grid (a pow2 floor here forfeited
            # up to ~2x of the budgeted slots just above a power of two)
            snapped = by_budget // 4096 * 4096
        else:
            snapped = 1 << (by_budget.bit_length() - 1)
        return min(MAX_SLOTS_HARD, max(MAX_SLOTS, snapped))

    @property
    def slots_exhausted(self) -> bool:
        mx = self._max_slots()
        if len(self._cp_pl) > mx * 3 // 4:
            self._compact_slots()
        if len(self._cp_pl) > mx:
            # retired placements stay pinned by their AGED rows: reclaim
            # idle rows first, then sweep every unreferenced slot — a
            # generational churn workload (new unique placements per wave)
            # keeps one table alive instead of rebuilding per call
            self._compact()
            self._compact_slots(aggressive=True)
        return (
            len(self._cp_pl) > mx
            or len(self._gvk_list) > mx
            or len(self._profiles) > mx
        )

    # -- device sync -------------------------------------------------------

    def _rebuild_tables(self) -> None:
        import os as _os
        import time as _t
        _dbg = _os.environ.get("KARMADA_SYNC_DEBUG") == "1"
        _t0 = _t.perf_counter()

        def _mark(tag):
            nonlocal _t0
            if _dbg:
                now = _t.perf_counter()
                print(f"# rebuild {tag}: {(now - _t0) * 1e3:.1f}ms", flush=True)
                _t0 = now

        snap = self.engine.snapshot
        gen = getattr(self.engine, "_snapshot_gen", 0)
        slots_changed = self._tables_dirty
        if gen != self._snapshot_gen and snap.mask_token == getattr(
            self, "_mask_token", None
        ):
            # availability-only swap: masks are pure functions of the
            # FILTER fields (mask_token), so every compiled slot is still
            # valid — recompiling 9k heterogeneous selectors through the
            # engine's LRU was ~6s per churn pass for identical results
            self._snapshot_gen = gen
        elif gen != self._snapshot_gen:
            # snapshot swapped in place (same cluster set): recompile each
            # slot's placement against the new snapshot, order-preserving so
            # row cp_idx values stay valid. DERIVED slots (interned spread
            # selections) are NOT recompiled — their mask IS the selection
            # content owned by core's selection cache; re-derivation happens
            # upstream per pass, landing changed selections in NEW slots via
            # the id(derived)-keyed row fingerprints. Recompiling them here
            # would overwrite the selection with the base affinity mask.
            self._snapshot_gen = gen
            self._cp_slot.clear()
            self._static_max = 0
            for i, (pl, cp_old) in enumerate(self._cp_pl):
                if getattr(cp_old, "derived", False):
                    cp = cp_old
                else:
                    cp = self.engine._compiled(pl)
                self._cp_pl[i] = (pl, cp)
                self._cp_slot[id(cp)] = i
                self._static_max = max(
                    self._static_max, int(cp.static_weights.max(initial=0))
                )
            # NOTE: device cp rows stay valid here — masks are functions
            # of the FILTER fields only, and a swap that changed those
            # fields changed mask_token, which the `full` check below
            # already catches (resetting _cp_uploaded on every gen bump
            # would re-upload the whole [U, 3C] table each churn pass)
        _mark("recompile")
        c = snap.num_clusters

        def cp_bits_np(slots) -> np.ndarray:
            """Bitpacked [aff&spread_field | taint] planes: uint8[k, 2*W8]
            (little bit order — _unpack_bits is the device inverse)."""
            aff = np.stack(
                [(cp.terms[0][1] & cp.spread_field_ok) for _, cp in slots]
            )
            taint = np.stack([cp.taint_ok for _, cp in slots])
            return np.concatenate(
                [
                    np.packbits(aff, axis=1, bitorder="little"),
                    np.packbits(taint, axis=1, bitorder="little"),
                ],
                axis=1,
            )

        def cp_static_np(slots) -> np.ndarray:
            return np.stack(
                [cp.static_weights.astype(np.int32) for _, cp in slots]
            )  # [k, C]

        # the mask tables are functions of the snapshot's FILTER fields only
        # (labels/taints/enablements/topology — snapshot.mask_token) and the
        # interned slot lists. An availability-only swap (churn) leaves both
        # unchanged, so the resident device tables stay valid. New interned
        # slots APPEND to a pow2-capacity device table (one small scatter —
        # re-uploading the full [U, 3C] table costs seconds per new
        # placement over the tunnel at heterogeneous U, and an exact-U
        # shape retraced the whole solve per slot); mask-token changes and
        # slot remaps rebuild in full.
        token = snap.mask_token
        n_slots = len(self._cp_pl)
        full = (
            self._dev_tables is None
            or token != getattr(self, "_mask_token", None)
            or self._cp_remapped
            or self._cp_uploaded == 0
        )
        w8 = (c + 7) // 8
        if full:
            # quantized capacity, padded with on-device zeros via concat
            # (a functional .at[:n].set on a zeros table would hold TWO
            # full-size buffers transiently); only live rows ship the wire
            cap_s = _slot_cap(n_slots)
            bits_live = jnp.asarray(cp_bits_np(self._cp_pl))
            static_live = jnp.asarray(cp_static_np(self._cp_pl))
            if cap_s > n_slots:
                pad = cap_s - n_slots
                cp_bits_dev = jnp.concatenate(
                    [bits_live, jnp.zeros((pad, 2 * w8), jnp.uint8)]
                )
                cp_static_dev = jnp.concatenate(
                    [static_live, jnp.zeros((pad, c), jnp.int32)]
                )
            else:
                cp_bits_dev = bits_live
                cp_static_dev = static_live
            self._cp_uploaded = n_slots
            self._cp_remapped = False
        else:
            cp_bits_dev = self._dev_tables[0]
            cp_static_dev = self._dev_tables[1]
            if n_slots > self._cp_uploaded:
                if n_slots > cp_bits_dev.shape[0]:  # grow device capacity
                    grow = _slot_cap(n_slots) - cp_bits_dev.shape[0]
                    cp_bits_dev = jnp.concatenate(
                        [cp_bits_dev, jnp.zeros((grow, 2 * w8), jnp.uint8)]
                    )
                    cp_static_dev = jnp.concatenate(
                        [cp_static_dev, jnp.zeros((grow, c), jnp.int32)]
                    )
                new_slots = self._cp_pl[self._cp_uploaded :]
                idx = jnp.arange(self._cp_uploaded, n_slots)
                cp_bits_dev = cp_bits_dev.at[idx].set(
                    jnp.asarray(cp_bits_np(new_slots))
                )
                cp_static_dev = cp_static_dev.at[idx].set(
                    jnp.asarray(cp_static_np(new_slots))
                )
                self._cp_uploaded = n_slots
        if full or slots_changed:
            gvk_rows = []
            for g in self._gvk_list:
                gid = snap.gvk_vocab.get(g) if g else None
                if gid is None:
                    mask = (
                        np.zeros(c, bool)
                        if g and len(snap.gvk_vocab) > 0
                        else np.ones(c, bool)
                    )
                else:
                    word, bit = gid // 32, gid % 32
                    mask = (snap.gvk_bits[:, word] >> np.uint32(bit)) & 1 != 0
                gvk_rows.append(mask)
            gvk_packed = np.packbits(
                np.stack(gvk_rows), axis=1, bitorder="little"
            )
            gvk_dev = (
                jnp.zeros((_pow2(max(len(gvk_rows), 4)), w8), jnp.uint8)
                .at[: len(gvk_rows)]
                .set(jnp.asarray(gvk_packed))
            )
            inc_dev = jnp.asarray(~snap.complete_enablements)
        else:
            _, _, gvk_dev, _, inc_dev = self._dev_tables
        _mark("masks")
        profs = np.stack(self._profiles)
        # pow2 row padding keeps the solve trace stable as profiles intern
        # (zero-request pad rows estimate to the untouched sentinel and are
        # never gathered — prof_idx stays below the live count)
        pad_p = _pow2(max(len(profs), 4))
        profs_dev = profs
        prof_ns = np.asarray(self._prof_ns, np.int32)
        if pad_p > len(profs):
            profs_dev = np.zeros((pad_p, profs.shape[1]), profs.dtype)
            profs_dev[: len(profs)] = profs
            prof_ns = np.concatenate(
                [prof_ns, np.full(pad_p - len(profs), -1, np.int32)]
            )
        # quota-aware table: cap-namespace profile slots get the static-
        # assignment ceiling min-folded into their availability row
        prof_table = self.engine._profile_table_quota(profs_dev, prof_ns)
        _mark("prof_table")
        # host mirror of the estimator max (general + models): the device
        # form is a blocking scalar fetch (~0.1s tunnel round-trip) and
        # this rebuild runs EVERY churn pass (snapshot gen bumps per drift)
        self._avail_max = self._host_avail_max(profs)
        _mark("avail_max")
        # under a mesh the slot tables replicate explicitly (empty-spec
        # NamedSharding): they are gathered per row by slot index inside
        # the sharded solve, and a one-time replicated upload beats a
        # per-pass broadcast from device 0. device_put is a no-op for
        # arrays already committed to the target sharding (the
        # incremental append path mutates replicated arrays in place).
        tables = (cp_bits_dev, cp_static_dev, gvk_dev, prof_table, inc_dev)
        if self._mesh is not None:
            repl = NamedSharding(self._mesh, P())
            tables = tuple(jax.device_put(a, repl) for a in tables)
        self._dev_tables = tables
        self._mask_token = token
        self._tables_dirty = False

    def _host_avail_max(self, profs: np.ndarray) -> int:
        """Sentinel-excluded max over the shared host mirror of the
        estimator profile table (core.host_profile_table, general +
        resource models). The device form was a blocking scalar fetch
        (~0.1s tunnel round-trip) running every churn pass."""
        from .core import host_profile_table

        mi = 2**31 - 1
        table = host_profile_table(
            self.engine.snapshot, profs,
            models_active=self.engine._models_active(),
        )
        valid = table != mi
        return int(table[valid].max()) if valid.any() else 0

    def _upload_state(self) -> tuple:
        """Full packed-state upload. Under a mesh the state replicates
        EXPLICITLY across every device (NamedSharding with an empty spec):
        the solve gathers per-row state by arbitrary row index, so a
        replica-local gather beats a per-pass broadcast of the whole
        grid from device 0."""
        arrays = tuple(jnp.asarray(self._st[k]) for k in _STATE_FIELDS)
        self._last_upload_bytes += sum(
            self._st[k].nbytes for k in _STATE_FIELDS
        )
        if self._mesh is None:
            return arrays
        return tuple(
            jax.device_put(a, NamedSharding(self._mesh, P()))
            for a in arrays
        )

    def _sync_device(self) -> None:
        self._last_upload_bytes = 0
        if self._tables_dirty or (
            getattr(self.engine, "_snapshot_gen", 0) != self._snapshot_gen
        ):
            self._rebuild_tables()
        if self._dev_state is None:
            self._dev_state = self._upload_state()
            self._dirty.clear()
        elif self._dirty:
            rows = np.fromiter(self._dirty, np.int64, len(self._dirty))
            if len(rows) > self.cap // 2:
                self._dev_state = self._upload_state()
            else:
                # pow2-pad the scatter (repeating the first row: duplicate
                # writes of identical values are idempotent) so distinct
                # dirty-row counts yield log-many traces, and ledger the
                # signature — an unmarked compile here would break the
                # warm-loop contract new_trace_last_pass carries
                pad = _pow2(len(rows))
                rows_p = np.concatenate(
                    [rows, np.full(pad - len(rows), rows[0], np.int64)]
                )
                vals = tuple(self._st[k][rows_p] for k in _STATE_FIELDS)
                self._last_upload_bytes += rows_p.nbytes + sum(
                    v.nbytes for v in vals
                )
                self._mark_trace(
                    "S", self.cap, pad, self._mesh is not None
                )
                self._dev_state = _scatter_rows(
                    self._dev_state, jnp.asarray(rows_p), vals
                )
            self._dirty.clear()

    # -- scheduling --------------------------------------------------------

    def schedule(
        self, problems: Sequence, compiled: Sequence, delta=None
    ) -> list:
        """One fleet pass, wrapped in a ``scheduler.solve`` wave span with
        per-phase kernel child spans (host pack / dispatch / fenced device
        execute / fetch+fold) emitted from the pass breakdown — the
        device/host attribution surface of ISSUE 6 (b). The span carries
        the pass's packed-vs-replayed row split (the churn-attribution
        series the history ring records per wave, ISSUE 12), and the
        device-byte ledger publishes after every pass.

        ``delta`` (optional) is a sequence of POSITIONS into ``problems``
        that changed since the last pass; every other position must hold
        the same problem/compiled objects the last pass scheduled (the
        caller's contract — the engine's batch-identity diff and the
        dirty-key plumbing both construct batches that way). When the
        table can prove its resident mirrors still cover the untouched
        rows, only the delta positions are packed and dispatched and the
        rest replay from the mirrors; otherwise the pass silently runs
        full."""
        from ..utils.tracing import tracer

        with tracer.span("scheduler.solve") as sp:
            res = self._schedule_pass(problems, compiled, delta)
            tmr = self.last_breakdown
            sp.attrs["rows"] = len(problems)
            sp.attrs["rows_packed"] = int(tmr.get("rows_packed", 0))
            sp.attrs["rows_replayed"] = int(tmr.get("rows_replayed", 0))
            sp.attrs["dirty_rows"] = int(tmr.get("dirty_rows", 0))
            self._emit_phase_spans()
        self._publish_device_bytes()
        return res

    def device_bytes(self) -> dict[str, int]:
        """Resident device bytes by ledger kind — the EXACT ``nbytes`` of
        the arrays this table holds right now (ISSUE 12 b): the packed
        state grid, the interned slot tables, the donated result
        residents (legacy entry vectors or dense pair), and the cached
        all-rows index. The accounting the 1M-on-16GB-HBM question needs
        before anyone puts the resident grid on a real part."""

        def nb(x) -> int:
            if x is None:
                return 0
            if isinstance(x, tuple):
                return sum(nb(v) for v in x)
            return int(getattr(x, "nbytes", 0))

        return {
            "packed_grid": nb(self._dev_state),
            "slot_tables": nb(self._dev_tables),
            "donated_residents": (
                nb(self._resident_entries)
                + nb(self._res_dense)
                + nb(self._res_meta)
            ),
            "rows_index": nb(self._all_rows_dev),
        }

    def _buffer_platform(self) -> str:
        """Platform of the buffers the ledger counts (PR 9's honesty
        rule carried to the gauge: forced-host bytes must never read as
        HBM — the label says whose memory it is)."""
        for x in (self._dev_state, self._dev_tables, self._res_dense,
                  self._resident_entries):
            arr = x[0] if isinstance(x, tuple) and x else x
            try:
                if arr is not None:
                    return next(iter(arr.devices())).platform
            except Exception:  # noqa: BLE001 — label is best-effort
                continue
        return "none"

    def _publish_device_bytes(self) -> None:
        """Refresh ``karmada_tpu_device_bytes{kind,bucket,platform}``
        from the live ledger: a clear-then-set sweep per kind so a cap
        regrow (bucket change) retires its stale sample instead of
        double-counting. With several engines in one process the gauge
        reflects the most recently dispatched table — the bucket label
        says which."""
        from ..utils.metrics import device_bytes as device_bytes_gauge

        bucket = f"{self.cap}x{self.engine.snapshot.num_clusters}"
        platform = self._buffer_platform()
        for kind, nbytes in self.device_bytes().items():
            device_bytes_gauge.remove_matching(kind=kind)
            device_bytes_gauge.set(
                nbytes, kind=kind, bucket=bucket, platform=platform
            )

    #: breakdown keys that are pure host work outside the dispatch/fetch
    #: windows (pack, delta scatter, result decode)
    _HOST_PHASE_KEYS = ("upsert", "sync", "prep", "post")

    def _emit_phase_spans(self) -> None:
        """Kernel phase spans + karmada_tpu_kernel_phase_seconds from the
        last pass's breakdown. Components are DISJOINT: ``fetch`` is the
        whole post-device window (wire transfer + decode + entry folds —
        its internal dispatch_b/fetch_b/delta_fold live inside it), and
        the fenced ``device`` window carries the compile attribution flag
        when this pass minted a fresh XLA trace."""
        from ..utils.metrics import kernel_phase_seconds
        from ..utils.tracing import tracer

        tmr = self.last_breakdown
        host = sum(tmr.get(k, 0.0) for k in self._HOST_PHASE_KEYS)
        # compile attribution: a synchronous backend compiles INSIDE the
        # dispatch call, an async tunnel behind it (surfacing at the
        # device fence) — on a fresh-trace pass both windows carry the
        # flag, so the summary's compile_s covers either backend
        fresh = bool(self.new_trace_last_pass)
        phases = [
            (
                "kernel.host",
                host,
                "host",
                # the pass's host->device bytes ride the host span so the
                # history sampler (and a dumped wave) can read transfer
                # volume without reaching into the engine
                {"upload_mb": tmr.get("upload_mb", 0.0)},
            ),
            (
                "kernel.dispatch",
                tmr.get("dispatch", 0.0),
                "host",
                {"compile": fresh} if fresh else {},
            ),
            (
                "kernel.device",
                tmr.get("device", 0.0),
                "device",
                {"compile": fresh},
            ),
            (
                "kernel.fetch",
                tmr.get("fetch", 0.0),
                "host",
                {
                    "fetch_mb": tmr.get("fetch_mb", 0.0),
                    "changed_rows": tmr.get("changed_rows", 0.0),
                },
            ),
        ]
        for name, seconds, kind, attrs in phases:
            if seconds <= 0.0:
                continue
            tracer.record(name, seconds, kind=kind, **attrs)
            kernel_phase_seconds.observe(seconds, phase=name.split(".")[1])

    def _schedule_pass(
        self, problems: Sequence, compiled: Sequence, delta=None
    ) -> list:
        import time as _time

        if delta is not None:
            res = self._schedule_delta(problems, compiled, delta)
            if res is not None:
                return res
            # ineligible (stale mirrors / uncertified / majority dirty):
            # fall through to the full pass below

        tmr: dict[str, float] = {}
        t0 = _time.perf_counter()
        self._pass += 1
        self.new_trace_last_pass = False
        self._packed_this_pass = 0
        ru = self._reuse
        if ru is not None and ru[0] is problems and ru[1] is compiled:
            # same batch objects as last pass: rows are current (upsert
            # would O(1)-skip every row anyway — this skips the loop).
            # _reuse_pass stands in for the per-row _row_last_used bump
            # the skipped upserts would have done; _compact honors it.
            rows_np = ru[2]
            self._reuse_pass = self._pass
        else:
            # reclaim rows of deleted/idle bindings before the table would
            # grow (compaction reindexes rows, so it must run before any
            # upsert of this pass hands out indices). Gated on ACTUAL new
            # keys so the steady all-rows storm pays one dict sweep at
            # capacity pressure, not an O(n_rows) compaction scan per pass.
            if self.n_rows + len(problems) > self.cap:
                new_keys = sum(
                    1 for p in problems if p.key not in self._key_row
                )
                if self.n_rows + new_keys > self.cap:
                    self._compact()
            rows_np = np.fromiter(
                (self.upsert(p, cp) for p, cp in zip(problems, compiled)),
                np.int32,
                len(problems),
            )
            self._reuse = (problems, compiled, rows_np)
            self._reuse_pass = self._pass
        tmr["upsert"] = _time.perf_counter() - t0
        # packed-vs-replayed split of THIS pass: a replayed row rode its
        # fingerprint (or the batch-identity fast path) without re-packing
        tmr["rows_packed"] = self._packed_this_pass
        tmr["rows_replayed"] = max(
            len(problems) - self._packed_this_pass, 0
        )
        t0 = _time.perf_counter()
        self._sync_device()
        tmr["sync"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        n = len(rows_np)
        # adaptive chunk: a straggler batch of a few hundred rows should
        # not execute a full 4096-row chunk (pow2 snapping keeps the trace
        # count logarithmic)
        eff_chunk = min(self.chunk, _pow2(max(n, 256)))
        n_pad = max(eff_chunk, -(-n // eff_chunk) * eff_chunk)
        n_chunks = n_pad // eff_chunk
        st = self._st
        # all-rows storm mode: the row-index upload is cached on device
        is_all = n == self.n_rows and np.array_equal(
            rows_np, np.arange(n, dtype=np.int32)
        )
        if is_all:
            if self._all_rows_n != n or self._all_rows_dev is None or (
                self._all_rows_dev.shape[0] != n_pad
            ):
                ar = np.full(n_pad, -1, np.int32)
                ar[:n] = np.arange(n, dtype=np.int32)
                self._all_rows_dev = jnp.asarray(ar)
                self._all_rows_n = n
            rows_dev = self._all_rows_dev
        else:
            ar = np.full(n_pad, -1, np.int32)
            ar[:n] = rows_np
            rows_dev = jnp.asarray(ar)
            self._last_upload_bytes += ar.nbytes

        reps_sel = st["replicas"][rows_np]
        strat_sel = st["strategy"][rows_np]
        max_n = int(reps_sel.max(initial=0))
        max_prev = int(st["prev_counts"][rows_np].max(initial=0))
        has_agg = bool((strat_sel == AGGREGATED).any())
        c = self.engine.snapshot.num_clusters
        from .core import kernel_variant

        wide, fast = kernel_variant(
            max(self._avail_max, max_n), self._static_max, max_prev, max_n, c
        )
        k_out = min(max(1, c), _pow2(max(max_n, 1)))
        is_dup = strat_sel == S_DUPLICATED
        need_bits = bool(is_dup.any() or (reps_sel == 0).any())
        bits_src = None
        if need_bits:
            # lazy feasibility bitsets: capture the PASS-TIME device
            # arrays (immutable) so a consumer decoding a Duplicated
            # result later gets this pass's sets even if the live tables
            # have since been rebuilt. Dispatched at most once per batch,
            # on first feasible/cluster access.
            _tables = self._dev_tables
            _state = self._dev_state
            _rows = rows_dev
            _chunk, _n_chunks = eff_chunk, n_chunks

            def bits_src():
                # the signature must carry every shape the trace closes
                # over: the cp-table capacity (slot growth re-traces), the
                # rows-buffer length, and the state cap — the old
                # (chunk, n_chunks)-only key let a slot-table growth mint
                # a new XLA trace that new_trace_last_pass never reported
                from ..parallel.mesh import mesh_shape as _bits_mesh_shape

                key = (
                    "B", _chunk, _n_chunks, _tables[0].shape,
                    int(_rows.shape[0]), int(_state[0].shape[0]),
                    # canonical mesh shape: the bits inputs commit to the
                    # mesh (replicated), so each shape is a distinct
                    # executable — a bool here let a mesh=2 manifest
                    # fake-warm a mesh=8 boot
                    _bits_mesh_shape(self._mesh),
                )
                if self._mark_trace(*key) and self._mesh is None:
                    # meshed dispatches stay manifest-UNRECORDED: the
                    # kernel has no mesh static, so a replay could only
                    # compile the single-device form and would seed this
                    # key as falsely warmed (see _quota_admission)
                    self._record_trace(
                        "fleet_bits", key, (*_tables, _rows, *_state),
                        chunk=_chunk, n_chunks=_n_chunks,
                    )
                return _fleet_bits(
                    *_tables, _rows, *_state, chunk=_chunk,
                    n_chunks=_n_chunks,
                )
        safe = int(
            np.minimum(np.where(is_dup, 0, reps_sel), k_out).sum()
        )
        # table-validated mesh (see __init__): the row axis shards over
        # "b" on every pass — batches are padded to the pow2 chunk, so
        # the mesh-divisible bucket holds by construction. The cluster
        # axis additionally shards when the engine opted in AND c divides
        # the "c" extent. mesh_el is the mesh's canonical SHAPE: the
        # trace-key/manifest element (a Mesh object is process-local; its
        # shape is the compile identity across processes and boots).
        from ..parallel.mesh import mesh_shape as _mesh_shape

        mesh = self._mesh
        shard_c = False
        if mesh is not None:
            c_sz = mesh.shape.get("c", 1)
            shard_c = (
                getattr(self.engine, "shard_clusters", False)
                and c_sz > 1
                and c % c_sz == 0
            )
        mesh_el = _mesh_shape(mesh)
        shared = dict(
            problems=problems, rows_np=rows_np, rows_dev=rows_dev, tmr=tmr,
            n=n, n_pad=n_pad, eff_chunk=eff_chunk, n_chunks=n_chunks,
            is_all=is_all, c=c, k_out=k_out, wide=wide, fast=fast,
            has_agg=has_agg, bits_src=bits_src, is_dup=is_dup, safe=safe,
            mesh=mesh, mesh_el=mesh_el, shard_c=shard_c,
            byte_wire=c <= 0xFFFF,
            # 21-bit entry packing: 2.625 B/entry when the site id fits
            # 13 bits — the churn wire is tunnel-bandwidth-bound
            pack21=c <= (1 << 13), t0=t0,
        )
        # host->device transfer of THIS pass so far (state scatter/upload
        # + row indices): the multichip bench's steady-pass bound — a
        # steady storm must ship changed rows' bytes, never the grid
        tmr["upload_mb"] = self._last_upload_bytes / 1e6
        if self.cap * c <= DENSE_RESIDENT_MAX_BYTES:
            res = self._solve_dense(**shared)
        else:
            res = self._solve_legacy(**shared)
        # this pass dispatched every reuse row, so the mirrors now cover
        # them at the current epoch — the delta-eligibility fence
        self._reuse_epoch = self._mirror_epoch
        return res

    #: full-pass buffer-tuning attributes frozen across a delta sub-pass:
    #: a few-thousand-row delta must never shrink the caps the next full
    #: storm dispatches at (every distinct cap pair is an XLA trace)
    _TUNE_ATTRS = (
        "_last_total", "_e_cap_cur", "_e_shrink_desire", "_m_cap_cur",
        "_shrink_desire", "_d_cap_cur", "_last_changed", "_last_dtotal",
        "_delta_live",
    )

    def _schedule_delta(self, problems, compiled, delta):
        """Partial pass: pack + dispatch ONLY the ``delta`` positions,
        replay every other row's result from the host mirrors. Returns
        None when ineligible — stale mirrors (a resident realloc since
        the covering pass), a moved snapshot generation, an uncertified
        kernel set, or a majority-dirty batch where the full pass is
        simply cheaper — and the caller runs the full pass."""
        import time as _time

        ru = self._reuse
        n = len(problems)
        if (
            ru is None
            or len(ru[0]) != n
            or len(ru[2]) != n
            or self._host_entries is None
            or self._host_meta is None
            or getattr(self.engine, "_snapshot_gen", 0) != self._snapshot_gen
            or self._reuse_epoch != self._mirror_epoch
            or not delta_certified()
        ):
            return None
        idx = np.unique(np.asarray(list(delta), np.int64))
        if idx.size and (idx[0] < 0 or idx[-1] >= n):
            return None
        if idx.size * 2 > n:
            return None  # majority dirty: the full pass wins
        t_all = _time.perf_counter()
        rows_full = ru[2]
        n_sub = int(idx.size)
        if n_sub == 0:
            # pure replay: nothing changed — serve the whole batch from
            # the mirrors without touching the device
            self._pass += 1
            self.new_trace_last_pass = False
            self._packed_this_pass = 0
            self._reuse = (problems, compiled, rows_full)
            self._reuse_pass = self._pass
            tmr: dict[str, float] = {
                "rows_packed": 0.0,
                "rows_replayed": float(n),
                "dirty_rows": 0.0,
            }
            res = self._replay_result(problems, rows_full, tmr)
            tmr["post"] = _time.perf_counter() - t_all
            self.last_breakdown = tmr
            return res
        sub_p = [problems[int(i)] for i in idx]
        sub_c = [compiled[int(i)] for i in idx]
        epoch = self._mirror_epoch
        cap_before = self.cap
        tune = tuple(getattr(self, a) for a in self._TUNE_ATTRS)
        # virgin tuning state for the sub dispatch: demand-sized caps
        # (the safe bounds for a sub batch — no overflow rerun possible)
        # keyed per pow2 sub-size bucket, so a settle train of equal-size
        # deltas converges to one trace instead of thrashing the tuned
        # full-pass caps
        self._last_total = None
        self._e_cap_cur = None
        self._e_shrink_desire = (None, 0)
        self._m_cap_cur = None
        self._shrink_desire = (None, 0)
        self._d_cap_cur = None
        self._last_changed = None
        self._last_dtotal = None
        self._delta_live = False
        try:
            self._schedule_pass(sub_p, sub_c)
        finally:
            for a, v in zip(self._TUNE_ATTRS, tune):
                setattr(self, a, v)
        if (
            self._mirror_epoch != epoch
            or self.cap != cap_before
            or self._reuse is None
        ):
            # a resident/mirror realloc (or table growth) happened inside
            # the sub pass: the replay base for the untouched rows is
            # gone — hand back to the caller for a full pass
            return None
        sub_rows = self._reuse[2]
        rows_new = rows_full
        if not np.array_equal(sub_rows, rows_full[idx]):
            rows_new = rows_full.copy()
            rows_new[idx] = sub_rows
        tmr = self.last_breakdown  # the sub pass's phase breakdown
        tmr["rows_replayed"] = float(n - n_sub)
        tmr["dirty_rows"] = float(n_sub)
        self._reuse = (problems, compiled, rows_new)
        self._reuse_pass = self._pass
        t0 = _time.perf_counter()
        res = self._replay_result(problems, rows_new, tmr)
        tmr["post"] = tmr.get("post", 0.0) + (_time.perf_counter() - t0)
        return res

    def _replay_result(self, problems, rows_full, tmr):
        """Batch result for ``rows_full`` built entirely from the host
        mirrors (entry runs + meta words) — the replay half of a delta
        pass. The mirrors cover every reuse row by induction: each row
        was dispatched by the pass that established the mapping (or a
        later one), and the _mirror_epoch fence rejects any realloc in
        between."""
        st = self._st
        n = len(problems)
        meta_sel = self._host_meta[rows_full]
        n_placed = (meta_sel & 0xFF).astype(np.int64)
        unsched = (meta_sel >> 8) & 1
        has_cand = (meta_sel >> 9) & 1
        reps_sel = st["replicas"][rows_full]
        is_dup = st["strategy"][rows_full] == S_DUPLICATED
        need_bits = bool(is_dup.any() or (reps_sel == 0).any())
        eff_chunk = min(self.chunk, _pow2(max(n, 256)))
        n_pad = max(eff_chunk, -(-n // eff_chunk) * eff_chunk)
        bits_src = None
        if need_bits:
            bits_src = self._bits_full_src(rows_full, n, n_pad, eff_chunk)
        self._result_gen += 1
        names = self.engine.snapshot.names
        batches = [
            _FleetBatch(
                names, self._host_entries, rows_full, bits_src,
                self, self._result_gen,
            )
        ]
        terms = [self._terms[r] for r in rows_full]
        return _FleetResultList(
            problems, terms, batches, n_pad, n_placed, unsched,
            has_cand, is_dup,
        )

    def _bits_full_src(self, rows_full, n, n_pad, eff_chunk):
        """Lazy feasibility-bitset thunk over the FULL reuse rows — the
        delta-pass counterpart of the inline bits closure in
        _schedule_pass (a replayed Duplicated row's consumer needs the
        whole batch's bitsets, not just the dirty sub-batch's). Dispatch
        + row-index upload are deferred to first access: most delta
        batches never decode a Duplicated row."""
        _tables = self._dev_tables
        _state = self._dev_state
        n_chunks = n_pad // eff_chunk

        def bits_src():
            from ..parallel.mesh import mesh_shape as _bits_mesh_shape

            ar = np.full(n_pad, -1, np.int32)
            ar[:n] = rows_full
            rows_dev = jnp.asarray(ar)
            key = (
                "B", eff_chunk, n_chunks, _tables[0].shape,
                int(rows_dev.shape[0]), int(_state[0].shape[0]),
                _bits_mesh_shape(self._mesh),
            )
            if self._mark_trace(*key) and self._mesh is None:
                self._record_trace(
                    "fleet_bits", key, (*_tables, rows_dev, *_state),
                    chunk=eff_chunk, n_chunks=n_chunks,
                )
            return _fleet_bits(
                *_tables, rows_dev, *_state, chunk=eff_chunk,
                n_chunks=n_chunks,
            )

        return bits_src

    def _alloc_resident(self, shape, dtype, mesh, *, c_axis=False):
        """Zeroed resident born on the solve's sharding layout (rows over
        mesh axis "b", optionally clusters over "c"): donation aliases
        input->output only when the shardings agree, so a resident must
        START on the layout the kernels pin their outputs to — otherwise
        the first meshed pass silently copies instead of aliasing."""
        if mesh is None:
            return jnp.zeros(shape, dtype)
        axes = ["b"] + [None] * (len(shape) - 1)
        if c_axis and len(shape) > 1:
            axes[1] = "c"
        return jnp.zeros(
            shape, dtype, device=NamedSharding(mesh, P(*axes))
        )

    def _upload_resident(self, host, mesh, *, c_axis=False):
        """Host mirror -> device resident on the same layout rule as
        ``_alloc_resident`` (the donation-overflow re-upload path)."""
        arr = jnp.asarray(host)
        if mesh is None:
            return arr
        axes = ["b"] + [None] * (arr.ndim - 1)
        if c_axis and arr.ndim > 1:
            axes[1] = "c"
        return jax.device_put(arr, NamedSharding(mesh, P(*axes)))

    def _solve_legacy(
        self, *, problems, rows_np, rows_dev, tmr, n, n_pad, eff_chunk,
        n_chunks, is_all, c, k_out, wide, fast, has_agg, bits_src, is_dup,
        safe, mesh, mesh_el, shard_c, byte_wire, pack21, t0,
    ) -> "_FleetResultList":
        """Single-dispatch entry-resident solve — the path for tables whose
        dense mirror would exceed the HBM budget (multi-million-row
        fleets). Everything ships per pass: full meta + tuned entry
        stream."""
        import time as _time

        cap_round = _cap_round
        # delta base: device-resident per-row entry vectors + the matching
        # host mirror, k_res wide (grow-only running max of k_out so a
        # straggler batch with smaller replicas doesn't wipe the base).
        # Table growth, a k_res increase, or a mesh-layout change resets
        # both — the next pass reports every row changed and refills them.
        k_res = max(self._k_res, k_out)
        if (
            self._resident_entries is None
            or self._resident_entries.shape != (self.cap, k_res)
            or self._resident_mesh != mesh_el
        ):
            self._resident_entries = self._alloc_resident(
                (self.cap, k_res), jnp.int32, mesh
            )
            self._host_entries = np.zeros((self.cap, k_res), np.int32)
            self._resident_mesh = mesh_el
            self._mirror_epoch += 1
        if self._host_meta is None or self._host_meta.shape[0] != self.cap:
            # legacy meta mirror: the wire ships full meta every pass, so
            # the mirror is pure bookkeeping here — but it is what lets a
            # delta pass replay untouched rows' n_placed/unsched/has_cand
            # without re-dispatching them
            self._host_meta = np.zeros(self.cap, np.int32)
            self._mirror_epoch += 1
        self._k_res = k_res

        # fetched bytes scale with e_cap, so tune it to ~1.25x the last
        # observed total; the safe bound can never overflow and is the
        # first-pass / fallback trace. Hysteresis: grow immediately, shrink
        # only after two consecutive lower demands — every distinct e_cap is
        # a fresh XLA trace, and a demand oscillating across a quantum
        # boundary was recompiling the solve once per storm wave
        # _last_total tracks the last pass's CHANGED-entry total — under
        # delta fetch a steady storm's demand is ~zero, so the tuned cap
        # (and with it the fetched buffer) collapses to the floor quantum;
        # a churn burst overflows once, reruns at the safe bound, and the
        # cap follows it back up
        def l_key(cap: int) -> tuple:
            # mesh_el (the canonical mesh SHAPE, not a bool): partitioned
            # executables are distinct per mesh shape, and the manifest
            # key must never let a mesh=1 record seed a mesh=8 boot
            return (
                "L", self.cap, c, self._dev_tables[0].shape, eff_chunk,
                n_chunks, k_out, k_res, cap, wide, fast, has_agg, is_all,
                mesh_el, shard_c, pack21 and byte_wire,
            )

        prev_e = self._e_cap_cur
        needed = cap_round(safe)
        if self._last_total is not None and self._last_total * 5 // 4 < safe:
            needed = min(needed, cap_round(self._last_total * 5 // 4))
        # demand-based grow-immediately / shrink-on-sustained-desire (same
        # policy as the dense pair: 2 passes to switch to an already-
        # compiled trace, SHRINK_SUSTAIN to compile a smaller one)
        if prev_e is None or needed >= prev_e:
            e_cap = needed
            self._e_shrink_desire = (None, 0)
        else:
            e_cap = prev_e
            tgt, cnt = self._e_shrink_desire
            cnt = cnt + 1 if tgt == needed else 1
            self._e_shrink_desire = (needed, cnt)
            sustain = (
                2 if l_key(needed) in self._seen_traces else SHRINK_SUSTAIN
            )
            if cnt >= sustain:
                e_cap = needed
                self._e_shrink_desire = (None, 0)
        self._e_cap_cur = e_cap

        def solve(rows_slice, cap, resident):
            if self._mark_trace(*l_key(cap)):
                self._record_trace(
                    "fleet_solve", l_key(cap),
                    (*self._dev_tables, rows_slice, *self._dev_state,
                     resident),
                    chunk=eff_chunk, n_chunks=n_chunks, k_out=k_out,
                    k_res=k_res, e_cap=cap, wide=wide, fast=fast,
                    has_aggregated=has_agg, all_rows=is_all, mesh=mesh,
                    shard_c=shard_c, pack21=pack21 and byte_wire,
                )
            return _fleet_solve(
                *self._dev_tables,
                rows_slice,
                *self._dev_state,
                resident,
                chunk=eff_chunk,
                n_chunks=n_chunks,
                k_out=k_out,
                k_res=k_res,
                e_cap=cap,
                wide=wide,
                fast=fast,
                has_aggregated=has_agg,
                all_rows=is_all,
                mesh=mesh,
                shard_c=shard_c,
                pack21=pack21 and byte_wire,
            )

        def decode(arr, cap):
            """(total, meta int32[n_pad], stream int32[*])"""
            if byte_wire:
                from .. import native

                total = native.le32(arr)
                meta = native.decode2(arr[4 : 4 + 2 * n_pad])
                tail = arr[4 + 2 * n_pad :]
                stream = (
                    native.decode21(tail, cap)
                    if pack21
                    else native.decode3(tail)
                )
                return total, meta, stream
            return int(arr[0]), arr[1 : 1 + n_pad], arr[1 + n_pad :]

        tmr["prep"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        # the resident base is DONATED into the dispatch: detach the
        # attribute first so a pass that dies mid-solve leaves no
        # deleted-buffer reference behind (the next pass re-seeds the
        # delta base instead of crashing on a consumed array)
        res_in, self._resident_entries = self._resident_entries, None
        flat, resident = solve(rows_dev, e_cap, res_in)
        tmr["dispatch"] = _time.perf_counter() - t0
        # device fence at the span boundary: block_until_ready splits the
        # on-device execute (plus compile, when this pass minted a fresh
        # trace) from the host-side transfer+decode that follows — the
        # fetch would block on the same event anyway, so the fence costs
        # nothing and buys the device/host attribution
        t0 = _time.perf_counter()
        flat.block_until_ready()
        tmr["device"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        raw = np.asarray(flat)
        fetched_bytes = raw.nbytes
        total, meta, stream = decode(raw, e_cap)
        if total > e_cap:
            # overflow: rerun at the safe bound. The first dispatch
            # DONATED the pre-pass resident, so the rerun diffs against a
            # re-upload of the host mirror — identical content by
            # construction (the fold below has not run yet). One extra
            # upload on the rare overflow pass buys alias-in-place on
            # every steady pass.
            res_in = self._upload_resident(self._host_entries, mesh)
            tmr["upload_mb"] = (
                tmr.get("upload_mb", 0.0) + self._host_entries.nbytes / 1e6
            )
            flat, resident = solve(rows_dev, cap_round(safe), res_in)
            raw = np.asarray(flat)
            fetched_bytes += raw.nbytes
            total, meta, stream = decode(raw, cap_round(safe))
        assert total <= len(stream), (total, e_cap)
        self._resident_entries = resident
        tmr["fetch"] = _time.perf_counter() - t0
        tmr["fetch_mb"] = fetched_bytes / 1e6
        t0 = _time.perf_counter()
        self._last_total = total
        n_placed = (meta & 0xFF).astype(np.int64)
        unsched = (meta >> 8) & 1
        has_cand = (meta >> 9) & 1
        changed = ((meta >> 10) & 1).astype(bool)
        # meta mirror covers every dispatched row (state bits only — the
        # changed flag is a per-pass wire artifact, not row state)
        self._host_meta[rows_np] = (
            np.asarray(meta[:n]) & 0x3FF
        ).astype(np.int32)
        # fold the changed rows' entry runs into the persistent host mirror
        ch_pos = np.flatnonzero(changed[:n])
        if len(ch_pos):
            from .. import native

            native.fold_entries(
                self._host_entries, rows_np[ch_pos], n_placed[ch_pos],
                np.asarray(stream, np.int32),
            )
        tmr["changed_rows"] = float(len(ch_pos))
        self._result_gen += 1

        names = self.engine.snapshot.names
        batches = [
            _FleetBatch(
                names, self._host_entries, rows_np, bits_src,
                self, self._result_gen,
            )
        ]
        terms = [self._terms[r] for r in rows_np]
        tmr["post"] = _time.perf_counter() - t0
        self.last_breakdown = tmr
        return _FleetResultList(
            problems, terms, batches, n_pad, n_placed, unsched,
            has_cand, is_dup,
        )

    def _e_key(
        self, chunk: int, n_chunks: int, k_out: int, e_cap: int,
        byte_wire: bool, pack21: bool,
    ) -> tuple:
        """THE ``_fleet_entries`` trace signature, shared by the exact
        phase-B fetch and the speculative dispatch in ``_solve_dense``.
        The two sites used to compose the cluster-count element
        differently (``self._res_dense.shape[1]`` vs the pass-local
        ``c``), so the same trace could be ledgered under two keys —
        spuriously flipping ``new_trace_last_pass`` (and double-entering
        the manifest). Keyed on the resident's OWN shape: that is the
        array the trace closes over. The resident's mesh layout rides
        along — a row-sharded dense resident compiles a different
        (gather-collective-bearing) executable than a single-device one."""
        return (
            "E", self._res_dense.shape[0], self._res_dense.shape[1],
            chunk, n_chunks, k_out, e_cap, byte_wire, pack21,
            self._resident_mesh,
        )

    @property
    def _entries_mesh(self):
        """Mesh arg for a phase-B dispatch: the mesh the dense resident
        was allocated on (None when it was born single-device)."""
        return self._mesh if self._resident_mesh is not None else None

    def _mark_entries_trace(
        self, rows_dev, *, chunk, n_chunks, k_out, e_cap, byte_wire, pack21,
    ) -> None:
        """Ledger + manifest entry for a ``_fleet_entries`` dispatch."""
        key = self._e_key(chunk, n_chunks, k_out, e_cap, byte_wire, pack21)
        if self._mark_trace(*key):
            self._record_trace(
                "fleet_entries", key, (self._res_dense, rows_dev),
                chunk=chunk, n_chunks=n_chunks, k_out=k_out, e_cap=e_cap,
                byte_wire=byte_wire, pack21=pack21,
                mesh=self._entries_mesh,
            )

    def _fetch_fold_exact(
        self, rows, counts, *, eff_chunk, k_out, byte_wire, pack21, tmr,
    ) -> int:
        """Dispatch an exact phase B over ``rows``, fetch its entry wire,
        and fold the full runs into the host mirror. The entry cap is
        host-summed from ``counts`` so overflow is structurally
        impossible. Returns the fetched byte count."""
        import time as _time

        e_want = int(counts.sum())
        m_pad_b = max(2048, _pow2(len(rows)))
        b_chunk = min(eff_chunk, m_pad_b)
        rows_b = np.full(m_pad_b, -1, np.int32)
        rows_b[: len(rows)] = rows
        e_cap = _cap_round(max(e_want, 1))
        t_b = _time.perf_counter()
        rows_b_dev = jnp.asarray(rows_b)
        self._mark_entries_trace(
            rows_b_dev, chunk=b_chunk, n_chunks=m_pad_b // b_chunk,
            k_out=k_out, e_cap=e_cap, byte_wire=byte_wire,
            pack21=pack21 and byte_wire,
        )
        flat2 = _fleet_entries(
            self._res_dense,
            rows_b_dev,
            chunk=b_chunk,
            n_chunks=m_pad_b // b_chunk,
            k_out=k_out,
            e_cap=e_cap,
            byte_wire=byte_wire,
            pack21=pack21 and byte_wire,
            mesh=self._entries_mesh,
        )
        tmr["dispatch_b"] = _time.perf_counter() - t_b
        t_b = _time.perf_counter()
        raw2 = np.asarray(flat2)
        tmr["fetch_b"] = _time.perf_counter() - t_b
        total2, stream = _decode_entry_wire(raw2, e_cap, byte_wire, pack21)
        assert total2 == e_want, (total2, e_want)
        from .. import native

        native.fold_entries(
            self._host_entries, rows, counts, np.asarray(stream, np.int32)
        )
        return raw2.nbytes

    def _solve_dense(
        self, *, problems, rows_np, rows_dev, tmr, n, n_pad, eff_chunk,
        n_chunks, is_all, c, k_out, wide, fast, has_agg, bits_src, is_dup,
        safe, mesh, mesh_el, shard_c, byte_wire, pack21, t0,
    ) -> "_FleetResultList":
        """Two-phase solve: _fleet_pass (divide + dense diff, ~13 KB wire
        on a steady pass) and, only when rows changed, _fleet_entries over
        exactly those rows with an exactly-sized entry buffer (no
        overflow rerun by construction)."""
        import time as _time

        if (
            self._res_dense is None
            or self._res_dense.shape != (self.cap, c)
            or self._resident_mesh != mesh_el
        ):
            self._res_dense = self._alloc_resident(
                (self.cap, c), jnp.uint8, mesh, c_axis=shard_c
            )
            self._res_meta = self._alloc_resident(
                (self.cap,), jnp.int32, mesh
            )
            self._host_meta = np.zeros(self.cap, np.int32)
            self._resident_mesh = mesh_el
            self._mirror_epoch += 1
        # host entry mirror: width grows in place (no resident to reset —
        # the dense base is width-independent)
        k_res = max(self._k_res, k_out)
        if self._host_entries is None or self._host_entries.shape[0] != (
            self.cap
        ):
            self._host_entries = np.zeros((self.cap, k_res), np.int32)
        elif self._host_entries.shape[1] < k_res:
            self._host_entries = np.pad(
                self._host_entries,
                ((0, 0), (0, k_res - self._host_entries.shape[1])),
            )
        self._k_res = k_res

        # changed-meta buffer: tuned like the legacy e_cap but overflow
        # costs one cheap _gather_meta round-trip, not a solve rerun
        def m_round(v: int) -> int:
            v = max(v, 1)
            q = -(-v // M_ROUND) * M_ROUND if v > 4096 else 4096
            return min(q, n_pad)

        def a_key(m: int, d: int) -> tuple:
            # mesh_el: canonical mesh shape (see l_key) — partitioned
            # executables and their manifest records are per-shape
            return (
                "A", self.cap, c, self._dev_tables[0].shape, eff_chunk,
                n_chunks, wide, fast, has_agg, is_all, m, d,
                mesh_el, shard_c,
            )

        # cap tuning, demand-based. Every distinct (m_cap, d_cap) pair is a
        # fresh XLA trace, so the policy is built around compile cost:
        # - GROW immediately when demand threatens a cap (overflow costs a
        #   round-trip or a full-row fold; growth normally lands in churn
        #   onset, which warm loops cover);
        # - SHRINK only on sustained desire: 2 consecutive passes when the
        #   smaller pair is already compiled (cheap switch), SHRINK_SUSTAIN
        #   when it would compile a new trace (a demand-regime shift like
        #   onset-overshoot -> steady churn; a wobble never qualifies, and
        #   warm loops that run past the window absorb the one compile —
        #   vote-delayed shrinks used to fire mid-storm: a 94s dispatch
        #   stall on the bench).
        # m demand: the changed-row count; d demand: the cell-delta count
        # with 1.5x headroom (dtotal wobbles a few percent pass to pass).
        needed_m = m_round(n)
        if self._last_changed is not None and (
            self._last_changed * 5 // 4 < n
        ):
            needed_m = min(needed_m, m_round(self._last_changed * 5 // 4))
        d_on = byte_wire and c <= (1 << 15)
        last = self._last_dtotal or 0
        d_need_min = (d_round(last * 9 // 8) if last else D_FLOOR) if d_on else 0
        d_need_tgt = (
            min(d_round(last * 3 // 2) if last else D_FLOOR,
                d_round(n_pad * 63))
            if d_on
            else 0
        )
        cur_m, cur_d = self._m_cap_cur, self._d_cap_cur
        if cur_m is None:
            m_cap, d_cap = needed_m, d_need_tgt
            self._shrink_desire = (None, 0)
        else:
            m_cap, d_cap = cur_m, (cur_d or 0) if d_on else 0
            grow_m = needed_m > cur_m
            grow_d = d_on and d_cap < d_need_min
            if grow_m:
                m_cap = needed_m
            if grow_d:
                d_cap = d_need_tgt
            if grow_m or grow_d:
                self._shrink_desire = (None, 0)
            else:
                want_m = min(needed_m, m_cap)
                want_d = (
                    d_need_tgt
                    if d_on and d_need_tgt * 2 <= d_cap
                    else d_cap
                )
                want = (want_m, want_d)
                if want != (m_cap, d_cap):
                    tgt, cnt = self._shrink_desire
                    cnt = cnt + 1 if tgt == want else 1
                    self._shrink_desire = (want, cnt)
                    sustain = (
                        2 if a_key(*want) in self._seen_traces
                        else SHRINK_SUSTAIN
                    )
                    if cnt >= sustain:
                        m_cap, d_cap = want
                        self._shrink_desire = (None, 0)
                else:
                    self._shrink_desire = (None, 0)
        self._m_cap_cur = m_cap
        self._d_cap_cur = d_cap if d_on else None

        cap_round = _cap_round
        tmr["prep"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        if self._mark_trace(*a_key(m_cap, d_cap)):
            self._record_trace(
                "fleet_pass", a_key(m_cap, d_cap),
                (*self._dev_tables, rows_dev, *self._dev_state,
                 self._res_dense, self._res_meta),
                chunk=eff_chunk, n_chunks=n_chunks, wide=wide, fast=fast,
                has_aggregated=has_agg, all_rows=is_all, m_cap=m_cap,
                d_cap=d_cap, mesh=mesh, shard_c=shard_c,
            )
        # the dense residents are DONATED into the pass: detach the
        # attributes first so a dispatch that dies cannot leave deleted-
        # buffer references (the next pass reallocates a zeroed, mutually
        # consistent resident/mirror pair and re-reports every row)
        rd_in, self._res_dense = self._res_dense, None
        rm_in, self._res_meta = self._res_meta, None
        flat, rowbuf, rd, rm = _fleet_pass(
            *self._dev_tables,
            rows_dev,
            *self._dev_state,
            rd_in,
            rm_in,
            chunk=eff_chunk,
            n_chunks=n_chunks,
            wide=wide,
            fast=fast,
            has_aggregated=has_agg,
            all_rows=is_all,
            m_cap=m_cap,
            d_cap=d_cap,
            mesh=mesh,
            shard_c=shard_c,
        )
        self._res_dense, self._res_meta = rd, rm
        # speculative phase B: when the last pass saw churn AND could not
        # ride the delta wire, dispatch the entry compaction over A's
        # device-resident changed-row buffer BEFORE fetching A — B
        # executes back-to-back with A on device and its wire overlaps
        # A's decode, removing a round-trip from the churn critical path.
        # Steady passes (last_changed == 0) and delta-carried churn skip
        # it (the full-row sort + wire would be pure waste there).
        spec_flat = None
        spec_cap = 0
        spec_used = False
        # skip the speculation when the cell-delta wire is expected to carry
        # this pass (cap already grown past the last observed demand): the
        # full-row sort + wire would be pure waste — and on the async tunnel
        # an unfetched speculative dispatch is WORSE than waste: its compile
        # + execution stay queued on device and surface in the NEXT pass's
        # blocking fetch (round 4's recorded 136s 1M churn onset was exactly
        # the warm pass's unused speculative _fleet_entries compile draining
        # into timed pass 0).
        delta_expected = bool(
            d_cap and self._last_dtotal and self._last_dtotal <= d_cap
        )
        if (
            self._last_changed and self._last_total
            and not self._delta_live and not delta_expected
        ):
            spec_cap = cap_round(self._last_total * 9 // 8)
            b_chunk = min(eff_chunk, m_cap)
            self._mark_entries_trace(
                rowbuf, chunk=b_chunk, n_chunks=m_cap // b_chunk,
                k_out=k_out, e_cap=spec_cap, byte_wire=byte_wire,
                pack21=pack21 and byte_wire,
            )
            spec_flat = _fleet_entries(
                self._res_dense,
                rowbuf,
                chunk=b_chunk,
                n_chunks=m_cap // b_chunk,
                k_out=k_out,
                e_cap=spec_cap,
                byte_wire=byte_wire,
                pack21=pack21 and byte_wire,
                mesh=self._entries_mesh,
            )
        tmr["dispatch"] = _time.perf_counter() - t0
        # device fence (see _solve_legacy): splits phase A's on-device
        # execute (+compile on a fresh trace) from the wire/decode window.
        # The speculative B keeps running behind it — the fence waits on
        # A's output only, so the B-overlaps-A's-decode flow is preserved.
        t0 = _time.perf_counter()
        flat.block_until_ready()
        tmr["device"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        # NOTE (measured, round 4): fusing A's wire with the speculative
        # B's into one device-side concat + single fetch LOSES to two
        # sequential fetches on the tunnel (churn p50 1.11s fused vs 0.79s
        # split, back-to-back A/B at 100k x 5k) — the link moves two
        # in-flight buffers faster than one large one, and B's transfer
        # overlaps A's fetch+decode. Keep the two-fetch flow.
        raw = np.asarray(flat)
        tmr["fetch_a"] = _time.perf_counter() - t0
        fetched_bytes = raw.nbytes
        from .. import native

        total = native.le32(raw)
        nb = n_pad // 8
        changed_bits = np.unpackbits(
            raw[4 : 4 + nb], bitorder="little"
        )[:n_pad].astype(bool)
        ch_pos = np.flatnonzero(changed_bits)
        assert len(ch_pos) == total, (len(ch_pos), total)
        ch_rows = rows_np[ch_pos] if total else np.empty(0, np.int64)
        have_dcounts = total <= m_cap
        if have_dcounts:
            metas = native.decode2(raw[4 + nb : 4 + nb + 2 * m_cap])[:total]
        else:
            # tuned buffer overflow (churn onset): one gather round-trip.
            # res_meta stores STATE only, so the per-row delta counts are
            # lost — this pass folds via the full-row phase B flow.
            m_pad_f = max(4096, _pow2(total))
            rows_f = np.full(m_pad_f, -1, np.int32)
            rows_f[:total] = ch_rows
            self._mark_trace("G", self.cap, m_pad_f, self._resident_mesh)
            mraw = np.asarray(
                _gather_meta(self._res_meta, jnp.asarray(rows_f))
            )
            fetched_bytes += mraw.nbytes
            metas = native.decode2(mraw)[:total]
        self._last_changed = total
        state = metas & 0x3FF  # n_placed | unsched<<8 | has_cand<<9
        off_d = 4 + nb + 2 * m_cap
        dtotal = native.le32(raw[off_d : off_d + 4]) if d_cap else None

        # fold: cell deltas when they fit, full-row phase B otherwise
        use_delta = False
        if total:
            self._host_meta[ch_rows] = state
            counts = (state & 0xFF).astype(np.int64)
            e_total = int(counts.sum())
            self._last_total = e_total
            use_delta = bool(
                d_cap and have_dcounts and dtotal <= d_cap
            )
            if use_delta:
                t_b = _time.perf_counter()
                dch = metas >> 10  # min(changed cells, 63) per changed row
                norm = dch <= 62
                nd_norm = dch[norm].astype(np.int64)
                assert int(nd_norm.sum()) == dtotal, (
                    int(nd_norm.sum()), dtotal,
                )
                if dtotal:
                    dstream = native.decode3(
                        raw[off_d + 4 : off_d + 4 + 3 * dtotal]
                    )
                    native.apply_deltas(
                        self._host_entries, ch_rows[norm], nd_norm, dstream
                    )
                # decode+merge time only; an overflow-row fetch below
                # reports its own dispatch_b/fetch_b
                tmr["delta_fold"] = _time.perf_counter() - t_b
                tmr["delta_rows"] = float(int(norm.sum()))
                rows_over = ch_rows[~norm]
                if rows_over.size:
                    # rows whose delta count overflowed the 6-bit meta
                    # field: fetch their full entry runs exactly
                    fetched_bytes += self._fetch_fold_exact(
                        rows_over, counts[~norm], eff_chunk=eff_chunk,
                        k_out=k_out, byte_wire=byte_wire, pack21=pack21,
                        tmr=tmr,
                    )
            elif not e_total:
                # every changed row lost its placements: clear the runs
                # (the fold below zero-fills rows it writes, covering the
                # mixed case without a second full sweep)
                self._host_entries[ch_rows] = 0
            if e_total and not use_delta:
                if (
                    spec_flat is not None
                    and total <= m_cap
                    and e_total <= spec_cap
                ):
                    # the speculative B covers exactly the changed rows
                    spec_used = True
                    t_b = _time.perf_counter()
                    raw2 = np.asarray(spec_flat)
                    fetched_bytes += raw2.nbytes
                    tmr["fetch_b"] = _time.perf_counter() - t_b
                    total2, stream = _decode_entry_wire(
                        raw2, spec_cap, byte_wire, pack21
                    )
                    assert total2 == e_total, (total2, e_total)
                    native.fold_entries(
                        self._host_entries, ch_rows, counts,
                        np.asarray(stream, np.int32),
                    )
                else:
                    # exact fallback: churn onset (no speculation) or the
                    # speculative caps were too small
                    fetched_bytes += self._fetch_fold_exact(
                        ch_rows, counts, eff_chunk=eff_chunk, k_out=k_out,
                        byte_wire=byte_wire, pack21=pack21, tmr=tmr,
                    )
        else:
            self._last_total = 0
        if spec_flat is not None and not spec_used:
            # speculation mispredicted (the pass folded another way): block
            # it out NOW and account the cost in this pass — an unfetched
            # dispatch would otherwise drain into the next pass's fetch
            t_b = _time.perf_counter()
            spec_flat.block_until_ready()
            tmr["spec_drain"] = _time.perf_counter() - t_b
        self._delta_live = use_delta
        if d_cap:
            self._last_dtotal = int(dtotal)
        tmr["fetch"] = _time.perf_counter() - t0
        tmr["fetch_mb"] = fetched_bytes / 1e6
        tmr["changed_rows"] = float(total)
        t0 = _time.perf_counter()

        meta_sel = self._host_meta[rows_np]
        n_placed = (meta_sel & 0xFF).astype(np.int64)
        unsched = (meta_sel >> 8) & 1
        has_cand = (meta_sel >> 9) & 1
        self._result_gen += 1
        names = self.engine.snapshot.names
        batches = [
            _FleetBatch(
                names, self._host_entries, rows_np, bits_src,
                self, self._result_gen,
            )
        ]
        terms = [self._terms[r] for r in rows_np]
        tmr["post"] = _time.perf_counter() - t0
        self.last_breakdown = tmr
        return _FleetResultList(
            problems, terms, batches, n_pad, n_placed, unsched,
            has_cand, is_dup,
        )
