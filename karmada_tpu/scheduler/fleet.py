"""Device-resident fleet scheduling: the informer->cache analogue.

Ref: pkg/scheduler/cache/cache.go:42-62 — the reference keeps a cluster
cache fed by informers so each scheduling attempt touches only deltas.
This module is that idea taken device-side: per-binding state (placement
slot, request profile slot, previous assignment sites, replicas, flags)
lives in HBM between scheduling passes, and each pass is

    host delta scatter  ->  ONE fused XLA dispatch  ->  ONE compact fetch.

Why this exists: round 1's engine packed every BindingProblem from scratch
per pass (Python loops over sparse entries + per-chunk np.pad + per-chunk
device syncs), which capped the engine at ~4k bindings/s while the kernel
alone did 100k x 5k in 0.74 s. The fleet table removes all per-pass O(B)
host packing for unchanged bindings and all but one device round-trip.

Tunnel-aware design (measured on the v5e tunnel: ~20-30 MB/s transfers with
~0.4-0.8 s fixed cost per transfer, ~100 ms per dispatch):

- all per-row state is gathered ON DEVICE from resident arrays (`rows` is
  the only per-pass index upload, and the all-rows storm case keeps even
  that cached on device);
- placement/taint/static-weight masks are interned per unique placement and
  gathered per chunk via the one-hot-matmul row gather
  (ops.estimate.gather_profile_rows) — plain [B]-index gathers inside
  lax.scan hang XLA compilation on the tunneled backend;
- results come back as ONE flat int32 array: a compacted
  (site << 8 | count) entry stream plus one metadata word per row; feasible
  bitsets ride a second, lazily-fetched output only when the batch contains
  Duplicated or zero-replica bindings.

Eligibility: a binding rides the fleet path when its placement has a single
affinity term, no spread-constraint selection (or the static-weight ignore
rule, select_clusters.go:63-78), no eviction tasks, <= K_PREV previous
sites, and (for Divided strategies) replicas <= MAX_REPLICAS_FAST so the
per-row top_k bound holds. Everything else takes the general host path —
the two paths are differentially fuzz-tested for identical placements.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.divide import AGGREGATED, DUPLICATED as S_DUPLICATED, _divide_batch
from ..ops.estimate import MAX_INT32, gather_profile_rows, merge_estimates

K_PREV = 32  # max previous-assignment sites on the fast path (small fleets
# legitimately spread one binding over dozens of clusters; rows beyond this
# take the general host path)
MAX_REPLICAS_FAST = 128  # divided-strategy replica cap (bounds top_k)
MAX_SLOTS = 4096  # unique placements/gvks/profiles before table rebuild
E_ROUND = 1 << 18  # entry-buffer quantum (bounds trace churn)


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# --------------------------------------------------------------------------
# fused solve
# --------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=(
        "chunk", "n_chunks", "k_out", "e_cap", "wide", "fast",
        "has_aggregated", "need_bits",
    ),
)
def _fleet_solve(
    cp_table,  # int32[U, 3C]: [aff&spread_field | taint | static_w]
    gvk_table,  # int32[G, C]
    prof_table,  # int32[P, C] general availability (-1 = no answer)
    incomplete_en,  # bool[C] — ~CompleteAPIEnablements
    rows,  # int32[n_pad] table rows (-1 = padding)
    cp_idx, gvk_idx, prof_idx,  # int32[cap]
    replicas, strategy,  # int32[cap]
    fresh,  # bool[cap]
    prev_sites, prev_counts,  # int32[cap, K_PREV]
    *,
    chunk: int,
    n_chunks: int,
    k_out: int,
    e_cap: int,
    wide: bool,
    fast: Optional[tuple],
    has_aggregated: bool,
    need_bits: bool,
):
    c = gvk_table.shape[1]
    valid = rows >= 0
    r = jnp.maximum(rows, 0)
    # compact per-pass state ([n_pad]), gathered outside the scan
    cp = cp_idx[r]
    gv = gvk_idx[r]
    pf = prof_idx[r]
    reps = jnp.where(valid, replicas[r], 0)
    st = strategy[r]
    fr = fresh[r] & valid
    ps = prev_sites[r]
    pc = jnp.where(valid[:, None], prev_counts[r], 0)

    def body(carry, i):
        sl = lambda a: lax.dynamic_slice_in_dim(a, i * chunk, chunk, axis=0)
        cpc, gvc, pfc = sl(cp), sl(gv), sl(pf)
        repsc, stc, frc, vc = sl(reps), sl(st), sl(fr), sl(valid)
        psc, pcc = sl(ps), sl(pc)
        prev = (
            jnp.zeros((chunk, c), jnp.int32)
            .at[jnp.arange(chunk)[:, None], psc]
            .add(pcc)
        )
        prev_mask = prev > 0
        cp_rows = gather_profile_rows(cp_table, cpc)  # [chunk, 3C]
        aff_m = cp_rows[:, :c] != 0
        taint_m = cp_rows[:, c : 2 * c] != 0
        static_w = cp_rows[:, 2 * c :]
        gvk_m = gather_profile_rows(gvk_table, gvc) != 0
        general = gather_profile_rows(prof_table, pfc)
        # mask composition — same algebra as TensorScheduler._pack_chunk
        feasible = (
            aff_m
            & (gvk_m | (prev_mask & incomplete_en[None, :]))
            & (taint_m | prev_mask)
            & vc[:, None]
        )
        avail = merge_estimates(repsc, (general,))
        rix = jnp.arange(chunk)[:, None]
        if fast is not None:
            # the dispense's packed-key top_k already identifies every
            # cluster the division can touch outside the previous sites
            # (take_by_weight_fast return_sites note); gathering at those
            # k_top + K_PREV sites replaces a full-width top_k
            assignment, unsched, tk_sites = _divide_batch(
                stc, repsc, feasible, static_w, avail, prev, frc,
                has_aggregated, wide, fast, want_sites=True,
            )
            # Duplicated rows are represented by the feasible bitset (their
            # count is just `replicas` everywhere feasible); zero their
            # dense rows so the entry stream carries only Divided placements
            assignment = jnp.where(
                (stc == S_DUPLICATED)[:, None], 0, assignment
            )
            g_tk = assignment[rix, tk_sites]
            g_pv = assignment[rix, psc]
            # previous sites already covered by the top-k set emit there
            dup_prev = (psc[:, :, None] == tk_sites[:, None, :]).any(-1)
            g_pv = jnp.where(dup_prev | (pcc <= 0), 0, g_pv)
            idx = jnp.concatenate([tk_sites, psc], axis=1)
            vals = jnp.concatenate([g_tk, g_pv], axis=1)
        else:
            assignment, unsched = _divide_batch(
                stc, repsc, feasible, static_w, avail, prev, frc,
                has_aggregated, wide, fast,
            )
            assignment = jnp.where(
                (stc == S_DUPLICATED)[:, None], 0, assignment
            )
            vals, idx = lax.top_k(assignment, k_out)
        n_placed = (vals > 0).sum(axis=1).astype(jnp.int32)
        has_cand = feasible.any(axis=1)
        outs = (idx.astype(jnp.int32), vals, n_placed, unsched, has_cand)
        if need_bits:
            pad = (-c) % 32
            f = jnp.pad(feasible, ((0, 0), (0, pad)))
            w32 = f.reshape(chunk, -1, 32).astype(jnp.uint32)
            shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :]
            outs = outs + ((w32 << shifts).sum(axis=-1, dtype=jnp.uint32),)
        return carry, outs

    _, outs = lax.scan(body, 0, jnp.arange(n_chunks))
    width = outs[0].shape[-1]
    sites = outs[0].reshape(-1, width)
    counts = outs[1].reshape(-1, width)
    n_placed = outs[2].reshape(-1)
    unsched = outs[3].reshape(-1)
    has_cand = outs[4].reshape(-1)

    # compact the (site, count) pairs into one row-major entry stream;
    # positions with a zero count are the padding the site lists carry
    valid_e = (counts > 0).reshape(-1)
    offs = jnp.cumsum(valid_e.astype(jnp.int32)) - valid_e
    total = offs[-1] + valid_e[-1].astype(jnp.int32)
    packed = (sites.reshape(-1) << 8) | counts.reshape(-1)
    write = jnp.where(valid_e & (offs < e_cap), offs, e_cap)
    buf = jnp.zeros((e_cap + 1,), jnp.int32).at[write].set(packed)
    entries = buf[:e_cap]

    # one metadata word per row: n_placed | unsched<<8 | has_cand<<9
    meta = (
        n_placed
        | (unsched.astype(jnp.int32) << 8)
        | (has_cand.astype(jnp.int32) << 9)
    )
    c_total = gvk_table.shape[1]
    if c_total <= 0xFFFF:
        # byte wire: transfer bytes are the pass's budget, and a packed
        # entry fits 3 bytes when the site index fits 16 bits (counts are
        # <= MAX_REPLICAS_FAST < 256, meta words < 2^10). Bytes are
        # decomposed with shifts, not bitcasts, so the layout is
        # endianness-independent.
        total_u8 = jnp.stack(
            [(total >> s) & 0xFF for s in (0, 8, 16, 24)]
        ).astype(jnp.uint8)
        meta_u8 = jnp.stack(
            [meta & 0xFF, (meta >> 8) & 0xFF], axis=-1
        ).astype(jnp.uint8).reshape(-1)
        e_u8 = jnp.stack(
            [entries & 0xFF, (entries >> 8) & 0xFF, (entries >> 16) & 0xFF],
            axis=-1,
        ).astype(jnp.uint8).reshape(-1)
        flat = jnp.concatenate([total_u8, meta_u8, e_u8])
    else:
        flat = jnp.concatenate([total[None], meta, entries])
    if need_bits:
        return flat, outs[5].reshape(-1, outs[5].shape[-1])
    return flat, None


# --------------------------------------------------------------------------
# results
# --------------------------------------------------------------------------


class _FleetBatch:
    """Shared fetched outputs for one fleet pass (results hold views)."""

    __slots__ = ("names", "entries", "starts", "_bits_dev", "_bits_np")

    def __init__(self, names, entries, starts, bits_dev):
        self.names = names
        self.entries = entries  # int32[total] (site << 8 | count)
        self.starts = starts  # int64[n_pad] entry offsets per position
        self._bits_dev = bits_dev  # device uint32[n_pad, W] or None
        self._bits_np = None

    def feasible_names(self, pos: int) -> tuple:
        if self._bits_np is None:
            # force little-endian word layout before the byte view so the
            # bit positions are host-endianness-independent (the entry
            # stream is decoded with shifts for the same reason)
            self._bits_np = np.ascontiguousarray(
                np.asarray(self._bits_dev).astype("<u4", copy=False)
            )
        row = self._bits_np[pos]
        idx = np.nonzero(
            np.unpackbits(row.view(np.uint8), bitorder="little")
        )[0]
        names = self.names
        return tuple(names[j] for j in idx if j < len(names))


class FleetResult:
    """Lazy ScheduleResult-compatible view over a fleet batch.

    `clusters`/`feasible` materialize on first access: the scheduling data
    already sits in host numpy arrays; building 100k Python dicts eagerly
    would cost more than the whole device pass."""

    __slots__ = (
        "key", "affinity_name", "error",
        "_batch", "_pos", "_n", "_dup_replicas", "_zero",
        "_clusters", "_feasible",
    )

    def __init__(self, key, affinity_name, error, batch, pos, n,
                 dup_replicas, zero):
        self.key = key
        self.affinity_name = affinity_name
        self.error = error
        self._batch = batch
        self._pos = pos
        self._n = n
        self._dup_replicas = dup_replicas  # Duplicated row: count everywhere
        self._zero = zero  # zero-replica (non-workload) row
        self._clusters = None
        self._feasible = None

    @property
    def success(self) -> bool:
        return not self.error

    @property
    def clusters(self) -> dict:
        if self._clusters is None:
            if not self.success:
                self._clusters = {}
            elif self._dup_replicas is not None:
                self._clusters = {
                    n: self._dup_replicas
                    for n in self._batch.feasible_names(self._pos)
                }
            else:
                b = self._batch
                start = int(b.starts[self._pos])
                names = b.names
                self._clusters = {
                    names[int(e) >> 8]: int(e) & 0xFF
                    for e in b.entries[start : start + self._n]
                }
        return self._clusters

    @property
    def feasible(self) -> tuple:
        if self._feasible is None:
            self._feasible = (
                self._batch.feasible_names(self._pos)
                if (self._zero and self.success)
                else ()
            )
        return self._feasible


class _FleetResultList:
    """Column-oriented result container: the scheduling data lives in the
    fetched numpy arrays; per-binding `FleetResult` views materialize on
    access (and are cached for identity stability). Building 100k Python
    objects eagerly would cost more host time than the whole device pass —
    consumers that iterate pay the same total, but batch callers that
    sample (bench verification, partial write-backs) don't pay for rows
    they never touch."""

    __slots__ = (
        "_problems", "_terms", "_batches", "_slice_rows", "_n_placed",
        "_unsched", "_has_cand", "_is_dup", "_cache",
    )

    def __init__(self, problems, terms, batches, slice_rows, n_placed,
                 unsched, has_cand, is_dup):
        self._problems = problems
        self._terms = terms
        self._batches = batches
        self._slice_rows = slice_rows
        self._n_placed = n_placed
        self._unsched = unsched
        self._has_cand = has_cand
        self._is_dup = is_dup
        self._cache: dict[int, FleetResult] = {}

    def __len__(self) -> int:
        return len(self._problems)

    def _make(self, i: int) -> FleetResult:
        res = self._cache.get(i)
        if res is not None:
            return res
        p = self._problems[i]
        if not self._has_cand[i]:
            err = "no clusters fit the placement"
        elif self._unsched[i]:
            err = "clusters available replicas are not enough"
        else:
            err = ""
        dup = (
            p.replicas
            if (self._is_dup[i] and p.replicas > 0 and not err)
            else None
        )
        res = FleetResult(
            p.key, self._terms[i], err,
            self._batches[i // self._slice_rows], i % self._slice_rows,
            int(self._n_placed[i]), dup, p.replicas == 0,
        )
        self._cache[i] = res
        return res

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._make(j) for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self._make(i)

    def __iter__(self):
        for i in range(len(self)):
            yield self._make(i)


# --------------------------------------------------------------------------
# the table
# --------------------------------------------------------------------------

_STATE_FIELDS = (
    "cp_idx", "gvk_idx", "prof_idx", "replicas", "strategy", "fresh",
    "prev_sites", "prev_counts",
)


@jax.jit
def _scatter_rows(state, rows, vals):
    return tuple(a.at[rows].set(v) for a, v in zip(state, vals))


class FleetTable:
    """Device-resident binding table bound to one TensorScheduler."""

    def __init__(self, engine):
        self.engine = engine
        self.chunk = engine.chunk_size
        self.cap = 0
        self.n_rows = 0
        self._key_row: dict[str, int] = {}
        self._problems: list = []
        self._fps: list = []
        self._terms: list = []  # affinity term name per row
        self._row_last_used: list[int] = []  # pass counter per row
        self._pass = 0
        # interning slots
        self._cp_slot: dict[int, int] = {}
        self._cp_pl: list = []  # slot -> (placement, compiled) pinned
        self._gvk_slot: dict[str, int] = {}
        self._gvk_list: list[str] = []
        self._prof_slot: dict[bytes, int] = {}
        self._profiles: list[np.ndarray] = []
        # host staging
        self._st: dict[str, np.ndarray] = {}
        # device
        self._dev_state: Optional[tuple] = None
        self._dev_tables: Optional[tuple] = None
        self._all_rows_dev = None
        self._all_rows_n = -1
        self._dirty: set[int] = set()
        self._tables_dirty = True
        self._avail_max = 0
        self._static_max = 0
        self._snapshot_gen = getattr(engine, "_snapshot_gen", 0)
        # last observed entry total: tunes the fetched buffer well below the
        # worst-case sum(replicas) bound (mean placed clusters per binding is
        # far under max replicas); overflow falls back to the safe bound
        self._last_total = 0
        self._e_cap_cur: Optional[int] = None
        self._shrink_votes = 0
        # per-phase wall times of the last pass (bench breakdown surface)
        self.last_breakdown: dict[str, float] = {}

    # -- rows --------------------------------------------------------------

    COMPACT_IDLE_PASSES = 4  # rows unused this many passes are evictable

    def _compact(self) -> bool:
        """Drop rows whose keys haven't been scheduled recently (deleted
        bindings leave stale rows behind — without eviction a create/delete
        churn workload grows the table and its pinned problems without
        bound). Returns True if at least half the rows were reclaimed."""
        cutoff = self._pass - self.COMPACT_IDLE_PASSES
        keep = [
            row
            for row in range(self.n_rows)
            if self._row_last_used[row] >= cutoff
        ]
        if len(keep) * 2 > self.n_rows:
            return False
        for k in ("_problems", "_fps", "_terms"):
            setattr(self, k, [getattr(self, k)[r] for r in keep])
        self._row_last_used = [self._row_last_used[r] for r in keep]
        idx = np.asarray(keep, np.int64)
        for name, arr in self._st.items():
            arr[: len(keep)] = arr[idx]
        self._key_row = {p.key: i for i, p in enumerate(self._problems)}
        self.n_rows = len(keep)
        self._dirty.clear()
        self._dev_state = None  # full re-upload with the compacted layout
        self._all_rows_n = -1
        return True

    def _grow(self, need: int) -> None:
        new_cap = max(self.chunk, _pow2(need))
        st = {
            "cp_idx": np.zeros(new_cap, np.int32),
            "gvk_idx": np.zeros(new_cap, np.int32),
            "prof_idx": np.zeros(new_cap, np.int32),
            "replicas": np.zeros(new_cap, np.int32),
            "strategy": np.zeros(new_cap, np.int32),
            "fresh": np.zeros(new_cap, bool),
            "prev_sites": np.zeros((new_cap, K_PREV), np.int32),
            "prev_counts": np.zeros((new_cap, K_PREV), np.int32),
        }
        for k, a in self._st.items():
            st[k][: self.cap] = a
        self._st = st
        self.cap = new_cap
        self._dev_state = None  # full re-upload

    @staticmethod
    def _fingerprint(p) -> tuple:
        return (
            id(p.placement), p.replicas, p.gvk, p.fresh,
            tuple(p.requests.items()), tuple(p.prev.items()),
        )

    def upsert(self, problem, compiled) -> int:
        row = self._key_row.get(problem.key)
        if row is not None:
            self._row_last_used[row] = self._pass
            if self._problems[row] is problem:
                return row
            fp = self._fingerprint(problem)
            if fp == self._fps[row]:
                self._problems[row] = problem
                return row
        else:
            if self.n_rows + 1 > self.cap:
                self._grow(self.n_rows + 1)
            row = self.n_rows
            self.n_rows = row + 1
            self._key_row[problem.key] = row
            self._problems.append(problem)
            self._fps.append(None)
            self._terms.append("")
            self._row_last_used.append(self._pass)
        self._pack_row(row, problem, compiled)
        return row

    def _pack_row(self, row: int, problem, compiled) -> None:
        snap = self.engine.snapshot
        st = self._st
        # placement slot
        slot = self._cp_slot.get(id(compiled))
        if slot is None:
            slot = len(self._cp_pl)
            self._cp_slot[id(compiled)] = slot
            self._cp_pl.append((problem.placement, compiled))
            self._static_max = max(
                self._static_max, int(compiled.static_weights.max(initial=0))
            )
            self._tables_dirty = True
        st["cp_idx"][row] = slot
        # gvk slot
        gslot = self._gvk_slot.get(problem.gvk)
        if gslot is None:
            gslot = len(self._gvk_list)
            self._gvk_slot[problem.gvk] = gslot
            self._gvk_list.append(problem.gvk)
            self._tables_dirty = True
        st["gvk_idx"][row] = gslot
        # request profile slot (pods-dim adjustment applied BEFORE interning,
        # mirroring _pack_chunk: each replica occupies a pod)
        vec = np.zeros(len(snap.dims), np.int64)
        for d, q in problem.requests.items():
            j = snap.dim_index(d)
            if j is not None:
                vec[j] = q
        pods = snap.dim_index("pods")
        if pods is not None and problem.replicas > 0:
            vec[pods] = max(vec[pods], 1)
        pkey = vec.tobytes()
        pslot = self._prof_slot.get(pkey)
        if pslot is None:
            pslot = len(self._profiles)
            self._prof_slot[pkey] = pslot
            self._profiles.append(vec)
            self._tables_dirty = True
        st["prof_idx"][row] = pslot
        st["replicas"][row] = problem.replicas
        st["strategy"][row] = compiled.strategy
        st["fresh"][row] = problem.fresh
        sites = np.zeros(K_PREV, np.int32)
        cnts = np.zeros(K_PREV, np.int32)
        k = 0
        for name, reps_prev in problem.prev.items():
            j = snap.index.get(name)
            if j is not None:
                sites[k] = j
                cnts[k] = reps_prev
                k += 1
        st["prev_sites"][row] = sites
        st["prev_counts"][row] = cnts
        self._fps[row] = self._fingerprint(problem)
        self._terms[row] = compiled.terms[0][0]
        self._dirty.add(row)

    @property
    def slots_exhausted(self) -> bool:
        return (
            len(self._cp_pl) > MAX_SLOTS
            or len(self._gvk_list) > MAX_SLOTS
            or len(self._profiles) > MAX_SLOTS
        )

    # -- device sync -------------------------------------------------------

    def _rebuild_tables(self) -> None:
        snap = self.engine.snapshot
        gen = getattr(self.engine, "_snapshot_gen", 0)
        if gen != self._snapshot_gen:
            # snapshot swapped in place (same cluster set): recompile each
            # slot's placement against the new snapshot, order-preserving so
            # row cp_idx values stay valid
            self._snapshot_gen = gen
            self._cp_slot.clear()
            self._static_max = 0
            for i, (pl, _) in enumerate(self._cp_pl):
                cp = self.engine._compiled(pl)
                self._cp_pl[i] = (pl, cp)
                self._cp_slot[id(cp)] = i
                self._static_max = max(
                    self._static_max, int(cp.static_weights.max(initial=0))
                )
        c = snap.num_clusters
        aff = np.stack(
            [
                (cp.terms[0][1] & cp.spread_field_ok).astype(np.int32)
                for _, cp in self._cp_pl
            ]
        )
        taint = np.stack(
            [cp.taint_ok.astype(np.int32) for _, cp in self._cp_pl]
        )
        static = np.stack(
            [cp.static_weights.astype(np.int32) for _, cp in self._cp_pl]
        )
        cp_table = np.concatenate([aff, taint, static], axis=1)  # [U, 3C]
        gvk_rows = []
        for g in self._gvk_list:
            gid = snap.gvk_vocab.get(g) if g else None
            if gid is None:
                mask = (
                    np.zeros(c, bool)
                    if g and len(snap.gvk_vocab) > 0
                    else np.ones(c, bool)
                )
            else:
                word, bit = gid // 32, gid % 32
                mask = (snap.gvk_bits[:, word] >> np.uint32(bit)) & 1 != 0
            gvk_rows.append(mask.astype(np.int32))
        gvk_table = np.stack(gvk_rows)
        prof_table = self.engine._profile_table(np.stack(self._profiles))
        self._avail_max = int(
            jnp.max(
                jnp.where(
                    (prof_table == MAX_INT32) | (prof_table == -1),
                    0,
                    prof_table,
                )
            )
        )
        self._dev_tables = (
            jnp.asarray(cp_table),
            jnp.asarray(gvk_table),
            prof_table,
            jnp.asarray(~snap.complete_enablements),
        )
        self._tables_dirty = False

    def _sync_device(self) -> None:
        if self._tables_dirty or (
            getattr(self.engine, "_snapshot_gen", 0) != self._snapshot_gen
        ):
            self._rebuild_tables()
        if self._dev_state is None:
            self._dev_state = tuple(
                jnp.asarray(self._st[k]) for k in _STATE_FIELDS
            )
            self._dirty.clear()
        elif self._dirty:
            rows = np.fromiter(self._dirty, np.int64, len(self._dirty))
            if len(rows) > self.cap // 2:
                self._dev_state = tuple(
                    jnp.asarray(self._st[k]) for k in _STATE_FIELDS
                )
            else:
                vals = tuple(self._st[k][rows] for k in _STATE_FIELDS)
                self._dev_state = _scatter_rows(
                    self._dev_state, jnp.asarray(rows), vals
                )
            self._dirty.clear()

    # -- scheduling --------------------------------------------------------

    def schedule(self, problems: Sequence, compiled: Sequence) -> list:
        import time as _time

        tmr: dict[str, float] = {}
        t0 = _time.perf_counter()
        self._pass += 1
        # reclaim rows of deleted/idle bindings before the table would grow
        # (compaction reindexes rows, so it must run before any upsert of
        # this pass hands out indices). Gated on ACTUAL new keys so the
        # steady all-rows storm pays one dict sweep at capacity pressure,
        # not an O(n_rows) compaction scan per pass.
        if self.n_rows + len(problems) > self.cap:
            new_keys = sum(1 for p in problems if p.key not in self._key_row)
            if self.n_rows + new_keys > self.cap:
                self._compact()
        rows_np = np.fromiter(
            (self.upsert(p, cp) for p, cp in zip(problems, compiled)),
            np.int32,
            len(problems),
        )
        tmr["upsert"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        self._sync_device()
        tmr["sync"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        n = len(rows_np)
        # adaptive chunk: a straggler batch of a few hundred rows should
        # not execute a full 4096-row chunk (pow2 snapping keeps the trace
        # count logarithmic)
        eff_chunk = min(self.chunk, _pow2(max(n, 256)))
        n_pad = max(eff_chunk, -(-n // eff_chunk) * eff_chunk)
        n_chunks = n_pad // eff_chunk
        # pipeline: large passes run as two equal slices — the host fetches
        # slice 0's buffer over the tunnel while the device executes slice 1
        # (transfer and compute are the two halves of the pass wall time)
        n_slices = 2 if n_chunks % 2 == 0 and n >= 4 * eff_chunk else 1
        if n_slices == 2:
            n_chunks //= 2
        st = self._st
        # all-rows storm mode: the row-index upload is cached on device
        is_all = n == self.n_rows and np.array_equal(
            rows_np, np.arange(n, dtype=np.int32)
        )
        if is_all:
            if self._all_rows_n != n or self._all_rows_dev is None or (
                self._all_rows_dev.shape[0] != n_pad
            ):
                ar = np.full(n_pad, -1, np.int32)
                ar[:n] = np.arange(n, dtype=np.int32)
                self._all_rows_dev = jnp.asarray(ar)
                self._all_rows_n = n
            rows_dev = self._all_rows_dev
        else:
            ar = np.full(n_pad, -1, np.int32)
            ar[:n] = rows_np
            rows_dev = jnp.asarray(ar)

        reps_sel = st["replicas"][rows_np]
        strat_sel = st["strategy"][rows_np]
        max_n = int(reps_sel.max(initial=0))
        max_prev = int(st["prev_counts"][rows_np].max(initial=0))
        has_agg = bool((strat_sel == AGGREGATED).any())
        c = self.engine.snapshot.num_clusters
        from .core import kernel_variant

        wide, fast = kernel_variant(
            max(self._avail_max, max_n), self._static_max, max_prev, max_n, c
        )
        k_out = min(max(1, c), _pow2(max(max_n, 1)))
        is_dup = strat_sel == S_DUPLICATED
        need_bits = bool(is_dup.any() or (reps_sel == 0).any())
        safe = int(
            np.minimum(np.where(is_dup, 0, reps_sel), k_out).sum()
        )

        def cap_round(v: int) -> int:
            v = max(v, 1)
            return (
                -(-v // E_ROUND) * E_ROUND if v > E_ROUND else _pow2(max(v, 1024))
            )

        # fetched bytes scale with e_cap, so tune it to ~1.25x the last
        # observed total; the safe bound can never overflow and is the
        # first-pass / fallback trace. Hysteresis: grow immediately, shrink
        # only after two consecutive lower demands — every distinct e_cap is
        # a fresh XLA trace, and a demand oscillating across a quantum
        # boundary was recompiling the solve once per storm wave
        # _last_total tracks the max per-slice entry total
        needed = cap_round(safe)
        if 0 < self._last_total and self._last_total * 5 // 4 < safe:
            needed = min(needed, cap_round(self._last_total * 5 // 4))
        prev_cap = self._e_cap_cur
        if prev_cap is None or needed >= prev_cap:
            e_cap = needed
            self._shrink_votes = 0
        else:
            self._shrink_votes += 1
            e_cap = needed if self._shrink_votes >= 2 else prev_cap
            if e_cap == needed:
                self._shrink_votes = 0
        self._e_cap_cur = e_cap

        def solve(rows_slice, cap):
            return _fleet_solve(
                *self._dev_tables,
                rows_slice,
                *self._dev_state,
                chunk=eff_chunk,
                n_chunks=n_chunks,
                k_out=k_out,
                e_cap=cap,
                wide=wide,
                fast=fast,
                has_aggregated=has_agg,
                need_bits=need_bits,
            )

        slice_rows = n_pad // n_slices
        slices = [
            rows_dev[s * slice_rows : (s + 1) * slice_rows]
            for s in range(n_slices)
        ]
        # dispatch every slice before fetching any: the device computes
        # slice s+1 while the host drains slice s's buffer
        byte_wire = c <= 0xFFFF

        def decode(arr):
            """(total, meta int32[slice_rows], entries int32[*])"""
            if byte_wire:
                a = arr.astype(np.int32)
                total = int(a[0] | (a[1] << 8) | (a[2] << 16) | (a[3] << 24))
                m = a[4 : 4 + 2 * slice_rows]
                meta = m[0::2] | (m[1::2] << 8)
                e = a[4 + 2 * slice_rows :]
                entries = e[0::3] | (e[1::3] << 8) | (e[2::3] << 16)
                return total, meta, entries
            return int(arr[0]), arr[1 : 1 + slice_rows], arr[1 + slice_rows :]

        tmr["prep"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        pending = [solve(rs, e_cap) for rs in slices]
        tmr["dispatch"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        metas, entry_bufs, bit_bufs, totals = [], [], [], []
        fetched_bytes = 0
        for s, (flat, bits) in enumerate(pending):
            raw = np.asarray(flat)
            fetched_bytes += raw.nbytes
            total, m, e = decode(raw)
            if total > e_cap:  # overflow: rerun this slice at the safe bound
                flat, bits = solve(slices[s], cap_round(safe))
                raw = np.asarray(flat)
                fetched_bytes += raw.nbytes
                total, m, e = decode(raw)
            assert total <= len(e), (total, e_cap)
            totals.append(total)
            metas.append(m)
            entry_bufs.append(e)
            bit_bufs.append(bits)
        tmr["fetch"] = _time.perf_counter() - t0
        tmr["fetch_mb"] = fetched_bytes / 1e6
        t0 = _time.perf_counter()
        self._last_total = max(totals)
        meta = np.concatenate(metas) if n_slices > 1 else metas[0]
        n_placed = (meta & 0xFF).astype(np.int64)
        unsched = (meta >> 8) & 1
        has_cand = (meta >> 9) & 1
        # per-slice entry offsets (each slice's stream starts at 0)
        starts = np.zeros(n_pad, np.int64)
        for s in range(n_slices):
            seg = n_placed[s * slice_rows : (s + 1) * slice_rows]
            np.cumsum(seg[:-1], out=starts[s * slice_rows + 1 : (s + 1) * slice_rows])

        names = self.engine.snapshot.names
        batches = [
            _FleetBatch(names, entry_bufs[s], starts[s * slice_rows :], bit_bufs[s])
            for s in range(n_slices)
        ]
        terms = [self._terms[r] for r in rows_np]
        tmr["post"] = _time.perf_counter() - t0
        self.last_breakdown = tmr
        return _FleetResultList(
            problems, terms, batches, slice_rows, n_placed, unsched,
            has_cand, is_dup,
        )
