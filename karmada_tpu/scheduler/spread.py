"""Spread-constraint selection: narrow feasible clusters before assignment.

Ref: pkg/scheduler/core/spreadconstraint/. The reference groups scored
clusters by topology and runs a DFS over group combinations; this build keeps
the batched tensor path for the dominant cases and a bounded host search for
ragged group combinatorics (SURVEY.md section 7 "hard parts").

Implemented here:
- ignore rules (select_clusters.go:63-86): static-weighted division ignores
  constraints entirely; Duplicated ignores available resource.
- cluster-level constraint (select_clusters_by_cluster.go:26-99): order by
  (score desc, credited availability desc, name asc), take maxGroups, then
  swap-repair from the remainder until cumulative availability covers the
  needed replicas.
- region-level DFS group selection lives in karmada_tpu.scheduler.groups
  (wired in by select_clusters_batch once constraints name region/provider/
  zone fields).

Scores: the in-tree score plugins sum to the locality score — 100 when the
cluster already holds the resource (cluster_locality.go:43-56), 0 otherwise.
Availability is credited with already-assigned replicas
(group_clusters.go:344-347).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

import numpy as np

from ..api.policy import DIVIDED, WEIGHTED, Placement, SpreadConstraint
from .snapshot import ClusterSnapshot, CompiledPlacement

if TYPE_CHECKING:
    from .core import BindingProblem

LOCALITY_SCORE = 100
INVALID_REPLICAS = -1


def should_ignore_spread_constraint(pl: Placement) -> bool:
    """select_clusters.go:63-78: static-weighted division ignores spread."""
    rs = pl.replica_scheduling
    if (
        rs is not None
        and rs.replica_scheduling_type == DIVIDED
        and rs.replica_division_preference == WEIGHTED
        and (
            rs.weight_preference is None
            or (
                len(rs.weight_preference.static_weight_list) != 0
                and not rs.weight_preference.dynamic_weight
            )
        )
    ):
        return True
    return False


def should_ignore_available_resource(pl: Placement) -> bool:
    """select_clusters.go:80-86: Duplicated ignores availability."""
    rs = pl.replica_scheduling
    return rs is None or rs.replica_scheduling_type != DIVIDED


def cluster_order(
    score: np.ndarray, avail_credited: np.ndarray, feasible: np.ndarray
) -> np.ndarray:
    """Indices of feasible clusters in (score desc, avail desc, idx asc)
    order (spreadconstraint/util.go:43-57 with the name tiebreak replaced by
    the snapshot index, which is name-stable for a sorted snapshot)."""
    c = score.shape[0]
    idx = np.arange(c)
    order = np.lexsort((idx, -avail_credited, -score))
    return order[feasible[order]]


def select_by_cluster_constraint(
    sc: SpreadConstraint,
    order: np.ndarray,
    avail_credited: np.ndarray,
    need_replicas: int,
) -> np.ndarray | None:
    """select_clusters_by_cluster.go:26-99. Returns selected cluster indices
    or None (FitError)."""
    total = order.size
    min_groups = max(sc.min_groups, 1)
    if total < min_groups:
        return None
    max_groups = sc.max_groups if sc.max_groups and sc.max_groups > 0 else total
    need_cnt = min(max_groups, total)

    ret = list(order[:need_cnt])
    rest = list(order[need_cnt:])
    if need_replicas == INVALID_REPLICAS:
        return np.asarray(ret, np.int64)

    def total_avail(sel: list) -> int:
        return int(sum(int(avail_credited[j]) for j in sel))

    # swap-repair: replace lowest-score members with the highest-availability
    # leftovers until the capacity covers need_replicas
    update = len(ret) - 1
    while total_avail(ret) < need_replicas and update >= 0:
        if rest:
            best = max(range(len(rest)), key=lambda k: int(avail_credited[rest[k]]))
            if int(avail_credited[rest[best]]) > int(avail_credited[ret[update]]):
                ret[update], rest[best] = rest[best], ret[update]
                update -= 1
                continue
        update -= 1
    if total_avail(ret) < need_replicas:
        return None
    return np.asarray(ret, np.int64)


def select_clusters_batch(
    snap: ClusterSnapshot,
    problems: Sequence["BindingProblem"],
    compiled: Sequence[CompiledPlacement],
    term_round: int,
    feasible: np.ndarray,  # bool[B, C]
    avail,  # int32[B, C] estimator availability (numpy OR device array —
    # only pulled to host when a row actually carries spread constraints)
    prev: np.ndarray,  # int32[B, C]
) -> np.ndarray:
    """SelectClusters stage over a chunk. Returns candidates bool[B, C]."""
    out = feasible.copy()
    rows_with_constraints = [
        i
        for i, cp in enumerate(compiled)
        if cp.spread_constraints
        and cp.placement is not None
        and not should_ignore_spread_constraint(cp.placement)
    ]
    if not rows_with_constraints:
        return out

    avail = np.asarray(avail)
    score = np.where(prev > 0, LOCALITY_SCORE, 0)
    credited = avail.astype(np.int64) + prev.astype(np.int64)

    from .groups import select_by_topology_groups  # host group search

    # the host group search is pure in (placement, need, replicas, and the
    # row's score/credited/feasible vectors); fleets schedule many bindings
    # that share all of those (same policy, same requests), so memoizing by
    # row content collapses the per-binding DFS to one per distinct input —
    # the "batch the binding axis" plan applied to the host stage
    memo: dict = {}
    for i in rows_with_constraints:
        cp = compiled[i]
        pl = cp.placement
        assert pl is not None
        need = (
            INVALID_REPLICAS
            if should_ignore_available_resource(pl)
            else problems[i].replicas
        )
        key = (
            id(cp), need, problems[i].replicas,
            score[i].tobytes(), credited[i].tobytes(), feasible[i].tobytes(),
        )
        row = memo.get(key)
        if row is None:
            by_field = {sc.spread_by_field: sc for sc in cp.spread_constraints}
            order = cluster_order(score[i], credited[i], feasible[i])
            if "region" in by_field or "provider" in by_field or "zone" in by_field:
                sel = select_by_topology_groups(
                    snap, by_field, order, score[i], credited[i], need,
                    duplicated=need == INVALID_REPLICAS,
                    replicas=problems[i].replicas,
                )
            elif "cluster" in by_field:
                sel = select_by_cluster_constraint(
                    by_field["cluster"], order, credited[i], need
                )
            else:
                # spreadByLabel-only constraints: the reference refuses
                # ("just support cluster and region spread constraint",
                # select_clusters.go:58) -> FitError, not silent pass-through
                sel = None
            row = np.zeros(snap.num_clusters, bool)
            if sel is not None and sel.size > 0:
                row[sel] = True
            memo[key] = row
        out[i] = row
    return out
