"""QuotaSnapshot: FederatedResourceQuota packed beside the cluster snapshot.

Ref: federatedresourcequota_types.go + the scheduling-side enforcement the
reference gates behind FederatedQuotaEnforcement. Where the cluster
snapshot packs member state into the filter/estimate tensors, this packs
the control plane's FRQ objects into the ADMISSION tensors the quota
kernels (ops.quota) consume:

- ``ns_index``/``remaining``: namespace -> row, and per-namespace
  ``limit - used`` over the engine snapshot's resource dims (int64,
  ``UNLIMITED`` where the namespace's quotas don't track a dim). Multiple
  FRQs in one namespace compose by elementwise min of remaining — every
  quota must admit.
- ``cap_index``/``cluster_caps``: namespaces with static_assignments get
  an ``[N, C, R]`` hard-cap tensor over the snapshot's cluster columns
  (UNLIMITED where a cluster/dim carries no slice) — folded into the
  divide kernel's availability as one more estimator answer.

Generation-stamped by the OWNER (the scheduler controller bumps on FRQ
watch events), so the engine's batch-identity replay can prove a wave's
admission inputs unchanged, and a denied binding retries on the next
quota generation instead of every pass. ``cap_token`` digests the
static-assignment layout alone: the fleet table bakes cap rows into its
interned profile slots, so the engine drops the table only when the CAP
content changes — a quota raise (remaining moved, caps unchanged) never
forces a re-pack.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

from ..ops.quota import DEMAND_CLAMP, UNLIMITED

#: ScheduleResult.error for a quota-denied binding; the scheduler
#: controller maps it to the Scheduled=False ``QuotaExceeded`` condition.
#: The reason code comes from THE taxonomy (utils.reasons.REASONS —
#: ISSUE 13 unification): it doubles as exclusion-mask stage bit 5, and
#: graftlint GL010 keeps every emission site on registered codes.
from ..utils.reasons import REASONS as _REASONS

QUOTA_EXCEEDED_REASON = _REASONS["QuotaExceeded"].code
QUOTA_EXCEEDED_ERROR = "namespace quota exceeded"


class QuotaSnapshot:
    """Packed view of every FederatedResourceQuota.

    ``remaining`` is WORKING state within one generation: the engine
    debits each wave's admitted demand from it so a drain spanning
    multiple engine passes (batch splits, follow-on waves before the
    usage controller recomputes) cannot re-admit the same budget; the
    next generation rebuilds it from recomputed usage, so debit and
    accounting never double-count. Everything else is immutable."""

    def __init__(
        self,
        dims: Sequence[str],
        ns_index: dict[str, int],
        remaining: np.ndarray,  # int64[N, R]
        cap_index: dict[str, int],
        cluster_caps: np.ndarray,  # int64[Ncap, C, R]
        generation: int,
        cap_token: int,
    ):
        self.dims = list(dims)
        self.ns_index = ns_index
        self.remaining = remaining
        self.cap_index = cap_index
        self.cluster_caps = cluster_caps
        self.generation = generation
        self.cap_token = cap_token

    @property
    def active(self) -> bool:
        return bool(self.ns_index)

    @property
    def has_caps(self) -> bool:
        return bool(self.cap_index)

    def demand_row(self, requests: dict, replicas_delta: int) -> np.ndarray:
        """int64[R] wave demand for one binding: per-replica requests over
        the snapshot dims (each replica occupies one pod, mirroring the
        estimator's implicit pods request) scaled by the replica delta and
        clamped so a whole wave's cumsum stays in int64. The scale runs in
        PYTHON ints (R is tiny): an int64 multiply of an absurd-but-legal
        request by a huge delta would wrap to zero/negative BEFORE a
        post-hoc clamp could bound it — silently bypassing admission and
        inflating remaining on debit."""
        vec = per_replica_vector(requests, self.dims)
        delta = max(int(replicas_delta), 0)
        return np.fromiter(
            (min(int(v) * delta, DEMAND_CLAMP) for v in vec),
            np.int64,
            len(vec),
        )


def per_replica_vector(requests: dict, dims: Sequence[str]) -> np.ndarray:
    """int64[R] per-replica request over ``dims`` with the implicit
    one-pod-per-replica floor (the same projection _pack_chunk and the
    usage controller apply, so demand, usage, and estimates agree)."""
    vec = np.zeros(len(dims), np.int64)
    for j, d in enumerate(dims):
        q = requests.get(d, 0)
        if q:
            vec[j] = q
    if "pods" in dims:
        pods = dims.index("pods")
        vec[pods] = max(vec[pods], 1)
    return vec


def usage_from_bindings(store, namespaces) -> dict:
    """namespace -> {resource: used} from bound ResourceBindings:
    ``assigned replicas x per-replica request`` per resource, each
    replica occupying one pod (the same projection demand_row applies,
    so demand and usage can never disagree). THE single source of the
    usage formula — the FRQ status controller delegates here, and the
    snapshot builder falls back to it for FRQs whose status has not been
    reconciled yet."""
    usage: dict[str, dict[str, int]] = {ns: {} for ns in namespaces}
    for rb in store.list("ResourceBinding"):
        acc = usage.get(rb.meta.namespace)
        if acc is None:
            continue
        assigned = sum(int(tc.replicas or 0) for tc in rb.spec.clusters)
        if assigned <= 0:
            continue
        req = (
            rb.spec.replica_requirements.resource_request
            if rb.spec.replica_requirements
            else {}
        )
        for res, qty in req.items():
            if qty:
                acc[res] = acc.get(res, 0) + assigned * int(qty)
        if not req.get("pods"):
            acc["pods"] = acc.get("pods", 0) + assigned
    return usage


def build_quota_snapshot(
    frqs: Sequence,
    snapshot,
    generation: int,
    store=None,
) -> Optional["QuotaSnapshot"]:
    """Pack FRQ objects against one ClusterSnapshot (dims + cluster
    columns). Returns None when no FRQ exists — the engine's quota hook
    is one ``is None`` check then.

    ``store``, when given, closes the status-lag window: an FRQ whose
    status has not been reconciled against its current spec
    (``status.overall != spec.overall`` — a fresh create, or a spec edit
    the status controller hasn't caught up with) has its namespace's
    usage recomputed LIVE from bound bindings instead of trusting the
    stale/empty ``status.overall_used`` — otherwise the first wave after
    creating an FRQ over a namespace with existing usage would admit a
    full extra budget that nothing ever revokes."""
    frqs = [q for q in frqs if q.meta.namespace]
    if not frqs:
        return None
    dims = list(snapshot.dims)
    r = len(dims)
    dim_index = {d: j for j, d in enumerate(dims)}
    by_ns: dict[str, list] = {}
    for q in frqs:
        by_ns.setdefault(q.meta.namespace, []).append(q)
    namespaces = sorted(by_ns)
    live_usage: dict = {}
    if store is not None:
        stale_ns = {
            q.meta.namespace
            for q in frqs
            if q.status.overall != q.spec.overall
        }
        if stale_ns:
            live_usage = usage_from_bindings(store, stale_ns)
    ns_index = {ns: i for i, ns in enumerate(namespaces)}
    remaining = np.full((len(namespaces), r), UNLIMITED, np.int64)
    cap_ns: list[str] = []
    cap_rows: list[np.ndarray] = []
    c = snapshot.num_clusters
    token = hashlib.blake2b(digest_size=16)
    for ns in namespaces:
        caps: Optional[np.ndarray] = None
        for q in by_ns[ns]:
            # every quota in the namespace must admit: compose remaining
            # by elementwise min over FRQs. Unreconciled FRQs read live
            # usage (see docstring) instead of their lagging status.
            if q.status.overall != q.spec.overall and ns in live_usage:
                used = live_usage[ns]
            else:
                used = q.status.overall_used or {}
            for res, limit in q.spec.overall.items():
                j = dim_index.get(res)
                if j is None:
                    continue  # resource outside the scheduling dims
                rem = max(int(limit) - int(used.get(res, 0)), 0)
                remaining[ns_index[ns], j] = min(
                    remaining[ns_index[ns], j], rem
                )
            for assignment in q.spec.static_assignments:
                col = snapshot.index.get(assignment.cluster_name)
                if col is None:
                    continue
                if caps is None:
                    caps = np.full((c, r), UNLIMITED, np.int64)
                for res, hard in assignment.hard.items():
                    j = dim_index.get(res)
                    if j is None:
                        continue
                    caps[col, j] = min(caps[col, j], int(hard))
                    token.update(
                        f"{ns}\x00{assignment.cluster_name}\x00{res}"
                        f"\x00{int(hard)}".encode()
                    )
        if caps is not None:
            cap_ns.append(ns)
            cap_rows.append(caps)
    cap_index = {ns: i for i, ns in enumerate(cap_ns)}
    cluster_caps = (
        np.stack(cap_rows)
        if cap_rows
        else np.zeros((0, c, r), np.int64)
    )
    # the cap token also pins the cluster-column universe: caps are packed
    # against snapshot.index, so a changed cluster set changes the rows
    token.update("\x00".join(snapshot.names).encode())
    return QuotaSnapshot(
        dims=dims,
        ns_index=ns_index,
        remaining=remaining,
        cap_index=cap_index,
        cluster_caps=cluster_caps,
        generation=generation,
        cap_token=int.from_bytes(token.digest(), "little"),
    )
