"""Cluster snapshot packing: API objects -> tensor-ready arrays.

The analogue of the scheduler cache snapshot (ref: pkg/scheduler/cache/
cache.go:42-62) fused with selector pre-compilation. Where the reference
deep-copies Cluster objects per scheduling attempt and re-runs string
matching per (binding, cluster, plugin), this build interns every string
universe once per snapshot (labels, taints, GVKs, topology) and compiles each
Placement into boolean masks over the cluster axis — the filter plugins of
framework/plugins/* become a handful of bitset ANDs.

Mask semantics per plugin:
- ClusterAffinity (cluster_affinity.go:46-77): per-term mask via
  util.ClusterMatches semantics (exclude > names/labels/fields).
- TaintToleration (taint_toleration.go:46-74): untolerated NoSchedule/
  NoExecute taints; per-binding leniency for already-placed clusters is
  composed downstream in the engine.
- APIEnablement (api_enablement.go:46-73): GVK bit present; leniency for
  already-placed clusters when enablements are incomplete composed downstream.
- SpreadConstraint filter (spread_constraint.go:44-60): topology field must
  be non-empty when a constraint spreads by it.
- ClusterEviction (cluster_eviction.go:46-53): per-binding, composed
  downstream from graceful-eviction tasks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..api.cluster import NO_EXECUTE, NO_SCHEDULE, Cluster, Toleration
from ..api.policy import (
    DUPLICATED,
    DIVIDED,
    AGGREGATED as PREF_AGGREGATED,
    WEIGHTED,
    ClusterAffinity,
    Placement,
    SpreadConstraint,
)
from ..ops import masks as mops
from ..ops.divide import AGGREGATED, DUPLICATED as S_DUPLICATED, DYNAMIC_WEIGHT, STATIC_WEIGHT

# canonical resource dimension order; extras appended at build time
DEFAULT_DIMS = ("cpu", "memory", "pods", "ephemeral-storage")


def strategy_code(placement: Optional[Placement]) -> int:
    """Map a Placement to the kernel strategy code
    (ref: newAssignState, assignment.go:89-107)."""
    if placement is None or placement.replica_scheduling_type() == DUPLICATED:
        return S_DUPLICATED
    rs = placement.replica_scheduling
    assert rs is not None
    if rs.replica_division_preference == PREF_AGGREGATED:
        return AGGREGATED
    # Weighted (or unset preference defaults to weighted static behavior)
    if rs.weight_preference is not None and rs.weight_preference.dynamic_weight:
        return DYNAMIC_WEIGHT
    return STATIC_WEIGHT


class ClusterSnapshot:
    """Immutable packed view of all member clusters."""

    def __init__(self, clusters: Sequence[Cluster], dims: Sequence[str] = ()):
        self.clusters = list(clusters)
        self.names = [c.name for c in self.clusters]
        self.index = {n: i for i, n in enumerate(self.names)}
        c = len(self.clusters)

        extra = [
            d
            for cl in self.clusters
            for d in cl.status.resource_summary.allocatable
            if d not in DEFAULT_DIMS
        ]
        self.dims: list[str] = list(DEFAULT_DIMS) + sorted(set(extra) | set(dims) - set(DEFAULT_DIMS))
        r = len(self.dims)

        # --- label / key vocab + bits ---
        self.label_vocab = mops.Vocab()
        self.key_vocab = mops.Vocab()
        pair_rows, key_rows = [], []
        for cl in self.clusters:
            p, k = mops.intern_labels(self.label_vocab, self.key_vocab, cl.meta.labels)
            pair_rows.append(p)
            key_rows.append(k)
        self.label_bits = mops.pack_bits(pair_rows, self.label_vocab.words)
        self.key_bits = mops.pack_bits(key_rows, self.key_vocab.words)

        # --- taints (only effects the scheduler filters on) ---
        self.taint_vocab = mops.Vocab()
        taint_rows = []
        self.taints = []  # vocab id -> Taint
        for cl in self.clusters:
            row = []
            for t in cl.spec.taints:
                if t.effect not in (NO_SCHEDULE, NO_EXECUTE):
                    continue
                tid = self.taint_vocab.intern(f"{t.key}={t.value}:{t.effect}")
                if tid == len(self.taints):
                    self.taints.append(t)
                row.append(tid)
            taint_rows.append(row)
        self.taint_bits = mops.pack_bits(taint_rows, self.taint_vocab.words)

        # --- API enablement ---
        self.gvk_vocab = mops.Vocab()
        gvk_rows = [
            [self.gvk_vocab.intern(g) for g in cl.status.api_enablements]
            for cl in self.clusters
        ]
        self.gvk_bits = mops.pack_bits(gvk_rows, self.gvk_vocab.words)
        self.complete_enablements = np.array(
            [
                any(
                    cond.type == "CompleteAPIEnablements" and cond.status
                    for cond in cl.status.conditions
                )
                for cl in self.clusters
            ],
            bool,
        )

        # --- topology ids (0 = missing field) ---
        self.provider_vocab = mops.Vocab()
        self.region_vocab = mops.Vocab()
        self.zone_vocab = mops.Vocab()
        for v in (self.provider_vocab, self.region_vocab, self.zone_vocab):
            v.intern("")  # id 0 reserved for "missing"
        self.provider_ids = np.array(
            [self.provider_vocab.intern(cl.spec.provider) for cl in self.clusters],
            np.int32,
        )
        self.region_ids = np.array(
            [self.region_vocab.intern(cl.spec.region) for cl in self.clusters], np.int32
        )
        self.zone_ids = np.array(
            [self.zone_vocab.intern(cl.spec.zone) for cl in self.clusters], np.int32
        )

        # --- capacity (general-estimator inputs) ---
        self.available_cap = np.zeros((c, r), np.int64)
        self.has_summary = np.zeros((c,), bool)
        for i, cl in enumerate(self.clusters):
            rs_ = cl.status.resource_summary
            self.has_summary[i] = bool(rs_.allocatable)
            for j, d in enumerate(self.dims):
                self.available_cap[i, j] = (
                    rs_.allocatable.get(d, 0)
                    - rs_.allocated.get(d, 0)
                    - rs_.allocating.get(d, 0)
                )

        # --- resource-model grades (CustomizedClusterResourceModeling) ---
        from ..models import pack_models

        self.model_pack = pack_models(self.clusters, self.dims)

    @property
    def num_clusters(self) -> int:
        return len(self.clusters)

    @property
    def mask_token(self) -> int:
        """Digest of every field the FILTER masks are a function of (names,
        labels, taints, API enablements, topology ids) — capacities and
        resource models excluded. Snapshots with equal tokens compile every
        placement to identical masks, so mask tables built against one are
        valid against the other: the fleet table uses this to skip the
        ~hundreds-of-MB mask-table re-upload on availability-only swaps
        (update_snapshot churn), which costs seconds over a tunneled
        device link."""
        tok = getattr(self, "_mask_token", None)
        if tok is None:
            import hashlib

            h = hashlib.blake2b(digest_size=16)
            h.update("\x00".join(self.names).encode())
            # every bitset/id array AND its vocab string table: equal bit
            # patterns under a renamed vocabulary (env=prod -> env=blue
            # interned at the same id) are DIFFERENT mask inputs
            h.update(self.label_bits.tobytes())
            h.update("\x00".join(self.label_vocab._ids).encode())
            h.update(self.key_bits.tobytes())
            h.update("\x00".join(self.key_vocab._ids).encode())
            h.update(self.taint_bits.tobytes())
            h.update("\x00".join(self.taint_vocab._ids).encode())
            h.update(self.gvk_bits.tobytes())
            h.update("\x00".join(self.gvk_vocab._ids).encode())
            h.update(self.complete_enablements.tobytes())
            h.update(self.provider_ids.tobytes())
            h.update("\x00".join(self.provider_vocab._ids).encode())
            h.update(self.region_ids.tobytes())
            h.update("\x00".join(self.region_vocab._ids).encode())
            h.update(self.zone_ids.tobytes())
            h.update("\x00".join(self.zone_vocab._ids).encode())
            tok = int.from_bytes(h.digest(), "little")
            self._mask_token = tok
        return tok

    def dim_index(self, name: str) -> Optional[int]:
        try:
            return self.dims.index(name)
        except ValueError:
            return None


def compile_affinity(aff: Optional[ClusterAffinity], snap: ClusterSnapshot) -> np.ndarray:
    """Evaluate a ClusterAffinity into bool[C] (util.ClusterMatches)."""
    c = snap.num_clusters
    m = np.ones((c,), bool)
    if aff is None:
        return m
    if aff.exclude:
        excl = {snap.index[n] for n in aff.exclude if n in snap.index}
        if excl:
            m[list(excl)] = False
    if aff.cluster_names:
        allow = np.zeros((c,), bool)
        idxs = [snap.index[n] for n in aff.cluster_names if n in snap.index]
        if idxs:
            allow[idxs] = True
        m &= allow
    if aff.label_selector is not None:
        sel = aff.label_selector
        require_pairs, require_keys, forbid_pairs, forbid_keys = [], [], [], []
        or_groups: list[list[int]] = []
        for k, v in sel.match_labels.items():
            pid = snap.label_vocab.get(mops.label_pair(k, v))
            if pid is None:
                return np.zeros((c,), bool)  # pair no cluster has
            require_pairs.append(pid)
        for req in sel.match_expressions:
            if req.operator == "In":
                ids = [
                    pid
                    for v in req.values
                    if (pid := snap.label_vocab.get(mops.label_pair(req.key, v)))
                    is not None
                ]
                if not ids:
                    return np.zeros((c,), bool)
                or_groups.append(ids)
            elif req.operator == "NotIn":
                # a key holds one value, so forbidding the listed pairs is
                # exactly NotIn (absent key passes)
                forbid_pairs.extend(
                    pid
                    for v in req.values
                    if (pid := snap.label_vocab.get(mops.label_pair(req.key, v)))
                    is not None
                )
            elif req.operator == "Exists":
                kid = snap.key_vocab.get(req.key)
                if kid is None:
                    return np.zeros((c,), bool)
                require_keys.append(kid)
            elif req.operator == "DoesNotExist":
                kid = snap.key_vocab.get(req.key)
                if kid is not None:
                    forbid_keys.append(kid)
            else:
                raise ValueError(f"unknown selector operator {req.operator}")
        lw, kw = snap.label_vocab.words, snap.key_vocab.words
        if require_pairs:
            m &= mops.contains_all(snap.label_bits, mops.bits_from_ids(require_pairs, lw))
        if require_keys:
            m &= mops.contains_all(snap.key_bits, mops.bits_from_ids(require_keys, kw))
        if forbid_pairs:
            m &= ~mops.intersects(snap.label_bits, mops.bits_from_ids(forbid_pairs, lw))
        if forbid_keys:
            m &= ~mops.intersects(snap.key_bits, mops.bits_from_ids(forbid_keys, kw))
        for ids in or_groups:
            m &= mops.intersects(snap.label_bits, mops.bits_from_ids(ids, lw))
    if aff.field_selector is not None:
        fields = {
            "provider": (snap.provider_ids, snap.provider_vocab),
            "region": (snap.region_ids, snap.region_vocab),
            "zone": (snap.zone_ids, snap.zone_vocab),
        }
        for req in aff.field_selector.match_expressions:
            ids_arr, vocab = fields[req.key]
            wanted = {vocab.get(v) for v in req.values} - {None}
            hit = np.isin(ids_arr, list(wanted)) if wanted else np.zeros((c,), bool)
            if req.operator == "In":
                m &= hit
            elif req.operator == "NotIn":
                m &= ~hit
            else:
                raise ValueError(f"unsupported field operator {req.operator}")
    return m


def _tolerated_bits(tolerations: Sequence[Toleration], snap: ClusterSnapshot) -> np.ndarray:
    ids = [
        tid
        for tid, taint in enumerate(snap.taints)
        if any(tol.tolerates(taint) for tol in tolerations)
    ]
    return mops.bits_from_ids(ids, snap.taint_vocab.words)


@dataclass
class CompiledPlacement:
    """A Placement evaluated against one snapshot."""

    placement: Optional[Placement]
    # ordered affinity groups: (name, mask[C]); a single unnamed group when
    # cluster_affinities is unset (scheduler.go:533-596)
    terms: list[tuple[str, np.ndarray]] = field(default_factory=list)
    taint_ok: np.ndarray = field(default_factory=lambda: np.zeros(0, bool))
    spread_field_ok: np.ndarray = field(default_factory=lambda: np.zeros(0, bool))
    strategy: int = S_DUPLICATED
    static_weights: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    spread_constraints: list[SpreadConstraint] = field(default_factory=list)
    # single-affinity-term + no effective spread constraints: the
    # placement-level half of the fleet fast-path gate, precomputed by
    # TensorScheduler._compiled (the per-problem check is a hot loop)
    fleet_single_term: bool = False


def compile_placement(placement: Optional[Placement], snap: ClusterSnapshot) -> CompiledPlacement:
    c = snap.num_clusters
    out = CompiledPlacement(placement=placement)
    pl = placement or Placement()

    if pl.cluster_affinities:
        out.terms = [
            (t.affinity_name, compile_affinity(t, snap)) for t in pl.cluster_affinities
        ]
    else:
        out.terms = [("", compile_affinity(pl.cluster_affinity, snap))]

    tol_bits = _tolerated_bits(pl.cluster_tolerations, snap)
    out.taint_ok = ~mops.intersects(snap.taint_bits, ~tol_bits)

    out.spread_field_ok = np.ones((c,), bool)
    for sc in pl.spread_constraints:
        if sc.spread_by_field == "provider":
            out.spread_field_ok &= snap.provider_ids != 0
        elif sc.spread_by_field == "region":
            out.spread_field_ok &= snap.region_ids != 0
        elif sc.spread_by_field == "zone":
            out.spread_field_ok &= snap.zone_ids != 0
    out.spread_constraints = list(pl.spread_constraints)

    out.strategy = strategy_code(placement)
    out.static_weights = np.zeros((c,), np.int32)
    if (
        out.strategy == STATIC_WEIGHT
        and pl.replica_scheduling is not None
        and pl.replica_scheduling.weight_preference is not None
    ):
        # weight = max over matching rules (division_algorithm.go:44-48)
        for rule in pl.replica_scheduling.weight_preference.static_weight_list:
            rule_mask = compile_affinity(rule.target_cluster, snap)
            out.static_weights = np.where(
                rule_mask,
                np.maximum(out.static_weights, np.int32(rule.weight)),
                out.static_weights,
            )
    return out
