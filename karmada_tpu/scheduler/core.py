"""TensorScheduler: the batched Filter/Score/Select/Assign pipeline.

Re-architecture of the reference's per-binding pipeline
(core/generic_scheduler.go:70-115 — findClustersThatFit ->
prioritizeClusters -> SelectClusters -> AssignReplicas) as chunked tensor
programs over [bindings, clusters] arrays:

- Filter: mask composition from compiled placements + per-binding leniency
  (already-placed) and eviction masks — HOT LOOP 1+2 of SURVEY.md section 3.1
  collapse into gathers and boolean ops.
- Score: locality scoring (cluster already holds the resource scores 100,
  clusterlocality/cluster_locality.go:43-56); used by spread selection.
- Select: spread-constraint group selection (karmada_tpu.scheduler.spread).
- Assign: the unified division kernel (karmada_tpu.ops.divide).

The ordered ClusterAffinities retry loop (scheduler.go:533-596) runs as a
short host loop over affinity-term rounds: each round schedules every not-
yet-placed binding against its term-t mask, so T rounds of fully batched
kernels replace per-binding retries (T == max #terms, almost always 1).

Chunking: bindings are processed in fixed-size chunks (padded) so jit traces
once; 100k bindings x 5k clusters stream through [chunk, C] arrays sized for
HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..api.policy import Placement
from ..ops.divide import divide_replicas
from ..ops.estimate import general_estimate, merge_estimates
from ..utils.features import CUSTOMIZED_CLUSTER_RESOURCE_MODELING, feature_gate
from .snapshot import ClusterSnapshot, CompiledPlacement, compile_placement

LOCALITY_SCORE = 100  # cluster_locality.go:43-56


def kernel_variant(
    avail_max: int, static_max: int, prev_max: int, max_n: int, c: int
) -> tuple[bool, Optional[tuple]]:
    """Choose the divide-kernel specialization from host-known bounds.

    Returns ``(wide, fast)`` for divide_replicas: int32 fast path when every
    weight x target product and per-row weight sum provably fits 31 bits
    (weights can be avail, prev, the fresh-mode avail+prev sum, or static
    weights; targets <= replicas), and the packed-key top_k dispense when
    the (weight, lastReplicas, index) key fits 31 bits with a small
    remainder rank. The bit split snaps to tiers so the static tuple (and
    hence the jit trace) does not churn as data maxima drift."""
    # exact weight bound by cohort: avail (<= avail_max), prev (<= prev_max),
    # fresh = avail + credited prev (<= sum), static (<= static_max) — the
    # bound decides both the int32 gate and the packed-key bit budget, so
    # every saved bit widens the fast path's reach
    max_w = max(avail_max + prev_max, static_max, 1)
    narrow = max_w * max(max_n, 1) < 2**31 and max_w * c < 2**31
    fast = None
    if narrow:
        w_bits = max(1, max_w.bit_length())
        l_bits = max(1, int(prev_max).bit_length())
        i_bits = max(1, (c - 1).bit_length())
        k_top = min(c, 1 << max(1, max(1, max_n) - 1).bit_length())
        div_f32 = max_w * max(max_n, 1) < 2**24 and max_n < 2**22
        if k_top <= 1024:
            if w_bits + l_bits + i_bits <= 31:
                # every tier is one bounded, persistently-cached trace; a
                # floor above 4 would push tight-budget fleets (large
                # i_bits + moderate w_bits) off the snap entirely and churn
                # traces with every data-maxima drift
                for l_tier in (4, 8, 12, 16):
                    if l_bits <= l_tier and w_bits <= 31 - i_bits - l_tier:
                        l_bits = l_tier
                        w_bits = 31 - i_bits - l_tier
                        break
                fast = (w_bits, l_bits, k_top, div_f32, True)
            elif w_bits + l_bits <= 31:
                # (weight, last) alone fits: the two-stage top_k dispense
                # (take_by_weight_fast with_idx=False) recovers index
                # tie-breaks without packing the index
                for l_tier in (4, 8, 12, 16):
                    if l_bits <= l_tier and w_bits <= 31 - l_tier:
                        l_bits = l_tier
                        w_bits = 31 - l_tier
                        break
                fast = (w_bits, l_bits, k_top, div_f32, False)
    return (not narrow), fast


def host_profile_table(
    snapshot, uniq: np.ndarray, models_active: bool = False
) -> np.ndarray:
    """numpy mirror of ``TensorScheduler._profile_table`` over unique
    request profiles: int64[U, C], MAX_INT32 sentinel where nothing is
    requested or the cluster gives no summary (ops/estimate.py:25-38),
    with the resource-model estimator replacing the summary estimate
    where applicable when ``models_active`` (general.go:63-94,118-135 —
    pods cap applied separately, exactly like the device form). THE
    single host-side mirror — the tiny-batch fast path and the fleet's
    avail-max bound both consume it, so sentinel semantics cannot drift.
    Values are clamped to the sentinel BEFORE comparison, exactly like
    the device form's final min — an absurd-but-legal ratio above 2^31-1
    must read as "no answer -> clamp to spec.Replicas", not as a huge
    availability."""
    mi = 2**31 - 1  # plain int (ops.estimate.MAX_INT32 is a DEVICE scalar)
    cap = np.maximum(np.asarray(snapshot.available_cap), 0)
    table = np.full((uniq.shape[0], cap.shape[0]), mi, np.int64)
    for d in range(uniq.shape[1]):
        req = uniq[:, d]
        ratio = cap[None, :, d] // np.maximum(req[:, None], 1)
        table = np.where((req > 0)[:, None], np.minimum(table, ratio), table)
    table = np.minimum(table, mi)
    if models_active:
        from ..models.modeling import estimate_by_models_np

        mp = snapshot.model_pack
        pods_dim = snapshot.dim_index("pods")
        req_models = np.asarray(uniq)
        if pods_dim is not None:
            req_models = req_models.copy()
            req_models[:, pods_dim] = 0
        model_avail, applicable = estimate_by_models_np(
            np.asarray(mp.min_bounds), np.asarray(mp.counts),
            np.asarray(mp.covered), req_models,
        )
        model_avail = model_avail.astype(np.int64)
        if pods_dim is not None:
            allowed = np.minimum(np.maximum(cap[:, pods_dim], 0), mi)
            model_avail = np.minimum(model_avail, allowed[None, :])
        use_model = np.asarray(mp.has_models)[None, :] & applicable
        table = np.where(use_model, model_avail, table)
    return np.where(np.asarray(snapshot.has_summary)[None, :], table, mi)


class _BoostedSnapshot:
    """Capacity-shifted view of a ClusterSnapshot for the preemption
    re-solve: ``available_cap`` reads as ``base + freed_caps`` (the
    victims' resources, per cluster column); every other attribute
    delegates. Never cached anywhere — the per-profile/selection caches
    key on the real snapshot only."""

    def __init__(self, base, freed_caps):
        self._base = base
        self.available_cap = np.asarray(base.available_cap) + np.asarray(
            freed_caps, dtype=np.asarray(base.available_cap).dtype
        )

    def __getattr__(self, name):
        return getattr(self._base, name)


@dataclass
class BindingProblem:
    """Engine-level scheduling unit (decoupled from the API object; the
    scheduler process builds these from ResourceBindings)."""

    key: str
    placement: Optional[Placement] = None
    replicas: int = 0
    requests: dict[str, int] = dc_field(default_factory=dict)
    gvk: str = ""
    prev: dict[str, int] = dc_field(default_factory=dict)  # spec.clusters
    evict_clusters: tuple[str, ...] = ()  # graceful-eviction tasks
    fresh: bool = False  # reschedule triggered
    namespace: str = ""  # quota-admission namespace ("" = not quota'd)
    # scarcity plane (ISSUE 14): the binding's priority class (0 = the
    # back-compat default — never preempts, preemptible by any class
    # above it) and the subset of evict_clusters whose eviction task is
    # a preemption (the explain capture's stage-7 bit)
    priority: int = 0
    preempt_clusters: tuple[str, ...] = ()


@dataclass
class ScheduleResult:
    key: str
    clusters: dict[str, int] = dc_field(default_factory=dict)
    feasible: tuple[str, ...] = ()  # post-filter candidates (zero-replica set)
    affinity_name: str = ""
    error: str = ""

    @property
    def success(self) -> bool:
        return not self.error


#: the divider's insufficient-capacity verdict (wire/compat surface —
#: tests and the oracle match on it; REASONS classifies it as
#: InsufficientReplicas). The preemption plane's demander predicate:
#: only THIS failure means "freeing capacity could place the binding".
INSUFFICIENT_ERROR = "clusters available replicas are not enough"


@dataclass
class PreemptionOutcome:
    """One pass's preemption verdict, deposited on the engine as
    ``last_preemption`` for the scheduler controller to act on (victim
    evictions are store writes — the engine never touches API objects,
    the quota-plane division of labor)."""

    #: (key, resident placement dict, priority) per selected victim
    victims: list = dc_field(default_factory=list)
    #: demander keys that re-solved successfully against the freed
    #: capacity (their results were patched in place)
    placed: list = dc_field(default_factory=list)
    #: demander keys still unschedulable even with every victim freed
    still_unschedulable: list = dc_field(default_factory=list)
    #: int64[C, R] capacity the victims free, per cluster column
    freed_caps: Optional[np.ndarray] = None


class TensorScheduler:
    """Schedules batches of bindings against one cluster snapshot."""

    #: the in-tree filter/score plugin set (framework/plugins/registry.go:30-39)
    PLUGINS = (
        "APIEnablement",
        "ClusterAffinity",
        "ClusterEviction",
        "ClusterLocality",
        "SpreadConstraint",
        "TaintToleration",
    )

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        chunk_size: int = 4096,
        extra_estimators: Sequence = (),
        disabled_plugins: Sequence[str] = (),
        custom_filters: Sequence = (),
        mesh=None,
        shard_clusters: bool = False,
        trace_manifest=None,
    ):
        self.snapshot = snapshot
        self.chunk_size = chunk_size
        # durable trace ledger (scheduler.prewarm.TraceManifest | path |
        # None = env default KARMADA_TPU_TRACE_MANIFEST, unset = off).
        # Resolved once here so every fleet table this engine builds
        # shares one manifest instance (one dedup set, one file).
        from .prewarm import resolve_manifest

        self.trace_manifest = resolve_manifest(trace_manifest)
        # scheduling-grid mesh (jax.sharding.Mesh with axes ("b", "c")):
        # the fleet solve shards its row axis over "b" (and the cluster
        # axis over "c" when shard_clusters) via sharding constraints —
        # multi-chip scale-out of the production path, placement-
        # identical to single-device. Resolved ONCE here, the manifest
        # pattern: an explicit Mesh passes through, None falls back to
        # the KARMADA_TPU_MESH_DEVICES env default, False forces
        # single-device even with the env set.
        from ..parallel.mesh import record_active_mesh, resolve_mesh

        self.mesh = resolve_mesh(mesh)
        if self.mesh is not None:
            record_active_mesh(self.mesh)
            # a >1 cluster axis only exists to shard clusters: opt in
            # automatically so the env knob alone configures both axes
            shard_clusters = bool(
                shard_clusters or self.mesh.shape.get("c", 1) > 1
            )
        self.shard_clusters = shard_clusters
        # callables (requests[B,R] int64, replicas[B] int32) -> int32[B,C]
        # availability with -1 for "no answer" (accurate estimators plug here)
        self.extra_estimators = list(extra_estimators)
        # --plugins enable/disable list (scheduler.go:243-247)
        self.disabled_plugins = set(disabled_plugins)
        # out-of-tree filter plugins (the plugin-registry seam,
        # framework/runtime/registry.go): callables
        # (snapshot, problems) -> bool[B, C] mask AND-composed with the
        # in-tree filters — batched by construction
        self.custom_filters = list(custom_filters)
        # id(placement) -> (placement, compiled), LRU-bounded. The strong
        # reference to the Placement keeps its id() from being reused by a
        # new object after GC — without it a fresh Placement landing at a
        # recycled address would silently reuse a stale compiled mask.
        # Eviction is safe (pin and compiled mask leave together) and bounds
        # memory under sustained binding churn against a long-lived engine.
        from collections import OrderedDict

        self._placement_cache: OrderedDict[
            int, tuple[Optional[Placement], CompiledPlacement]
        ] = OrderedDict()
        # device-resident fleet table (scheduler.fleet): engaged for large
        # batches of fleet-eligible bindings; generation counter lets the
        # table detect in-place snapshot swaps (update_snapshot)
        self._fleet = None
        self._snapshot_gen = 0
        # (id(base compiled), selection bytes) -> (derived cp, pinned base)
        self._selection_cache: dict = {}
        # batch-identity fast path (see schedule()): id() array of the last
        # all-fleet batch + the derived lists; _batch_problems pins the
        # problem objects so a recycled id() cannot alias a stale batch
        self._batch_ids: Optional[np.ndarray] = None
        self._batch_gen = -1
        self._batch_cache: Optional[tuple] = None
        self._batch_problems: Optional[list] = None
        self._batch_spread = True  # batch holds derived spread selections
        self._batch_token = None  # snapshot.mask_token at cache time
        # per-pass dirty-key set (ISSUE 20): the controller's invalidation
        # sources (watch bus, quota bumps, estimator movement, evictions)
        # accumulate binding keys whose problems changed since the last
        # wave; schedule() stages them here and the batch-identity diff
        # unions them with the id()-diff to form the delta positions.
        # None = caller supplied no dirty info (diff alone decides).
        self._dirty_keys: Optional[set] = None
        # key -> position map of the armed batch (lazily built, only when
        # dirty keys need resolving against a large wave)
        self._key_pos: Optional[dict] = None
        # estimator-backed batch-identity fast path (see schedule()):
        # (ids, snapshot gen, estimator ids, confirm tokens, results +
        # pinned problems) of the last host-path batch whose estimators
        # could all prove their memo content via refresh_token
        self._est_batch: Optional[tuple] = None
        # binding key -> (row fingerprint, derived cp | None): skips the
        # packing+selection stage for unchanged spread rows in steady storms
        self._derived_rows: dict = {}
        # batched solves dispatched (host chunks + fleet passes): the
        # chaos bench reads this to prove a failover wave reschedules its
        # displaced bindings in O(chunks) solves, not O(bindings)
        self.solve_batches = 0
        # request-profile bytes -> availability row [C] (per snapshot gen)
        self._sel_profile_rows: dict = {}
        self._sel_profile_gen = -1
        # quota plane (scheduler.quota.QuotaSnapshot | None): admission
        # runs as ONE batched kernel pass before the solve; static-
        # assignment caps fold into availability as one more estimator.
        # Disarmed = a single `is None` check per schedule() call.
        self.quota = None
        # (problem ids, quota generation, admitted sub-list, denied
        # results) of the last wave with denials: keeps the admitted
        # sub-list IDENTITY-stable across steady storm passes so the
        # batch-identity fast paths below still fire under enforcement
        self._quota_cache: Optional[tuple] = None
        # device mirror of the static-assignment cap tensor, keyed by the
        # quota snapshot's cap_token (caps change rarely; remaining often)
        self._caps_dev = None
        self._caps_dev_token = None
        # engine-level trace ledger for the quota kernels (the fleet table
        # ledgers the solve family; admission dispatches engine-side)
        self._engine_traces: set = set()
        self._engine_new_trace = False
        # placement provenance (ISSUE 13): when armed, every schedule()
        # pass runs ONE extra batched explain dispatch per chunk and
        # deposits the exclusion masks + top-k summaries in the
        # process-wide ExplainStore. Disarmed — the default — the hot
        # path costs one `is None` check (the quota/fault pattern).
        from ..utils.explainstore import explain_armed, store as _estore

        self.explain = _estore() if explain_armed() else None
        # scarcity plane (ISSUE 14): when armed, a pass whose priority>0
        # rows answer "available replicas are not enough" runs ONE
        # batched plane-wide victim selection (ops.preempt) and re-solves
        # the demanders against the freed capacity IN THE SAME PASS.
        # ``preempt_source`` is a callable(exclude_keys) answering the
        # resident victim pool as BindingProblems (the controller wires
        # it per pass; None — the default — is the disarmed state: one
        # `is None` check per schedule() call, the quota/fault pattern).
        self.preempt_source = None
        self.last_preemption: Optional[PreemptionOutcome] = None

    PLACEMENT_CACHE_CAP = 8192
    #: minimum eligible-batch size before the device-resident path engages
    #: (below it, per-pass dispatch overhead beats the host packing cost).
    #: Kept low enough that a storm's straggler batches ride the same
    #: already-compiled fleet trace instead of fresh host-path chunk shapes
    fleet_threshold = 256

    # -- compilation -------------------------------------------------------

    def _compiled(self, placement: Optional[Placement]) -> CompiledPlacement:
        key = id(placement) if placement is not None else 0
        hit = self._placement_cache.get(key)
        if hit is not None:
            self._placement_cache.move_to_end(key)
            return hit[1]
        cp = compile_placement(placement, self.snapshot)
        # placement-level half of the fleet-eligibility predicate, computed
        # once per compiled placement: the per-problem check in schedule()
        # runs 100k times per storm pass and must stay a plain attribute
        # test, not a function call (measured ~240ms/pass as a method)
        from .spread import should_ignore_spread_constraint

        cp.fleet_single_term = len(cp.terms) == 1 and (
            not cp.spread_constraints
            or should_ignore_spread_constraint(cp.placement or Placement())
        )
        self._placement_cache[key] = (placement, cp)
        # the cap must exceed the fleet table's live-slot budget: a live
        # placement set larger than the LRU turns a storm's cyclic access
        # into a 100% miss rate (~every row recompiles its selector, tens
        # of seconds per pass), and each recompile mints a NEW compiled
        # object whose id() mints a NEW fleet slot — ballooning the slot
        # table until it dies (observed on the 9k-unique rotation bench)
        cache_cap = self.PLACEMENT_CACHE_CAP
        if self._fleet is not None:
            cache_cap = max(cache_cap, 2 * self._fleet._max_slots())
        if len(self._placement_cache) > cache_cap:
            self._placement_cache.popitem(last=False)
        return cp

    # -- public API --------------------------------------------------------

    def update_snapshot(self, snapshot: ClusterSnapshot) -> bool:
        """Swap in a refreshed snapshot over the SAME cluster set (the
        informer-cache delta case: capacity/taints/enablements drifted but
        no cluster joined or left). Returns False when the cluster set or
        resource dims changed — callers must rebuild the engine then.

        Keeps the device-resident fleet table's binding rows valid (cluster
        indices are stable), so a fleet-wide storm after a status heartbeat
        costs mask/estimator table rebuilds instead of a full repack."""
        if (
            snapshot.names != self.snapshot.names
            or snapshot.dims != self.snapshot.dims
        ):
            return False
        # compiled placements are functions of the FILTER fields only
        # (snapshot.mask_token): an availability-only swap keeps every
        # cached mask valid, so a heterogeneous fleet's churn pass skips
        # recompiling thousands of selectors (~0.5s/pass at 3.5k placements)
        if snapshot.mask_token != self.snapshot.mask_token:
            self._placement_cache.clear()
            self._selection_cache.clear()
        self._derived_rows.clear()  # selections depend on capacities
        self.snapshot = snapshot
        self._snapshot_gen += 1
        return True

    @property
    def last_pass_new_trace(self) -> bool:
        """True when the last schedule() pass dispatched at least one XLA
        trace signature the fleet table had not dispatched before (a compile
        ran, or — on the async tunnel — is still queued). Bench warmup loops
        poll this until a pass is compile-stable before opening a timed
        window. Engine-dispatched quota kernels count too."""
        return bool(
            (self._fleet is not None and self._fleet.new_trace_last_pass)
            or self._engine_new_trace
        )

    @property
    def mesh_info(self):
        """Canonical shape of the scheduling mesh — ``(("b", nb),
        ("c", nc))``, or None single-device. The reporting form: the
        solver sidecar's boot line, ``/debug/traces`` and the warmup
        stats all quote it so an operator can tell a single-chip from an
        8-chip plane."""
        from ..parallel.mesh import mesh_shape

        return mesh_shape(self.mesh)

    def set_quota(self, quota) -> None:
        """Swap in a (re)built QuotaSnapshot (None = enforcement off).

        A changed ``cap_token`` (static-assignment content or cluster
        columns moved) drops the fleet table: cap rows are baked into its
        interned profile slots. A generation-only bump (remaining moved —
        the common case: usage recompute, quota raise) keeps every packed
        row and trace; only the admission partition recomputes — a denied
        binding clears on a quota raise without a full re-pack."""
        old = self.quota
        self.quota = quota
        # a quota with NO static assignments bakes nothing into the fleet
        # profile slots — treat its cap token as absent so toggling
        # enforcement (or FRQ churn without caps) never drops the table
        new_tok = (
            quota.cap_token
            if quota is not None and quota.cap_index
            else None
        )
        old_tok = (
            old.cap_token if old is not None and old.cap_index else None
        )
        if new_tok != old_tok:
            self._fleet = None
            self._batch_ids = None
            self._batch_cache = None
            self._batch_problems = None
            self._est_batch = None
            self._quota_cache = None
            self._caps_dev = None
            self._caps_dev_token = None
            # derived spread selections rank groups on cap-folded
            # availability: cap content changes invalidate them
            self._derived_rows.clear()

    # -- quota admission ---------------------------------------------------

    _ENGINE_TRACE_KERNELS = {
        "Q": "quota_admit",
        "K": "quota_cluster_caps",
        "E": "explain_pass",
        "P": "preempt_select",
    }

    def _mark_trace(self, *key) -> bool:
        """Engine-side trace ledger for the quota kernels — the fleet
        table's contract (new-trace flag + compile counter + manifest
        record eligibility), for kernels dispatched outside it."""
        if key in self._engine_traces:
            return False
        self._engine_traces.add(key)
        self._engine_new_trace = True
        from ..utils.metrics import kernel_compiles

        bucket = "x".join(
            str(v) for v in key[1:] if isinstance(v, (int, bool))
        )[:64]
        kernel_compiles.inc(
            kernel=self._ENGINE_TRACE_KERNELS.get(key[0], str(key[0])),
            bucket=bucket,
        )
        return True

    def _record_trace(self, kernel: str, key, arrays, **statics) -> None:
        """Best-effort manifest record of a fresh engine-side trace (the
        fleet table's semantics: durability is optional, the wave is
        not)."""
        manifest = self.trace_manifest
        if manifest is None:
            return
        try:
            manifest.record(kernel, key, arrays, statics)
        except Exception as exc:  # noqa: BLE001 — never abort a wave
            import logging

            logging.getLogger("karmada_tpu").warning(
                "trace manifest record of %s failed (%s)",
                kernel, type(exc).__name__,
            )

    def _caps_device(self):
        """Device mirror of the static-assignment cap tensor, rebuilt only
        when the quota snapshot's cap content changes. Rebuilds refresh
        the device-byte ledger's quota slice (the fleet table publishes
        its own kinds per pass)."""
        q = self.quota
        if self._caps_dev is None or self._caps_dev_token != q.cap_token:
            self._caps_dev = jnp.asarray(q.cluster_caps)
            self._caps_dev_token = q.cap_token
            from ..utils.metrics import device_bytes as device_bytes_gauge

            caps = self._caps_dev
            try:
                platform = next(iter(caps.devices())).platform
            except Exception:  # noqa: BLE001 — label is best-effort
                platform = "none"
            device_bytes_gauge.remove_matching(kind="quota_caps")
            device_bytes_gauge.set(
                int(caps.nbytes),
                kind="quota_caps",
                bucket="x".join(str(int(s)) for s in caps.shape),
                platform=platform,
            )
        return self._caps_dev

    def device_bytes(self) -> dict[str, int]:
        """Resident device bytes by ledger kind across this engine: the
        fleet table's kinds plus the quota cap tensor — the exact
        ``nbytes`` of the arrays held (ISSUE 12 b). The bench asserts
        the sum is constant across steady passes and equals the gauge's
        samples."""
        out: dict[str, int] = (
            self._fleet.device_bytes() if self._fleet is not None else {}
        )
        if self._caps_dev is not None:
            out["quota_caps"] = int(self._caps_dev.nbytes)
        return out

    def _quota_cap_rows(self, problems) -> Optional[np.ndarray]:
        """int32[B] row into the cap tensor per binding (-1 = uncapped),
        or None when no binding is in a capped namespace."""
        q = self.quota
        if q is None or not q.has_caps:
            return None
        cap_index = q.cap_index
        rows = np.fromiter(
            (cap_index.get(p.namespace, -1) for p in problems),
            np.int32,
            len(problems),
        )
        return rows if (rows >= 0).any() else None

    def _quota_caps_np(self, cap_rows, requests) -> np.ndarray:
        """Host mirror of the cap estimate (same kernel body as the
        device form — cluster_caps_np instantiates it over numpy)."""
        from ..ops.quota import cluster_caps_np

        return cluster_caps_np(
            self.quota.cluster_caps, cap_rows, requests
        )

    def _quota_caps_dev(self, cap_rows, requests) -> jnp.ndarray:
        from ..ops.quota import quota_cluster_caps

        caps_dev = self._caps_device()
        arrays = (
            caps_dev,
            jnp.asarray(cap_rows, jnp.int32),
            jnp.asarray(requests, jnp.int64),
        )
        # meshed cap fold: binding rows shard over "b" (cap tensor
        # replicates via _caps_device's one-time upload); ledger key per
        # mesh shape, manifest-unrecorded when meshed (see
        # _quota_admission for the rationale)
        q_mesh_el = None
        if self.mesh is not None:
            from ..parallel.mesh import mesh_shape, shard_rows

            rows_dev, req_dev = shard_rows(self.mesh, arrays[1], arrays[2])
            if rows_dev is not arrays[1]:
                q_mesh_el = mesh_shape(self.mesh)
            arrays = (caps_dev, rows_dev, req_dev)
        key = (
            "K", int(len(cap_rows)), tuple(int(s) for s in caps_dev.shape),
            q_mesh_el,
        )
        if self._mark_trace(*key) and q_mesh_el is None:
            self._record_trace("quota_cluster_caps", key, arrays)
        return quota_cluster_caps(*arrays)

    def _quota_admission(self, problems):
        """One batched admission pass over the wave. Returns
        ``(partition, pending_debit)``: partition is None when no binding
        is quota'd or every row admitted, else (admitted sub-list, denied
        results as (index, ScheduleResult) pairs) — identity-stable
        across steady passes via _quota_cache so the batch-identity fast
        paths keep firing under enforcement. ``pending_debit`` is the
        wave's admitted demand per namespace, to be committed by the
        caller AFTER the solve (None on cache replay — already
        committed)."""
        from ..ops.quota import quota_admit
        from .quota import QUOTA_EXCEEDED_ERROR

        q = self.quota
        ns_index = q.ns_index
        b = len(problems)
        ns_ids = np.fromiter(
            (ns_index.get(p.namespace, -1) for p in problems), np.int32, b
        )
        if not (ns_ids >= 0).any():
            return None, None
        cache = self._quota_cache
        ids = np.fromiter(map(id, problems), np.int64, b)
        if (
            cache is not None
            and cache[1] == q.generation
            and len(cache[0]) == b
            and np.array_equal(cache[0], ids)
        ):
            if cache[2] is None:  # cached all-admitted wave
                return None, None
            return (cache[2], cache[3]), None
        out = self._quota_admission_delta(problems, ids, ns_ids, cache)
        if out is not None:
            return out
        demand = np.zeros((b, len(q.dims)), np.int64)
        for i in np.flatnonzero(ns_ids >= 0):
            p = problems[i]
            delta = p.replicas - sum(p.prev.values())
            if delta > 0:
                demand[i] = q.demand_row(p.requests, delta)
        # pow2 row padding bounds the admission kernel's trace count;
        # pad rows are unquota'd zero-demand and always admit
        b_pad = 1 << max(0, (b - 1).bit_length())
        if b_pad > b:
            ns_ids = np.pad(ns_ids, (0, b_pad - b), constant_values=-1)
            demand = np.pad(demand, ((0, b_pad - b), (0, 0)))
        n_pad = 1 << max(2, (q.remaining.shape[0] - 1).bit_length())
        remaining = q.remaining
        if n_pad > remaining.shape[0]:
            from ..ops.quota import UNLIMITED

            remaining = np.pad(
                remaining,
                ((0, n_pad - remaining.shape[0]), (0, 0)),
                constant_values=UNLIMITED,
            )
        arrays = (
            jnp.asarray(ns_ids),
            jnp.asarray(demand),
            jnp.asarray(remaining),
        )
        # meshed admission: the wave rows shard over "b" (the quota
        # family's FAMILY_SPECS layout), the remaining tensor replicates
        # — quota_admit's sort/cumsum ride GSPMD collectives, placement-
        # identical to single-device. The ledger key carries the mesh
        # shape (a sharded-input executable is a distinct compile), but
        # meshed dispatches stay manifest-UNRECORDED: the kernel has no
        # mesh static, so a replay could only compile the single-device
        # form and would fake coverage.
        q_mesh_el = None
        if self.mesh is not None:
            from ..parallel.mesh import mesh_shape, shard_rows

            ns_dev, dem_dev = shard_rows(self.mesh, arrays[0], arrays[1])
            if ns_dev is not arrays[0]:  # divisible: placement happened
                q_mesh_el = mesh_shape(self.mesh)
            arrays = (ns_dev, dem_dev, arrays[2])
        key = ("Q", b_pad, n_pad, int(remaining.shape[1]), q_mesh_el)
        if self._mark_trace(*key) and q_mesh_el is None:
            self._record_trace("quota_admit", key, arrays)
        admitted_dev, wave_used = quota_admit(*arrays)
        admitted = np.asarray(admitted_dev)[:b]
        # the wave's admitted demand is the PENDING debit against the
        # working remaining: a drain spanning multiple engine passes
        # within ONE quota generation (batch splits, follow-on waves
        # before the usage controller recomputes) must not re-admit the
        # same budget. The caller commits it AFTER the solve so a pass
        # that dies mid-solve (worker bisect/retry) charges nothing; the
        # next generation rebuilds remaining from recomputed usage, so
        # debit and accounting never double-count.
        wu = np.asarray(wave_used)[: q.remaining.shape[0]]
        debit = wu if wu.any() else None
        if admitted.all():
            # cache the all-admitted outcome: a steady storm re-passing
            # the same wave skips the demand rebuild and the kernel.
            # The problems list is PINNED so a recycled id() cannot alias
            # a stale partition (the _batch_problems hazard).
            self._quota_cache = (
                ids, q.generation, None, None, np.zeros(0, np.int64),
                list(problems),
            )
            return None, debit
        denied_idx = np.flatnonzero(~admitted)
        denied = [
            (
                int(i),
                ScheduleResult(
                    key=problems[i].key, error=QUOTA_EXCEEDED_ERROR
                ),
            )
            for i in denied_idx
        ]
        # identity stability: an unchanged partition re-uses the PREVIOUS
        # admitted sub-list object, so the inner batch-identity paths see
        # the very same list across steady storm passes
        if (
            cache is not None
            and len(cache[4]) == len(denied_idx)
            and np.array_equal(cache[4], denied_idx)
            and len(cache[0]) == b
            and np.array_equal(cache[0], ids)
        ):
            sub = cache[2]
        else:
            sub = [problems[i] for i in np.flatnonzero(admitted)]
        # the full problems list is pinned (last element) so a recycled
        # id() cannot alias a stale partition
        self._quota_cache = (
            ids, q.generation, sub, denied, denied_idx, list(problems)
        )
        return (sub, denied), debit

    def _quota_admission_delta(self, problems, ids, ns_ids, cache):
        """Delta admission (ISSUE 20): a wave whose ids moved in a
        MINORITY of positions within the SAME quota generation re-admits
        only the changed rows. ``quota_admit`` is row_coupled (FIFO
        segments share a per-namespace cumsum), so the changed rows run
        through a COMPLETE admission kernel over their own sub-batch — a
        scoped full pass over the affected segment, never a partial
        dispatch — against the working remaining, which already carries
        every previously admitted row's debit. Unchanged rows replay
        their cached outcome exactly: within one generation the working
        remaining only decreases, so a prior denial stays denied and a
        prior admission stays charged. The returned debit covers ONLY
        the changed rows' delta demand — replayed rows are never
        re-charged (the PR 14 working-remaining restore contract,
        extended to the delta path). Returns (partition, debit), or None
        when ineligible (the caller runs the full admission)."""
        from ..ops.quota import quota_admit
        from .quota import QUOTA_EXCEEDED_ERROR

        q = self.quota
        b = len(problems)
        if (
            cache is None
            or cache[1] != q.generation
            or len(cache[0]) != b
            or not self._delta_enabled()
        ):
            return None
        ch = np.flatnonzero(ids != cache[0])
        if ch.size == 0 or ch.size * 2 > b:
            return None
        nd = len(q.dims)
        m = int(ch.size)
        ns_ch = ns_ids[ch]
        demand = np.zeros((m, nd), np.int64)
        for j in np.flatnonzero(ns_ch >= 0):
            p = problems[int(ch[j])]
            delta = p.replicas - sum(p.prev.values())
            if delta > 0:
                demand[j] = q.demand_row(p.requests, delta)
        old_denied = cache[4]
        if demand.any():
            b_pad = 1 << max(0, (m - 1).bit_length())
            ns_pad, dem_pad = ns_ch, demand
            if b_pad > m:
                ns_pad = np.pad(ns_ch, (0, b_pad - m), constant_values=-1)
                dem_pad = np.pad(demand, ((0, b_pad - m), (0, 0)))
            n_pad = 1 << max(2, (q.remaining.shape[0] - 1).bit_length())
            remaining = q.remaining
            if n_pad > remaining.shape[0]:
                from ..ops.quota import UNLIMITED

                remaining = np.pad(
                    remaining,
                    ((0, n_pad - remaining.shape[0]), (0, 0)),
                    constant_values=UNLIMITED,
                )
            arrays = (
                jnp.asarray(ns_pad),
                jnp.asarray(dem_pad),
                jnp.asarray(remaining),
            )
            q_mesh_el = None
            if self.mesh is not None:
                from ..parallel.mesh import mesh_shape, shard_rows

                ns_dev, dem_dev = shard_rows(self.mesh, arrays[0], arrays[1])
                if ns_dev is not arrays[0]:
                    q_mesh_el = mesh_shape(self.mesh)
                arrays = (ns_dev, dem_dev, arrays[2])
            key = ("Q", b_pad, n_pad, int(remaining.shape[1]), q_mesh_el)
            if self._mark_trace(*key) and q_mesh_el is None:
                self._record_trace("quota_admit", key, arrays)
            admitted_dev, wave_used = quota_admit(*arrays)
            adm_ch = np.asarray(admitted_dev)[:m]
            wu = np.asarray(wave_used)[: q.remaining.shape[0]]
            debit = wu if wu.any() else None
        else:
            # no changed row carries positive delta demand: all admit
            # trivially and nothing is charged
            adm_ch = np.ones(m, bool)
            debit = None
        new_denied = np.union1d(
            np.setdiff1d(old_denied, ch), ch[~adm_ch]
        ).astype(np.int64)
        if new_denied.size == 0:
            self._quota_cache = (
                ids.copy(), q.generation, None, None,
                np.zeros(0, np.int64), list(problems),
            )
            return (None, debit)
        denied = [
            (
                int(i),
                ScheduleResult(
                    key=problems[int(i)].key, error=QUOTA_EXCEEDED_ERROR
                ),
            )
            for i in new_denied
        ]
        if (
            cache[2] is not None
            and len(old_denied) == new_denied.size
            and np.array_equal(old_denied, new_denied)
        ):
            # partition shape unchanged: swap the changed admitted rows
            # into the PREVIOUS sub-list so the solve-level delta path
            # sees an identity-stable wave downstream
            sub = list(cache[2])
            ch_adm = ch[adm_ch]
            if ch_adm.size:
                sub_pos = ch_adm - np.searchsorted(new_denied, ch_adm)
                for s_i, i in zip(sub_pos, ch_adm):
                    sub[int(s_i)] = problems[int(i)]
        else:
            admitted_mask = np.ones(b, bool)
            admitted_mask[new_denied] = False
            sub = [problems[i] for i in np.flatnonzero(admitted_mask)]
        self._quota_cache = (
            ids.copy(), q.generation, sub, denied, new_denied,
            list(problems),
        )
        return ((sub, denied), debit)

    @property
    def cap_shrink_pending(self) -> bool:
        """A buffer-cap shrink desire is accumulating in the fleet table
        (see FleetTable.shrink_pending) — warm loops should continue until
        it either fires (compiling inside warmup) or clears."""
        return bool(self._fleet is not None and self._fleet.shrink_pending)

    def set_explain(self, store) -> None:
        """Arm/disarm provenance capture for this engine (None =
        disarmed; benches and tests arm programmatically, processes via
        ``KARMADA_TPU_EXPLAIN=1``)."""
        self.explain = store

    def set_preemption(self, source) -> None:
        """Arm/disarm the preemption plane for this engine (None =
        disarmed). ``source(exclude_keys)`` answers the resident victim
        pool; the controller arms it per pass so dry solves and disarmed
        planes never pay more than the `is None` check."""
        self.preempt_source = source

    def schedule(
        self,
        problems: Sequence[BindingProblem],
        dirty_keys: Optional[set] = None,
    ) -> list[ScheduleResult]:
        """Provenance wrapper: the solve runs unchanged; when explain is
        armed the pass's decision provenance captures AFTER the results
        exist (one extra armed-only dispatch per chunk — telemetry, so
        a capture failure logs and never aborts the wave).

        ``dirty_keys`` (optional) is the caller's per-wave dirty-row set:
        binding keys whose problems changed since the last wave (watch-bus
        spec/generation movement, quota bumps, estimator pings, eviction
        displacements — the controller accumulates them). It rides beside
        the batch-identity token: the delta solve unions it with the
        object-identity diff, so a caller that rebuilds a problem object
        without changing content still gets the row re-dispatched when it
        says so. Disarmed (``KARMADA_TPU_DELTA_SOLVE=0``) or absent, the
        pass costs one ``is None`` check over the existing paths."""
        self.last_preemption = None
        self._dirty_keys = set(dirty_keys) if dirty_keys else None
        try:
            results = self._schedule_quota(problems)
        finally:
            self._dirty_keys = None
        # the preemption pass runs BEFORE the explain capture so a
        # re-solved demander's provenance shows its final placement. A
        # failed preemption pass logs and leaves the demanders' honest
        # unschedulable results intact — never the wave.
        if self.preempt_source is not None and problems:
            try:
                results = self._preempt_pass(list(problems), results)
            except Exception as exc:  # noqa: BLE001 — scarcity remedy is
                # optional; losing it must never lose the solve results.
                # The outcome is cleared too: a pass that died AFTER
                # victim selection but BEFORE the re-solve must not hand
                # the controller victims to evict with no demander placed
                self.last_preemption = None
                import logging

                logging.getLogger("karmada_tpu").warning(
                    "preemption pass failed (%s)", type(exc).__name__
                )
        # the store's enabled gate honors KARMADA_TPU_EXPLAIN_CAP=0:
        # a disabled ring must not pay the capture dispatch either
        if self.explain is not None and self.explain.enabled and problems:
            try:
                self._capture_explain(list(problems), results)
            except Exception as exc:  # noqa: BLE001 — provenance is
                # telemetry: losing a capture must never lose the wave
                import logging

                logging.getLogger("karmada_tpu").warning(
                    "explain capture failed (%s)", type(exc).__name__
                )
        return results

    def _schedule_quota(
        self, problems: Sequence[BindingProblem]
    ) -> list[ScheduleResult]:
        """Quota admission wrapper around the solve: when a QuotaSnapshot
        is set and the wave touches quota'd namespaces, ONE batched
        admission kernel partitions the wave; denied bindings answer a
        QuotaExceeded result without being solved, admitted ones ride the
        unchanged batched paths below. Disarmed quota costs one `is None`
        check."""
        self._engine_new_trace = False
        q = self.quota
        if q is not None and q.active:
            part, debit = self._quota_admission(problems)
            if part is not None:
                sub, denied = part
                try:
                    sub_res = self._schedule_inner(sub)
                except BaseException:
                    # a failed solve charges nothing AND drops the armed
                    # partition cache: the retry (same or rebuilt problem
                    # objects) re-admits against the uncharged remaining
                    self._quota_cache = None
                    raise
                # the wave's budget debit COMMITS only after the solve
                # returned: a pass that dies mid-solve (poisoned key,
                # backend error — the worker bisects and retries) must
                # not leave its demand charged, or the retry re-admits
                # against an already-debited remaining and spuriously
                # denies bindings that fit
                self._apply_quota_debit(debit)
                results: list = [None] * len(problems)
                for i, res in denied:
                    results[i] = res
                it = iter(sub_res)
                for i in range(len(problems)):
                    if results[i] is None:
                        results[i] = next(it)
                return results
            try:
                res = self._schedule_inner(problems)
            except BaseException:
                self._quota_cache = None
                raise
            self._apply_quota_debit(debit)
            return res
        return self._schedule_inner(problems)

    def _apply_quota_debit(self, debit) -> None:
        """Commit one admitted wave's demand against the working
        remaining (see QuotaSnapshot: debit within a generation, rebuilt
        from recomputed usage at the next). None = nothing to commit
        (cache replay, or no quota'd rows)."""
        if debit is None:
            return
        from ..ops.quota import UNLIMITED as _UNL

        q = self.quota
        limited = q.remaining < _UNL
        q.remaining = np.where(
            limited, np.maximum(q.remaining - debit, 0), q.remaining
        )

    # -- scarcity plane: plane-wide preemption (ISSUE 14) -------------------

    _PREEMPT_PAD = 256  # pow2 floor so tiny waves share one trace bucket

    def _preempt_pass(self, problems, results) -> list:
        """One armed-only preemption round per engine pass: demanders are
        the wave's priority>0 rows whose solve answered insufficient
        capacity AND that quota ADMITTED (a quota-denied row may never
        preempt its way past its namespace budget); victims come from the
        controller-wired resident pool. Victim selection is ONE
        ``ops.preempt.preempt_select`` dispatch over the combined rows;
        the freed per-cluster capacity re-enters the divide path in the
        same pass via ``_resolve_boosted``, and the outcome (victims to
        evict, re-solved placements) lands in ``last_preemption``.

        Returns the results list — MATERIALIZED to a plain list when a
        re-solve patched demander rows (the all-fleet path answers a
        lazy column-oriented ``_FleetResultList`` that rejects item
        assignment), the caller's original object otherwise."""
        import time as _time

        from ..ops.quota import DEMAND_CLAMP
        from ..utils.tracing import tracer as _tracer

        demand_idx = [
            i
            for i, (p, res) in enumerate(zip(problems, results))
            if getattr(p, "priority", 0) > 0
            and res.error == INSUFFICIENT_ERROR
        ]
        if not demand_idx:
            return results
        t0 = _time.perf_counter()
        snap = self.snapshot
        wave_keys = {p.key for p in problems}
        victims_pool = [
            v
            for v in (self.preempt_source(wave_keys) or ())
            if v.prev and sum(v.prev.values()) > 0
        ]
        outcome = PreemptionOutcome()
        self.last_preemption = outcome
        if not victims_pool:
            outcome.still_unschedulable = [
                problems[i].key for i in demand_idx
            ]
            return results
        dims = list(snap.dims)
        r = len(dims)
        c = snap.num_clusters
        demanders = [problems[i] for i in demand_idx]
        rows = demanders + victims_pool
        b = len(rows)
        prio = np.fromiter(
            (getattr(p, "priority", 0) for p in rows), np.int32, b
        )
        demand = np.zeros((b, r), np.int64)
        freed = np.zeros((b, r), np.int64)
        victim_ok = np.zeros(b, bool)
        weight = np.zeros(b, np.int32)
        assigned = np.zeros((b, c), np.int32)
        requests = np.zeros((b, r), np.int64)
        from .quota import per_replica_vector

        def scaled(req_row, count: int) -> np.ndarray:
            # scale in PYTHON ints (the quota demand_row rule): an
            # absurd-but-legal request x a huge count must clamp, not
            # wrap int64 to zero/negative and vanish from the cumsum
            return np.fromiter(
                (min(int(v) * count, DEMAND_CLAMP) for v in req_row),
                np.int64,
                len(req_row),
            )

        for i, p in enumerate(rows):
            req = per_replica_vector(p.requests, dims)
            requests[i] = np.minimum(req, DEMAND_CLAMP)
            if i < len(demanders):
                # unmet demand: the shortfall the divide could not cover
                # (fresh rows re-place everything, so the whole request
                # is unmet; scale-ups demand only the delta — the quota
                # plane's delta-demand rule)
                short = p.replicas - (
                    0 if p.fresh else sum(p.prev.values())
                )
                if short > 0:
                    demand[i] = scaled(requests[i], int(short))
            else:
                total = 0
                for name, reps in p.prev.items():
                    j = snap.index.get(name)
                    if j is not None and reps > 0:
                        assigned[i, j] = reps
                        total += int(reps)
                if total > 0:
                    weight[i] = min(total, 2**20 - 1)
                    victim_ok[i] = True
                    freed[i] = scaled(requests[i], total)
        if not demand.any() or not victim_ok.any():
            outcome.still_unschedulable = [p.key for p in demanders]
            return results

        # pow2 row padding bounds the trace count (pad rows are
        # priority-0 non-demander non-victims — inert by construction)
        b_pad = max(1 << max(0, (b - 1).bit_length()), self._PREEMPT_PAD)

        def pad(a):
            if b_pad == b:
                return a
            w = ((0, b_pad - b),) + ((0, 0),) * (a.ndim - 1)
            return np.pad(a, w)

        from ..ops.preempt import preempt_select
        from ..parallel.mesh import mesh_shape

        mesh = self.mesh
        if mesh is not None and b_pad % max(mesh.shape.get("b", 1), 1):
            mesh = None  # non-divisible batch: single-device semantics
        mesh_el = mesh_shape(mesh)
        arrays = tuple(
            jnp.asarray(a)
            for a in (
                pad(prio), pad(demand), pad(freed), pad(victim_ok),
                pad(weight), pad(assigned), pad(requests),
            )
        )
        key = ("P", int(b_pad), int(c), int(r), mesh_el)
        if self._mark_trace(*key):
            # recorded meshed too: preempt_select carries a real mesh
            # static (the explain_pass contract), so replay can
            # materialize the shape
            self._record_trace(
                "preempt_select", key, arrays, mesh=mesh_el
            )
        victims_dev, freed_caps_dev = preempt_select(*arrays, mesh=mesh)
        victim_mask = np.asarray(victims_dev)[:b]
        freed_caps = np.asarray(freed_caps_dev)
        if not victim_mask.any():
            outcome.still_unschedulable = [p.key for p in demanders]
            _tracer.record(
                "scheduler.preempt", _time.perf_counter() - t0,
                demanders=len(demanders), victims=0,
            )
            return results
        for i in np.flatnonzero(victim_mask):
            p = rows[int(i)]
            outcome.victims.append(
                (p.key, dict(p.prev), int(getattr(p, "priority", 0)))
            )
        outcome.freed_caps = freed_caps

        # freed capacity re-enters the divide path NOW: one extra batched
        # solve over just the demanders, against availability recomputed
        # on boosted capacity (still min-folded with static quota caps —
        # preemption never lifts a cap)
        compiled = [self._compiled(p.placement) for p in demanders]
        self.solve_batches += 1
        re_res = self._resolve_boosted(demanders, compiled, freed_caps)
        # the all-fleet path answers a lazy _FleetResultList: patch a
        # materialized copy (iteration decodes each row exactly once)
        results = list(results)
        for i, res in zip(demand_idx, re_res):
            if res.success:
                results[i] = res
                outcome.placed.append(res.key)
            else:
                outcome.still_unschedulable.append(res.key)
        _tracer.record(
            "scheduler.preempt", _time.perf_counter() - t0,
            demanders=len(demanders), victims=len(outcome.victims),
        )
        return results

    def _resolve_boosted(self, problems, compiled, freed_caps):
        """Re-solve a (small) demander batch against capacity boosted by
        the victims' freed resources: the general/model estimator mirror
        runs over ``available_cap + freed_caps`` (out-of-tree estimator
        answers are deliberately NOT consulted — they estimate from live
        member state, which cannot see a not-yet-evicted victim's
        capacity), static quota caps still fold, and the divide runs the
        oracle-identical numpy path when host-small (the
        ``_schedule_chunk`` bound) else the device kernels."""
        from ..ops import masks as mops
        from ..ops.divide import AGGREGATED as S_AGG, DYNAMIC_WEIGHT as S_DYN

        snap = self.snapshot
        out: list[ScheduleResult] = []
        for start in range(0, len(problems), self.chunk_size):
            chunk = problems[start : start + self.chunk_size]
            cchunk = compiled[start : start + self.chunk_size]
            base, strategy, replicas, static_w, requests, prev, fresh = (
                self._pack_chunk(chunk, cchunk, 0, with_affinity=False)
            )
            b = len(chunk)
            mi = 2**31 - 1
            # boosted availability: the host_profile_table mirror over a
            # capacity-shifted view of the snapshot (sentinel semantics
            # identical to _availability_np)
            boosted = _BoostedSnapshot(snap, freed_caps)
            uniq, inv = np.unique(requests, axis=0, return_inverse=True)
            dense = host_profile_table(
                boosted, uniq, models_active=self._models_active()
            )[inv]
            cap_rows = self._quota_cap_rows(chunk)
            if cap_rows is not None:
                dense = np.minimum(
                    dense, self._quota_caps_np(cap_rows, requests)
                )
            reps_col = replicas.astype(np.int64)[:, None]
            avail = np.where(reps_col == 0, mi, dense)
            avail = np.where(avail == mi, reps_col, avail)
            avail = np.minimum(avail, mi).astype(np.int32)

            # ordered-affinity selection on the boosted numbers (the
            # ranked path's exact predicate)
            cp_slot: dict[int, int] = {}
            unique_cps: list[CompiledPlacement] = []
            cp_idx = np.zeros(b, np.int32)
            for i, cp in enumerate(cchunk):
                slot = cp_slot.get(id(cp))
                if slot is None:
                    slot = len(unique_cps)
                    cp_slot[id(cp)] = slot
                    unique_cps.append(cp)
                cp_idx[i] = slot
            tmax = max(len(cp.terms) for cp in unique_cps)
            term_stack = np.zeros((len(unique_cps), tmax, snap.num_clusters), bool)
            term_len_u = np.ones(len(unique_cps), np.int32)
            for u, cp in enumerate(unique_cps):
                term_len_u[u] = len(cp.terms)
                for t, (_name, mask) in enumerate(cp.terms):
                    term_stack[u, t] = mask
            if "ClusterAffinity" in self.disabled_plugins:
                term_stack[:] = True
            cand_tc = base[:, None, :] & term_stack[cp_idx]
            rank, _fit = mops.first_fit_group(
                cand_tc,
                term_len_u[cp_idx],
                avail.astype(np.int64),
                replicas.astype(np.int64),
                prev.astype(np.int64),
                (strategy == S_DYN) | (strategy == S_AGG),
                fresh.astype(bool),
            )
            feasible = np.take_along_axis(
                cand_tc, rank[:, None, None].astype(np.intp), axis=1
            )[:, 0, :]
            candidates = self._select_for_chunk(
                chunk, cchunk, feasible, avail, prev
            )
            wmax = int(
                max(
                    int(avail.max(initial=0)) + int(prev.max(initial=0)),
                    int(static_w.max(initial=0)),
                    0,
                )
            )
            lmax = int(prev.max(initial=0)) + 1
            if (wmax + 1) * lmax * snap.num_clusters < 2**63:
                from ..refimpl.divider_np import assign_batch_np

                assignment, unschedulable = assign_batch_np(
                    strategy, replicas, candidates, static_w,
                    avail, prev, fresh,
                )
            else:
                res = self._assign(
                    strategy, replicas, candidates, static_w,
                    jnp.asarray(avail), prev, fresh,
                )
                assignment = np.asarray(res.assignment)
                unschedulable = np.asarray(res.unschedulable)
            out.extend(
                self._unpack(chunk, cchunk, rank, candidates,
                             assignment, unschedulable)
            )
        return out

    # -- placement provenance (ISSUE 13) -----------------------------------

    def _capture_explain(self, problems, results) -> None:
        """One armed-only provenance dispatch per chunk: compose the
        per-stage masks host-side (the same algebra ``_pack_chunk``
        feeds the solve, kept PER STAGE instead of AND-folded), run the
        ``ops.explain.explain_pass`` kernel, and deposit the capture in
        the process-wide ExplainStore under the current wave."""
        import time as _time

        from ..utils.tracing import tracer as _tracer

        t0 = _time.perf_counter()
        wave = _tracer.current_context().wave
        rows = 0
        for start in range(0, len(problems), self.chunk_size):
            chunk = problems[start : start + self.chunk_size]
            res = results[start : start + self.chunk_size]
            self.explain.add(self._explain_chunk(chunk, res, wave))
            rows += len(chunk)
        _tracer.record(
            "scheduler.explain", _time.perf_counter() - t0, rows=rows
        )

    def _explain_chunk(self, problems, results, wave: int):
        """Build one chunk's ExplainCapture. Stage masks carry the
        solve's exact leniency rules (already-placed taint/API leniency,
        evictions folded into the taint/NoExecute stage, the spread
        selection where a derived row exists) so a bit here means "this
        stage excluded this cluster in THIS pass". Out-of-tree custom
        filters are engine-level host hooks with no stage identity and
        are not attributed."""
        from ..ops import masks as mops
        from ..ops.divide import AGGREGATED as S_AGG, DYNAMIC_WEIGHT as S_DYN
        from ..ops.explain import explain_pass, topk_width
        from ..utils.explainstore import ExplainCapture
        from .quota import QUOTA_EXCEEDED_ERROR

        snap = self.snapshot
        disabled = self.disabled_plugins
        compiled = [self._compiled(p.placement) for p in problems]
        b, c = len(problems), snap.num_clusters

        cp_slot: dict[int, int] = {}
        unique_cps: list[CompiledPlacement] = []
        cp_idx = np.empty(b, np.int32)
        for i, cp in enumerate(compiled):
            slot = cp_slot.get(id(cp))
            if slot is None:
                slot = len(unique_cps)
                cp_slot[id(cp)] = slot
                unique_cps.append(cp)
            cp_idx[i] = slot
        spread_pl = np.stack([cp.spread_field_ok for cp in unique_cps])
        taint_pl = np.stack([cp.taint_ok for cp in unique_cps])

        gvk_slot: dict[str, int] = {}
        gvk_masks: list[np.ndarray] = []
        gvk_idx = np.empty(b, np.int32)
        for i, p in enumerate(problems):
            slot = gvk_slot.get(p.gvk)
            if slot is None:
                slot = len(gvk_masks)
                gvk_slot[p.gvk] = slot
                gid = snap.gvk_vocab.get(p.gvk) if p.gvk else None
                if gid is None:
                    m = (
                        np.zeros(c, bool)
                        if p.gvk and len(snap.gvk_vocab) > 0
                        else np.ones(c, bool)
                    )
                else:
                    word, bit_ = gid // 32, gid % 32
                    m = (snap.gvk_bits[:, word] >> np.uint32(bit_)) & 1 != 0
                gvk_masks.append(m)
            gvk_idx[i] = slot
        api_gvk = np.stack(gvk_masks)

        replicas = np.fromiter((p.replicas for p in problems), np.int32, b)
        fresh = np.fromiter((p.fresh for p in problems), bool, b)
        strategy = np.fromiter(
            (cp.strategy for cp in compiled), np.int32, b
        )
        r = len(snap.dims)
        prev = np.zeros((b, c), np.int32)
        evict = np.zeros((b, c), bool)
        preempted = np.zeros((b, c), bool)
        requests = np.zeros((b, r), np.int64)
        dim_index = {d: j for j, d in enumerate(snap.dims)}
        pods_dim = dim_index.get("pods")
        for i, p in enumerate(problems):
            for name, reps in p.prev.items():
                j = snap.index.get(name)
                if j is not None:
                    prev[i, j] = reps
            for name in p.evict_clusters:
                j = snap.index.get(name)
                if j is not None:
                    evict[i, j] = True
            for name in getattr(p, "preempt_clusters", ()):
                j = snap.index.get(name)
                if j is not None:
                    preempted[i, j] = True
            for d, q in p.requests.items():
                j = dim_index.get(d)
                if j is not None:
                    requests[i, j] = q
            if pods_dim is not None and p.replicas > 0:
                requests[i, pods_dim] = max(requests[i, pods_dim], 1)
        prev_mask = prev > 0

        taint_tol = taint_pl[cp_idx] | prev_mask
        if "TaintToleration" in disabled:
            taint_tol = np.ones((b, c), bool)
        if "ClusterEviction" in disabled:
            evict = np.zeros((b, c), bool)
        taint_ok = taint_tol & ~evict
        api_ok = api_gvk[gvk_idx] | (
            prev_mask & ~snap.complete_enablements[None, :]
        )
        if "APIEnablement" in disabled:
            api_ok = np.ones((b, c), bool)
        spread_ok = spread_pl[cp_idx]
        if "SpreadConstraint" in disabled:
            spread_ok = np.ones((b, c), bool)
        else:
            # spread rows with a derived selection: the Select stage's
            # surviving set IS the selection mask (id-pinned row cache)
            for i, (p, cp) in enumerate(zip(problems, compiled)):
                if len(cp.terms) == 1 and not cp.fleet_single_term:
                    hit = self._derived_rows.get(p.key)
                    if (
                        hit is not None
                        and hit[1] is p.placement
                        and hit[2] is not None
                    ):
                        spread_ok[i] = spread_ok[i] & hit[2].terms[0][1]

        # pre-cap merged availability: the host mirror when exact, the
        # device merge (without the cap estimator — the cap is its own
        # stage) when out-of-tree estimators are registered
        if self.extra_estimators:
            avail = np.asarray(
                self._availability(requests, replicas, None)
            ).astype(np.int32)
        else:
            avail = self._availability_np(requests, replicas, None)
        mi = np.int32(2**31 - 1)
        cap_rows = self._quota_cap_rows(problems)
        caps = (
            self._quota_caps_np(cap_rows, requests).astype(np.int32)
            if cap_rows is not None
            else np.full((b, c), mi, np.int32)
        )

        dynamic = (strategy == S_DYN) | (strategy == S_AGG)
        admitted = np.fromiter(
            (res.error != QUOTA_EXCEEDED_ERROR for res in results), bool, b
        )
        assignment = np.zeros((b, c), np.int32)
        for i, res in enumerate(results):
            for name, n_assigned in res.clusters.items():
                j = snap.index.get(name)
                if j is not None:
                    assignment[i, j] = n_assigned

        # selected affinity group: the tensorized ordered-failover
        # selection (ops.masks.first_fit_group — the ranked path's exact
        # predicate), so a displaced binding's capture records WHICH
        # fallback group it landed on. The SELECTION consumes the same
        # cap-folded availability the ranked solve ranks groups on
        # (_schedule_chunk_ranked passes cap_rows into _availability) —
        # only the kernel's per-stage avail input stays pre-cap, because
        # the cap is its own stage bit there.
        tmax = max(len(cp.terms) for cp in unique_cps)
        if tmax > 1 and "ClusterAffinity" not in disabled:
            if cap_rows is None:
                avail_rank = avail
            elif self.extra_estimators:
                avail_rank = np.asarray(
                    self._availability(requests, replicas, cap_rows)
                ).astype(np.int32)
            else:
                avail_rank = self._availability_np(
                    requests, replicas, cap_rows
                )
            term_stack = np.zeros((len(unique_cps), tmax, c), bool)
            term_len_u = np.ones(len(unique_cps), np.int32)
            for u, cp in enumerate(unique_cps):
                term_len_u[u] = len(cp.terms)
                for t, (_name, m) in enumerate(cp.terms):
                    term_stack[u, t] = m
            base = taint_ok & api_ok & spread_ok
            cand_tc = base[:, None, :] & term_stack[cp_idx]
            rank, _fit = mops.first_fit_group(
                cand_tc,
                term_len_u[cp_idx],
                avail_rank.astype(np.int64),
                replicas.astype(np.int64),
                prev.astype(np.int64),
                dynamic.astype(bool),
                fresh.astype(bool),
            )
            group_rank = rank.astype(np.int32)
            aff_ok = np.take_along_axis(
                term_stack[cp_idx],
                rank[:, None, None].astype(np.intp),
                axis=1,
            )[:, 0, :]
        else:
            group_rank = np.zeros(b, np.int32)
            aff_ok = np.stack(
                [cp.terms[0][1] for cp in unique_cps]
            )[cp_idx]
            if "ClusterAffinity" in disabled:
                aff_ok = np.ones((b, c), bool)

        # pow2 row padding bounds the trace count (the admission-kernel
        # discipline); pad rows are zero-replica all-excluded and are
        # sliced off before the capture
        b_pad = 1 << max(0, (b - 1).bit_length())
        b_pad = min(max(b_pad, b), max(self.chunk_size, b))
        pad = b_pad - b

        def pad_rows(a, value=0):
            if pad == 0:
                return a
            width = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
            return np.pad(a, width, constant_values=value)

        k = topk_width(c)
        mesh = self.mesh
        if mesh is not None and b_pad % max(mesh.shape.get("b", 1), 1):
            mesh = None  # non-divisible batch: single-device semantics
        shard_c = bool(self.shard_clusters and mesh is not None)
        arrays = tuple(
            jnp.asarray(a)
            for a in (
                pad_rows(aff_ok), pad_rows(taint_ok), pad_rows(api_ok),
                pad_rows(spread_ok), pad_rows(avail), pad_rows(caps),
                pad_rows(admitted, True), pad_rows(dynamic),
                pad_rows(replicas), pad_rows(assignment), pad_rows(prev),
                pad_rows(preempted),
            )
        )
        from ..parallel.mesh import mesh_shape

        mesh_el = mesh_shape(mesh)
        key = ("E", int(b_pad), int(c), int(k), mesh_el, shard_c)
        if self._mark_trace(*key):
            # recorded meshed too: explain_pass carries a real mesh
            # static (the fleet-kernel contract), so replay can
            # materialize the shape — unlike the static-less quota keys
            self._record_trace(
                "explain_pass", key, arrays,
                k=k, mesh=mesh_el, shard_c=shard_c,
            )
        mask_dev, topk_dev = explain_pass(
            *arrays, k=k, mesh=mesh, shard_c=shard_c
        )
        return ExplainCapture(
            wave=wave,
            names=snap.names,
            keys=[p.key for p in problems],
            masks=np.asarray(mask_dev)[:b],
            topk=np.asarray(topk_dev)[:b],
            group_rank=group_rank,
            errors=[res.error for res in results],
            assignment=assignment,
        )

    def _delta_enabled(self) -> bool:
        """The ISSUE 20 kill switch, read per pass so flipping
        ``KARMADA_TPU_DELTA_SOLVE=0`` takes effect on the next wave with
        no restart. Disarmed, every delta site collapses to one cheap
        check and the pre-existing full paths run untouched."""
        import os

        return os.environ.get("KARMADA_TPU_DELTA_SOLVE", "1") != "0"

    def _delta_pass(self, problems, ids, t0):
        """Batch-identity DELTA path (ISSUE 20): the wave has the shape
        of the armed batch but a minority of positions hold new problem
        objects (and/or the caller marked keys dirty). Compiles just the
        changed rows, verifies each against the fleet-eligibility
        predicate, and hands the fleet the swapped lists plus the dirty
        positions — the table packs and dispatches only those rows and
        replays the rest from its resident mirrors. Returns None when
        ineligible and the caller runs the full prologue: armed
        preemption (preempt_select is row_coupled — a partial wave
        cannot see the plane-wide victim cumsum), a moved snapshot
        generation (the replay base is stale), a changed row that is not
        fleet-eligible, or majority churn where the full pass wins."""
        import time as _time

        if (
            self._fleet is None
            or self.preempt_source is not None
            or self._batch_gen != self._snapshot_gen
            or not self._delta_enabled()
        ):
            return None
        n = len(problems)
        diff = np.flatnonzero(ids != self._batch_ids)
        dk = self._dirty_keys
        if dk:
            # dirty keys are advisory positions ON TOP of the id diff: a
            # mapping miss only over-dispatches (safe superset) — a truly
            # changed row always shows in the id diff as well
            kp = self._key_pos
            if kp is None or len(kp) != n:
                kp = {p.key: i for i, p in enumerate(problems)}
                self._key_pos = kp
            extra = [kp[k] for k in dk if k in kp]
            if extra:
                diff = np.union1d(diff, np.asarray(extra, np.int64))
        if diff.size * 2 > n:
            return None
        from ..ops.divide import DUPLICATED as _DUP
        from .fleet import K_PREV as _KP, MAX_REPLICAS_FAST as _MRF

        fp, fc = self._batch_cache
        fp2 = list(fp)
        fc2 = list(fc)
        for pos in diff:
            pos = int(pos)
            p = problems[pos]
            cp = self._compiled(p.placement)
            if not (
                cp.fleet_single_term
                and not p.evict_clusters
                and len(p.prev) <= _KP
                and (cp.strategy == _DUP or p.replicas <= _MRF)
            ):
                # a changed row left the fleet-eligible set (spread/
                # multi-term/eviction): the full prologue partitions it
                return None
            fp2[pos] = p
            fc2[pos] = cp
        self.last_breakdown = {"compile": _time.perf_counter() - t0}
        self.solve_batches += 1
        res = self._fleet.schedule(fp2, fc2, delta=diff)
        self.last_breakdown.update(self._fleet.last_breakdown)
        # re-arm the identity token on the swapped lists (gen and mask
        # token are unchanged by construction; _batch_spread likewise —
        # swapped-in rows are never derived selections)
        self._batch_problems = fp2
        self._batch_ids = ids
        self._batch_cache = (fp2, fc2)
        return res

    def _schedule_inner(
        self, problems: Sequence[BindingProblem]
    ) -> list[ScheduleResult]:
        import time as _time

        # estimator-backed batch-identity fast path: extra estimators force
        # the host path (no fleet table), but a storm re-scheduling the
        # SAME problem objects against the SAME snapshot generation is pure
        # in (problems, snapshot, estimator answers) — and a registry-backed
        # estimator can PROVE its answers unchanged via refresh_token
        # (generation confirmation: O(servers) pings, zero wire when
        # already confirmed). A no-member-movement refresh pass collapses
        # to the ping + an id() sweep instead of a full re-solve; any
        # unprovable estimator (no token, unconfirmed cluster, memo drop)
        # falls through to the full path, which retries it.
        if (
            self._est_batch is not None
            and self.extra_estimators
            and not self.custom_filters
        ):
            ids0, gen0, est_ids0, tokens0, results0, _pinned = self._est_batch
            if (
                gen0 == self._snapshot_gen
                and len(problems) == len(results0)
                and est_ids0 == tuple(map(id, self.extra_estimators))
            ):
                t0 = _time.perf_counter()
                ids = np.fromiter(map(id, problems), np.int64, len(problems))
                if np.array_equal(ids, ids0):
                    tokens = self._est_tokens()
                    if None not in tokens and tokens == tokens0:
                        self.last_breakdown = {
                            "compile": _time.perf_counter() - t0
                        }
                        return list(results0)

        # batch-identity fast path: a storm re-scheduling the SAME problem
        # objects against the SAME snapshot generation is pure in those
        # inputs — compilation, spread selection, and the eligibility
        # partition all key on object identity + snapshot gen, so one id()
        # sweep (~8ms at 100k) replaces the ~55ms host prologue. This is
        # the vectorized form of the per-row `is problem` fast path the
        # fleet's upsert already takes; like it, it assumes problem objects
        # are not mutated in place between passes.
        if (
            self._batch_ids is not None
            and (
                self._batch_gen == self._snapshot_gen
                # availability-only drift keeps every compiled mask and the
                # eligibility partition valid (placements key on filter
                # fields = mask_token); only derived SPREAD selections
                # depend on capacities, so spread-free batches reuse across
                # the swap — churn passes skip the prologue too
                or (
                    not self._batch_spread
                    and self._batch_token == self.snapshot.mask_token
                )
            )
            and not (
                self.custom_filters
                or self.extra_estimators
                or self.disabled_plugins
            )
            and len(problems) == len(self._batch_ids)
        ):
            t0 = _time.perf_counter()
            ids = np.fromiter(map(id, problems), np.int64, len(problems))
            if np.array_equal(ids, self._batch_ids) and not self._dirty_keys:
                self.last_breakdown = {
                    "compile": _time.perf_counter() - t0
                }
                fp, fc = self._batch_cache
                self.solve_batches += 1
                res = self._fleet.schedule(fp, fc)
                self.last_breakdown.update(self._fleet.last_breakdown)
                return res
            # not the identical batch: a minority of moved positions (or
            # caller-declared dirty keys) is the DELTA case — pack and
            # dispatch just those rows, replay the rest from the fleet's
            # resident mirrors (ISSUE 20)
            res = self._delta_pass(problems, ids, t0)
            if res is not None:
                return res

        t0 = _time.perf_counter()
        compiled = [self._compiled(p.placement) for p in problems]
        self.last_breakdown = {"compile": _time.perf_counter() - t0}
        # engine-level features that the device-resident path does not
        # model force the general host path for the whole batch
        if not (
            self.custom_filters or self.extra_estimators or self.disabled_plugins
        ):
            t0 = _time.perf_counter()
            from ..ops.divide import DUPLICATED as _DUP
            from .fleet import K_PREV as _KP, MAX_REPLICAS_FAST as _MRF

            # spread-constraint rows ride the fleet too: their host-side
            # group selection collapses to a per-row candidate mask, which
            # is interned as a DERIVED placement (terms = the selection)
            # so the device-resident path divides over exactly the selected
            # set — SelectClusters becomes part of placement compilation
            compiled = self._derive_spread_selections(problems, compiled)
            self.last_breakdown["select"] = _time.perf_counter() - t0

            t0 = _time.perf_counter()
            # THE fleet-eligibility predicate (single source of truth):
            # placement half precomputed as cp.fleet_single_term; the
            # per-problem half stays a plain inline expression because this
            # comprehension runs B times per storm pass — a method call per
            # row costs ~2.4us x 100k = 240ms
            fast_idx = [
                i
                for i, (p, cp) in enumerate(zip(problems, compiled))
                if cp.fleet_single_term
                and not p.evict_clusters
                and len(p.prev) <= _KP
                and (cp.strategy == _DUP or p.replicas <= _MRF)
            ]
            self.last_breakdown["eligible"] = _time.perf_counter() - t0
            # the host prologue (placement compile + spread selection +
            # eligibility partition) is the wave tree's "pack" phase —
            # recorded as one span so a storm's pass decomposes into
            # pack / solve(dispatch/device/fetch) under scheduler.pass
            from ..utils.tracing import tracer as _tracer

            _tracer.record(
                "scheduler.pack",
                sum(
                    self.last_breakdown.get(k, 0.0)
                    for k in ("compile", "select", "eligible")
                ),
                rows=len(problems),
            )
            if len(fast_idx) >= self.fleet_threshold:
                from .fleet import FleetTable

                if self._fleet is not None and self._fleet.slots_exhausted:
                    import sys as _sys

                    print(
                        "# fleet table rebuild: "
                        + self._fleet.exhaustion_summary(),
                        file=_sys.stderr,
                        flush=True,
                    )
                    self._fleet = None
                if self._fleet is None:
                    self._fleet = FleetTable(self)
                fp = [problems[i] for i in fast_idx]
                fc = [compiled[i] for i in fast_idx]
                self.solve_batches += 1
                fast_res = self._fleet.schedule(fp, fc)
                self.last_breakdown.update(self._fleet.last_breakdown)
                if len(fast_idx) == len(problems):
                    # all rows rode the fleet: hand back the lazy
                    # column-oriented result list as-is, and arm the
                    # batch-identity fast path for the next pass (fp/fc
                    # are the very list objects the fleet keys its own
                    # O(1) reuse on)
                    self._batch_problems = fp
                    self._batch_ids = np.fromiter(
                        map(id, fp), np.int64, len(fp)
                    )
                    self._batch_gen = self._snapshot_gen
                    self._batch_cache = (fp, fc)
                    self._batch_spread = any(
                        getattr(cp, "derived", False) for cp in fc
                    )
                    self._batch_token = self.snapshot.mask_token
                    return fast_res
                results: list = [None] * len(problems)
                for i, res in zip(fast_idx, fast_res):
                    results[i] = res
                slow_idx = [i for i in range(len(problems)) if results[i] is None]
                if slow_idx:
                    slow_res = self._schedule_host(
                        [problems[i] for i in slow_idx],
                        [compiled[i] for i in slow_idx],
                    )
                    for i, res in zip(slow_idx, slow_res):
                        results[i] = res
                return results
        res = self._schedule_host(problems, compiled)
        self._arm_est_batch(problems, res)
        return res

    def _est_tokens(self) -> tuple:
        """One refresh_token probe per extra estimator (None for
        estimators without the protocol)."""
        tokens = []
        for est in self.extra_estimators:
            probe = getattr(est, "refresh_token", None)
            tokens.append(probe() if probe is not None else None)
        return tuple(tokens)

    def _arm_est_batch(self, problems, res) -> None:
        """Arm the estimator-backed batch-identity fast path after a full
        host-path pass: cache the results keyed by problem ids, snapshot
        generation, and each estimator's confirm token. The problems list
        is pinned so a recycled id() cannot alias a stale batch."""
        if not self.extra_estimators or self.custom_filters:
            return
        tokens = self._est_tokens()
        if None in tokens:
            self._est_batch = None
            return
        self._est_batch = (
            np.fromiter(map(id, problems), np.int64, len(problems)),
            self._snapshot_gen,
            tuple(map(id, self.extra_estimators)),
            tokens,
            list(res),
            list(problems),
        )

    #: cap on interned selection variants; selection outcomes are memoized
    #: by row content so real fleets produce few — the cap only bounds
    #: adversarial churn
    SELECTION_CACHE_CAP = 8192

    def _derive_spread_selections(
        self,
        problems: Sequence[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[CompiledPlacement]:
        """Replace each single-term spread-constraint row's compiled
        placement with a DERIVED one whose affinity term IS the selected
        candidate set (select_clusters.go's SelectClusters stage folded
        into placement compilation). Selection runs on host exactly as the
        general path's Select stage does (same code, same memoization);
        the interned result makes the row fleet-eligible, so spread
        workloads get the device-resident delta-fetch path. Rows the
        selection REJECTS (FitError) keep their original placement and
        fall through to the host path, which reports the failure.

        Steady-state cost: selections are pure in (snapshot generation,
        placement, replicas/requests/prev), so a per-binding-key cache
        skips the whole packing+selection stage for unchanged rows, and
        availability rows come from a per-profile cache (one device fetch
        per NEW profile per snapshot generation)."""
        from .spread import select_clusters_batch

        # cheap predicate: fleet_single_term is precomputed per compiled
        # placement; a single-term cp that is NOT fleet-eligible is exactly
        # a spread-constrained one (the ignore rule is folded in)
        spread_idx = [
            i
            for i, cp in enumerate(compiled)
            if len(cp.terms) == 1 and not cp.fleet_single_term
        ]
        if not spread_idx:
            return compiled
        compiled = list(compiled)
        snap = self.snapshot
        gen = self._snapshot_gen
        cache = self._selection_cache
        row_cache = self._derived_rows
        pending: list[int] = []
        for i in spread_idx:
            p = problems[i]
            fp = (
                gen, id(p.placement), p.replicas,
                tuple(p.requests.items()), tuple(p.prev.items()),
            )
            hit = row_cache.get(p.key)
            # hit[1] pins the Placement whose id() the fingerprint embeds:
            # without it a GC'd placement re-allocated at the same address
            # would alias a stale derived selection (same hazard the
            # _selection_cache pins its base against)
            if hit is not None and hit[0] == fp and hit[1] is p.placement:
                if hit[2] is not None:
                    compiled[i] = hit[2]
                continue  # None = cached FitError: stay on the host path
            pending.append(i)
        if not pending:
            return compiled

        for start in range(0, len(pending), self.chunk_size):
            idx = pending[start : start + self.chunk_size]
            sub_p = [problems[i] for i in idx]
            sub_c = [compiled[i] for i in idx]
            feasible, _strat, replicas, _sw, requests, prev, _fr = (
                self._pack_chunk(sub_p, sub_c, 0)
            )
            avail = self._selection_availability(requests, replicas, gen)
            # static-assignment caps bound the SELECTION's availability
            # too: group selection must rank groups on the same
            # cap-folded numbers the divide will see, or it can pick a
            # group the capped divide cannot fill
            cap_rows = self._quota_cap_rows(sub_p)
            if cap_rows is not None:
                avail = np.minimum(
                    avail, self._quota_caps_np(cap_rows, requests)
                ).astype(np.int32)
            candidates = select_clusters_batch(
                snap, sub_p, sub_c, 0, feasible, avail, prev
            )
            for k, i in enumerate(idx):
                p = problems[i]
                fp = (
                    gen, id(p.placement), p.replicas,
                    tuple(p.requests.items()), tuple(p.prev.items()),
                )
                sel = candidates[k]
                if not sel.any():
                    # FitError: host reports (placement pinned, see lookup)
                    row_cache[p.key] = (fp, p.placement, None)
                    continue
                base = compiled[i]
                key = (id(base), sel.tobytes())
                entry = cache.get(key)
                if entry is None:
                    c = snap.num_clusters
                    derived = CompiledPlacement(
                        placement=base.placement,
                        terms=[(base.terms[0][0], sel.copy())],
                        # selection already ran on the post-filter set;
                        # all-true here keeps the fleet's leniency
                        # re-composition idempotent
                        taint_ok=np.ones(c, bool),
                        spread_field_ok=np.ones(c, bool),
                        strategy=base.strategy,
                        static_weights=base.static_weights,
                        spread_constraints=[],
                        fleet_single_term=True,
                    )
                    derived.derived = True  # fleet keys rows on id(derived)
                    if len(cache) >= self.SELECTION_CACHE_CAP:
                        cache.clear()
                    # pin base: the key embeds id(base) — a GC'd base whose
                    # address is recycled must not alias a cache entry
                    cache[key] = (derived, base)
                else:
                    derived = entry[0]
                compiled[i] = derived
                row_cache[p.key] = (fp, p.placement, derived)
        if len(row_cache) > 4 * max(len(problems), 1) + 65536:
            row_cache.clear()  # key-churn bound; repopulates next pass
        return compiled

    def _selection_availability(
        self, requests: np.ndarray, replicas: np.ndarray, gen: int
    ) -> np.ndarray:
        """Per-row availability for the Select stage from a per-profile
        cache: one device fetch per NEW request profile per snapshot
        generation (requests repeat fleet-wide), mirroring merge_estimates
        exactly — min over estimates with -1 ignored, MAX_INT32 sentinel
        clamped to spec.Replicas, zero-replica short-circuit."""
        from ..ops.estimate import MAX_INT32 as _MI

        if self._sel_profile_gen != gen:
            self._sel_profile_gen = gen
            self._sel_profile_rows.clear()
        uniq, inv = np.unique(requests, axis=0, return_inverse=True)
        missing = [
            u for u in range(len(uniq))
            if uniq[u].tobytes() not in self._sel_profile_rows
        ]
        if missing:
            table = np.asarray(
                self._profile_table(uniq[np.asarray(missing)])
            ).astype(np.int64)
            for row, u in enumerate(missing):
                self._sel_profile_rows[uniq[u].tobytes()] = table[row]
        dense = np.stack(
            [self._sel_profile_rows[uniq[u].tobytes()] for u in range(len(uniq))]
        )[inv]
        reps_col = replicas.astype(np.int64)[:, None]
        avail = np.where(
            dense == int(_MI), reps_col, np.where(dense < 0, reps_col, dense)
        )
        # zero-replica rows short-circuit to the sentinel path exactly
        # like merge_estimates (avail == replicas == 0 everywhere)
        avail = np.where(reps_col == 0, 0, avail)
        return np.minimum(avail, int(_MI)).astype(np.int32)

    def _schedule_host(
        self,
        problems: Sequence[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[ScheduleResult]:
        from ..utils.tracing import tracer

        with tracer.span("scheduler.host", rows=len(problems)):
            return self._schedule_host_rounds(problems, compiled)

    def _schedule_host_rounds(
        self,
        problems: Sequence[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[ScheduleResult]:
        """Ordered ClusterAffinities dispatch. Multi-term batches take the
        TENSORIZED first-fit path: the per-binding ranked affinity-group
        selection (ops.masks.first_fit_group) picks every row's group in
        one vectorized pass and the whole batch solves ONCE — a failover
        wave rescheduling thousands of displaced bindings costs one
        batched solve per chunk, not T sequential rounds. Multi-term rows
        that ALSO carry spread constraints keep the per-round loop (their
        per-term group search is a host search, and the combination is
        rare); single-term batches keep the plain one-round path."""
        max_terms = max((len(cp.terms) for cp in compiled), default=1)
        if max_terms > 1:
            legacy_idx = [
                i
                for i, cp in enumerate(compiled)
                if len(cp.terms) > 1 and cp.spread_constraints
            ]
            if not legacy_idx:
                return self._schedule_ranked(problems, compiled)
            legacy = set(legacy_idx)
            ranked_idx = [i for i in range(len(problems)) if i not in legacy]
            results: list = [None] * len(problems)
            for res_i, res in zip(
                ranked_idx,
                self._schedule_ranked(
                    [problems[i] for i in ranked_idx],
                    [compiled[i] for i in ranked_idx],
                ),
            ):
                results[res_i] = res
            for res_i, res in zip(
                legacy_idx,
                self._schedule_round_loop(
                    [problems[i] for i in legacy_idx],
                    [compiled[i] for i in legacy_idx],
                ),
            ):
                results[res_i] = res
            return results
        return self._schedule_round_loop(problems, compiled)

    def _schedule_ranked(
        self,
        problems: Sequence[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[ScheduleResult]:
        out: list[ScheduleResult] = []
        for start in range(0, len(problems), self.chunk_size):
            out.extend(
                self._schedule_chunk_ranked(
                    list(problems[start : start + self.chunk_size]),
                    compiled[start : start + self.chunk_size],
                )
            )
        return out

    def _schedule_round_loop(
        self,
        problems: Sequence[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[ScheduleResult]:
        results: list[Optional[ScheduleResult]] = [None] * len(problems)
        max_terms = max((len(cp.terms) for cp in compiled), default=1)

        pending = list(range(len(problems)))
        for term_round in range(max_terms):
            if not pending:
                break
            in_round = [i for i in pending if term_round < len(compiled[i].terms)]
            if not in_round:
                break
            round_results = self._schedule_round(
                [problems[i] for i in in_round],
                [compiled[i] for i in in_round],
                term_round,
            )
            next_pending = []
            for i, res in zip(in_round, round_results):
                has_more = term_round + 1 < len(compiled[i].terms)
                if res.success or not has_more:
                    results[i] = res
                else:
                    next_pending.append(i)  # FitError -> try next group
            # bindings whose term list was exhausted before this round keep
            # their last failure
            for i in pending:
                if i not in in_round and results[i] is None:
                    results[i] = ScheduleResult(
                        key=problems[i].key, error="no affinity group fits"
                    )
            pending = next_pending
        for i, res in enumerate(results):
            if res is None:
                results[i] = ScheduleResult(key=problems[i].key, error="not scheduled")
        return results  # type: ignore[return-value]

    # -- internals ---------------------------------------------------------

    def _schedule_round(
        self,
        problems: list[BindingProblem],
        compiled: list[CompiledPlacement],
        term_round: int,
    ) -> list[ScheduleResult]:
        out: list[ScheduleResult] = []
        for start in range(0, len(problems), self.chunk_size):
            chunk = problems[start : start + self.chunk_size]
            cchunk = compiled[start : start + self.chunk_size]
            out.extend(self._schedule_chunk(chunk, cchunk, term_round))
        return out

    def _pack_chunk(
        self,
        problems: list[BindingProblem],
        compiled: list[CompiledPlacement],
        term_round: int,
        with_affinity: bool = True,
    ):
        """Vectorized packing: per-binding work is O(sparse entries); the
        O(B x C) mask algebra happens once per *unique* placement/GVK and is
        gathered by row — the constant-factor lever SURVEY.md section 7 calls
        out for label matching at fleet scale."""
        snap = self.snapshot
        b, c, r = len(problems), snap.num_clusters, len(snap.dims)
        dim_index = {d: j for j, d in enumerate(snap.dims)}
        disabled = self.disabled_plugins

        # --- unique placements -> stacked per-placement masks -------------
        cp_slot: dict[int, int] = {}
        unique_cps: list[CompiledPlacement] = []
        cp_idx = np.empty(b, np.int32)
        for i, cp in enumerate(compiled):
            slot = cp_slot.get(id(cp))
            if slot is None:
                slot = len(unique_cps)
                cp_slot[id(cp)] = slot
                unique_cps.append(cp)
            cp_idx[i] = slot
        aff_pl = np.stack(
            [cp.terms[min(term_round, len(cp.terms) - 1)][1] for cp in unique_cps]
        )
        spread_pl = np.stack([cp.spread_field_ok for cp in unique_cps])
        taint_pl = np.stack([cp.taint_ok for cp in unique_cps])
        static_pl = np.stack([cp.static_weights for cp in unique_cps])
        strategy = np.array([cp.strategy for cp in unique_cps], np.int32)[cp_idx]

        # --- unique GVKs -> per-GVK enablement masks ----------------------
        gvk_slot: dict[str, int] = {}
        gvk_masks: list[np.ndarray] = []
        gvk_idx = np.empty(b, np.int32)
        for i, p in enumerate(problems):
            slot = gvk_slot.get(p.gvk)
            if slot is None:
                slot = len(gvk_masks)
                gvk_slot[p.gvk] = slot
                gid = snap.gvk_vocab.get(p.gvk) if p.gvk else None
                if gid is None:
                    mask = (
                        np.zeros(c, bool)
                        if p.gvk and len(snap.gvk_vocab) > 0
                        else np.ones(c, bool)
                    )
                else:
                    word, bit = gid // 32, gid % 32
                    mask = (snap.gvk_bits[:, word] >> np.uint32(bit)) & 1 != 0
                gvk_masks.append(mask)
            gvk_idx[i] = slot
        api_gvk = np.stack(gvk_masks)

        # --- sparse per-binding state -------------------------------------
        replicas = np.fromiter((p.replicas for p in problems), np.int32, b)
        fresh = np.fromiter((p.fresh for p in problems), bool, b)
        prev = np.zeros((b, c), np.int32)
        evict = np.zeros((b, c), bool)
        requests = np.zeros((b, r), np.int64)
        pods_dim = dim_index.get("pods")
        for i, p in enumerate(problems):
            for name, reps in p.prev.items():
                j = snap.index.get(name)
                if j is not None:
                    prev[i, j] = reps
            for name in p.evict_clusters:
                j = snap.index.get(name)
                if j is not None:
                    evict[i, j] = True
            for d, q in p.requests.items():
                j = dim_index.get(d)
                if j is not None:
                    requests[i, j] = q
            if pods_dim is not None and p.replicas > 0:
                # each replica occupies a pod (getAllowedPodNumber)
                requests[i, pods_dim] = max(requests[i, pods_dim], 1)
        prev_mask = prev > 0

        # --- mask composition (api_enablement.go / taint_toleration.go
        # leniency for already-placed clusters) -----------------------------
        feasible = np.ones((b, c), bool)
        if with_affinity and "ClusterAffinity" not in disabled:
            feasible &= aff_pl[cp_idx]
        if "SpreadConstraint" not in disabled:
            feasible &= spread_pl[cp_idx]
        if "APIEnablement" not in disabled:
            feasible &= api_gvk[gvk_idx] | (
                prev_mask & ~snap.complete_enablements[None, :]
            )
        if "TaintToleration" not in disabled:
            feasible &= taint_pl[cp_idx] | prev_mask
        if "ClusterEviction" not in disabled:
            feasible &= ~evict
        for custom in self.custom_filters:
            feasible &= np.asarray(custom(snap, problems), bool)
        static_w = static_pl[cp_idx]
        return feasible, strategy, replicas, static_w, requests, prev, fresh

    def _profile_table(self, profiles_np: np.ndarray) -> jnp.ndarray:
        """int32[P, C] general+model availability per unique request profile
        (-1 where the cluster gives no answer). The shared estimator core of
        _availability and the device-resident fleet path (scheduler.fleet)."""
        snap = self.snapshot
        req = jnp.asarray(profiles_np)
        general = general_estimate(jnp.asarray(snap.available_cap), req)
        mp = snap.model_pack
        if self._models_active():
            # model path replaces the summary path where applicable, still
            # capped by allowed pods (general.go:63-94,118-135)
            from ..models import estimate_by_models

            # the implicit pods dimension is the allowedPods cap, applied
            # separately — models never declare it (general.go:96-114 vs
            # :198-249), so it must not defeat model applicability
            pods_dim = snap.dim_index("pods")
            req_models = (
                req.at[:, pods_dim].set(0) if pods_dim is not None else req
            )
            model_avail, applicable = estimate_by_models(
                jnp.asarray(mp.min_bounds),
                jnp.asarray(mp.counts),
                jnp.asarray(mp.covered),
                req_models,
            )
            if pods_dim is not None:
                allowed_pods = jnp.minimum(
                    jnp.maximum(jnp.asarray(snap.available_cap[:, pods_dim]), 0),
                    2**31 - 1,
                ).astype(jnp.int32)
                model_avail = jnp.minimum(model_avail, allowed_pods[None, :])
            use_model = jnp.asarray(mp.has_models)[None, :] & applicable
            general = jnp.where(use_model, model_avail, general)
        # clusters with no ResourceSummary give no answer (UnauthenticReplica)
        return jnp.where(
            jnp.asarray(snap.has_summary)[None, :], general, jnp.int32(-1)
        )

    def _profile_table_quota(
        self, profiles_np: np.ndarray, prof_ns: np.ndarray
    ) -> jnp.ndarray:
        """``_profile_table`` with the static-assignment quota ceiling
        folded per (profile, namespace) slot — the fleet table's interned
        profiles carry a cap-namespace id beside the request vector, so
        the device-resident path divides against cap-bounded availability
        with NO kernel-signature change. The fold mirrors the host merge:
        a constrained cell becomes a real estimator answer (min of the
        general answer — or the untouched sentinel — and the cap), an
        unconstrained cell passes through, including the -1 no-summary
        convention this table uses."""
        table = self._profile_table(profiles_np)
        q = self.quota
        prof_ns = np.asarray(prof_ns, np.int32)
        if q is None or not q.has_caps or not (prof_ns >= 0).any():
            return table
        caps_out = self._quota_caps_dev(prof_ns, profiles_np)
        mi = jnp.int32(2**31 - 1)
        return jnp.where(
            caps_out < mi,
            jnp.minimum(jnp.where(table < 0, mi, table), caps_out),
            table,
        )

    def _models_active(self) -> bool:
        """Whether the resource-model estimator path would answer — THE
        predicate _profile_table activates the model estimation with; the
        tiny-batch host fast path must gate on exactly the same condition
        or small batches would silently diverge from the device path."""
        return bool(
            feature_gate.enabled(CUSTOMIZED_CLUSTER_RESOURCE_MODELING)
            and self.snapshot.model_pack.has_models.any()
        )

    def _availability_np(
        self,
        requests: np.ndarray,
        replicas: np.ndarray,
        cap_rows: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Host mirror of ``_availability`` for the tiny-batch fast path
        (general + resource-model estimators — callers gate off
        out-of-tree estimators only): the shared ``host_profile_table``
        plus merge_estimates' exact sentinel semantics (no-summary -> no
        answer -> clamp to spec.Replicas; zero-replica short-circuit).
        ``cap_rows`` folds the static-assignment quota caps as one more
        estimator answer, mirroring the device path's merge order: min
        over estimates FIRST, then the zero-replica override, then the
        untouched-sentinel clamp."""
        mi = 2**31 - 1
        uniq, inv = np.unique(requests, axis=0, return_inverse=True)
        dense = host_profile_table(
            self.snapshot, uniq, models_active=self._models_active()
        )[inv]
        if cap_rows is not None:
            dense = np.minimum(
                dense, self._quota_caps_np(cap_rows, requests)
            )
        reps_col = replicas.astype(np.int64)[:, None]
        avail = np.where(reps_col == 0, mi, dense)
        avail = np.where(avail == mi, reps_col, avail)
        return np.minimum(avail, mi).astype(np.int32)

    def _availability(
        self,
        requests: np.ndarray,
        replicas: np.ndarray,
        cap_rows: Optional[np.ndarray] = None,
    ) -> jnp.ndarray:
        """calAvailableReplicas (core/util.go:54-104): min-merge over
        registered estimators, sentinel clamped to spec.Replicas.

        Request rows are interned host-side (np.unique): the general/model
        estimators run per unique profile ([U, C]) and per-binding rows are a
        gather — fleets carry few unique ReplicaRequirements, so this removes
        the O(B x C x R) division hot loop. ``cap_rows`` joins the merge as
        one more estimator answer (the static-assignment quota ceiling,
        MAX_INT32 = no constraint)."""
        profiles_np, prof_inv = np.unique(requests, axis=0, return_inverse=True)
        reps = jnp.asarray(replicas)
        general = self._profile_table(profiles_np)
        # profile -> binding gather ([U, C] -> [B, C])
        estimates = [general[jnp.asarray(prof_inv.astype(np.int32))]]
        if cap_rows is not None:
            estimates.append(self._quota_caps_dev(cap_rows, requests))
        for est in self.extra_estimators:
            # out-of-tree estimators see the full per-binding requests
            estimates.append(jnp.asarray(est(jnp.asarray(requests), reps)))
        return merge_estimates(reps, tuple(estimates))

    def _schedule_chunk(
        self,
        problems: list[BindingProblem],
        compiled: list[CompiledPlacement],
        term_round: int,
    ) -> list[ScheduleResult]:
        from ..utils.metrics import scheduling_algorithm_duration as algo_timer

        snap = self.snapshot
        with algo_timer.time(schedule_step="Filter"):
            feasible, strategy, replicas, static_w, requests, prev, fresh = (
                self._pack_chunk(problems, compiled, term_round)
            )
            # pad the binding axis to the next power of two (capped at the
            # chunk size) so jit traces are reused across differently-sized
            # batches; pad rows are no-candidate zero-replica bindings
            b = len(problems)
            padded = 1
            while padded < b:
                padded *= 2
            padded = min(padded, self.chunk_size)
            if padded > b:
                pad = padded - b
                feasible = np.pad(feasible, ((0, pad), (0, 0)))
                strategy = np.pad(strategy, (0, pad))
                replicas = np.pad(replicas, (0, pad))
                static_w = np.pad(static_w, ((0, pad), (0, 0)))
                requests = np.pad(requests, ((0, pad), (0, 0)))
                prev = np.pad(prev, ((0, pad), (0, 0)))
                fresh = np.pad(fresh, (0, pad))
        # tiny-batch host fast path: a handful of bindings pays more in
        # device round-trips (~0.1s fixed each over a tunnel) than the
        # whole problem costs in numpy. The vectorized-numpy divider is the
        # oracle-verified identity referent (tests/test_divider_np.py +
        # every bench run), so placements are bit-identical. The resource-
        # model estimator has its own exact numpy mirror (host_profile
        # _table models_active branch), so only out-of-tree estimators
        # force the device path.
        host_small = (
            padded * snap.num_clusters <= 1 << 16
            and not self.extra_estimators
        )
        cap_rows = self._quota_cap_rows(problems)
        if cap_rows is not None and padded > b:
            cap_rows = np.pad(cap_rows, (0, padded - b), constant_values=-1)
        with algo_timer.time(schedule_step="Score"):
            avail = (
                self._availability_np(requests, replicas, cap_rows)
                if host_small
                else self._availability(requests, replicas, cap_rows)
            )

        # Select: spread-constraint group selection narrows the candidate set
        from .spread import select_clusters_batch  # local import (cycle-free)

        with algo_timer.time(schedule_step="Select"):
            # avail stays on device unless a row carries spread constraints
            # (select pulls it lazily) — a constraint-free chunk does zero
            # device->host traffic between estimate and assign
            candidates = select_clusters_batch(
                snap, problems, compiled, term_round, feasible, avail, prev,
            )

        if host_small:
            # the numpy dispense packs (weight, last, index) into ONE int64
            # key; inputs beyond that bound (near-MAX availability with
            # large previous counts) must take the device kernels, which
            # have no such packing
            avail_np = np.asarray(avail)
            wmax = int(
                max(
                    int(avail_np.max(initial=0)) + int(prev.max(initial=0)),
                    int(static_w.max(initial=0)),
                    0,
                )
            )
            lmax = int(prev.max(initial=0)) + 1
            host_small = (wmax + 1) * lmax * snap.num_clusters < 2**63
        with algo_timer.time(schedule_step="AssignReplicas"):
            self.solve_batches += 1
            if host_small:
                from ..refimpl.divider_np import assign_batch_np

                assignment, unschedulable = assign_batch_np(
                    strategy, replicas, candidates, static_w,
                    avail_np, prev, fresh,
                )
            else:
                res = self._assign(
                    strategy, replicas, candidates, static_w, avail,
                    prev, fresh,
                )
                assignment = np.asarray(res.assignment)
                unschedulable = np.asarray(res.unschedulable)
        return self._unpack(problems, compiled, term_round, candidates,
                            assignment, unschedulable)

    def _schedule_chunk_ranked(
        self,
        problems: list[BindingProblem],
        compiled: list[CompiledPlacement],
    ) -> list[ScheduleResult]:
        """One chunk of the tensorized ordered-failover path: pack every
        term's mask as a [B, T, C] candidate tensor, pick each row's first
        fitting affinity group in one vectorized selection
        (ops.masks.first_fit_group — the divider's exact schedulability
        predicate), then solve the WHOLE chunk once against the selected
        masks. T ordered fallback groups cost T batched [B, C] reductions
        plus one solve, instead of up to T sequential solves."""
        from ..ops import masks as mops
        from ..ops.divide import AGGREGATED as S_AGG, DYNAMIC_WEIGHT as S_DYN
        from ..utils.metrics import scheduling_algorithm_duration as algo_timer

        snap = self.snapshot
        with algo_timer.time(schedule_step="Filter"):
            base, strategy, replicas, static_w, requests, prev, fresh = (
                self._pack_chunk(problems, compiled, 0, with_affinity=False)
            )
            b = len(problems)
            padded = 1
            while padded < b:
                padded *= 2
            padded = min(padded, self.chunk_size)
            if padded > b:
                pad = padded - b
                base = np.pad(base, ((0, pad), (0, 0)))
                strategy = np.pad(strategy, (0, pad))
                replicas = np.pad(replicas, (0, pad))
                static_w = np.pad(static_w, ((0, pad), (0, 0)))
                requests = np.pad(requests, ((0, pad), (0, 0)))
                prev = np.pad(prev, ((0, pad), (0, 0)))
                fresh = np.pad(fresh, (0, pad))
            # stacked per-placement term tensors (the ranked affinity-
            # group surface): bool[U, Tmax, C] + live-term counts
            cp_slot: dict[int, int] = {}
            unique_cps: list[CompiledPlacement] = []
            cp_idx = np.zeros(padded, np.int32)
            for i, cp in enumerate(compiled):
                slot = cp_slot.get(id(cp))
                if slot is None:
                    slot = len(unique_cps)
                    cp_slot[id(cp)] = slot
                    unique_cps.append(cp)
                cp_idx[i] = slot
            tmax = max(len(cp.terms) for cp in unique_cps)
            c = snap.num_clusters
            term_stack = np.zeros((len(unique_cps), tmax, c), bool)
            term_len_u = np.ones(len(unique_cps), np.int32)
            for u, cp in enumerate(unique_cps):
                term_len_u[u] = len(cp.terms)
                for t, (_name, mask) in enumerate(cp.terms):
                    term_stack[u, t] = mask
            disabled = self.disabled_plugins
            if "ClusterAffinity" in disabled:
                term_stack[:] = True

        host_small = (
            padded * snap.num_clusters <= 1 << 16
            and not self.extra_estimators
        )
        cap_rows = self._quota_cap_rows(problems)
        if cap_rows is not None and padded > b:
            cap_rows = np.pad(cap_rows, (0, padded - b), constant_values=-1)
        with algo_timer.time(schedule_step="Score"):
            avail = (
                self._availability_np(requests, replicas, cap_rows)
                if host_small
                else self._availability(requests, replicas, cap_rows)
            )

        with algo_timer.time(schedule_step="Select"):
            avail_np = np.asarray(avail)
            cand_tc = base[:, None, :] & term_stack[cp_idx]
            rank, _fit = mops.first_fit_group(
                cand_tc,
                term_len_u[cp_idx],
                avail_np.astype(np.int64),
                replicas.astype(np.int64),
                prev.astype(np.int64),
                (strategy == S_DYN) | (strategy == S_AGG),
                fresh.astype(bool),
            )
            feasible = np.take_along_axis(
                cand_tc, rank[:, None, None].astype(np.intp), axis=1
            )[:, 0, :]
            # spread selection still narrows single-term spread rows
            # (multi-term spread rows never reach this path)
            candidates = self._select_for_chunk(
                problems, compiled, feasible, avail, prev
            )

        if host_small:
            wmax = int(
                max(
                    int(avail_np.max(initial=0)) + int(prev.max(initial=0)),
                    int(static_w.max(initial=0)),
                    0,
                )
            )
            lmax = int(prev.max(initial=0)) + 1
            host_small = (wmax + 1) * lmax * snap.num_clusters < 2**63
        with algo_timer.time(schedule_step="AssignReplicas"):
            self.solve_batches += 1
            if host_small:
                from ..refimpl.divider_np import assign_batch_np

                assignment, unschedulable = assign_batch_np(
                    strategy, replicas, candidates, static_w,
                    avail_np, prev, fresh,
                )
            else:
                res = self._assign(
                    strategy, replicas, candidates, static_w, avail,
                    prev, fresh,
                )
                assignment = np.asarray(res.assignment)
                unschedulable = np.asarray(res.unschedulable)
        return self._unpack(problems, compiled, rank, candidates,
                            assignment, unschedulable)

    def _select_for_chunk(self, problems, compiled, feasible, avail, prev):
        from .spread import select_clusters_batch

        return select_clusters_batch(
            self.snapshot, problems, compiled, 0, feasible, avail, prev
        )

    def _assign(self, strategy, replicas, candidates, static_w, avail, prev, fresh):
        from ..ops.divide import AGGREGATED

        max_n = int(replicas.max(initial=0))
        c = candidates.shape[1] if candidates.ndim == 2 else 1
        wide, fast = kernel_variant(
            int(jnp.max(avail)) if avail.size else 0,
            int(static_w.max(initial=0)),
            int(prev.max(initial=0)),
            max_n,
            c,
        )
        return divide_replicas(
            jnp.asarray(strategy),
            jnp.asarray(replicas),
            jnp.asarray(candidates),
            jnp.asarray(static_w),
            avail,
            jnp.asarray(prev),
            jnp.asarray(fresh),
            has_aggregated=bool((strategy == AGGREGATED).any()),
            wide=wide,
            fast=fast,
        )

    def _unpack(
        self, problems, compiled, term_round, candidates, assignment, unschedulable
    ) -> list[ScheduleResult]:
        """Vectorized result building: one np.nonzero over the whole chunk
        replaces per-binding scans, and the feasible-cluster tuple is only
        materialized for zero-replica (non-workload) bindings — its sole
        consumer (the scheduler controller writes all feasible clusters as
        the schedule of a non-workload binding)."""
        snap = self.snapshot
        names = snap.names
        b = len(problems)
        has_candidates = candidates[:b].any(axis=1)
        rows, cols = np.nonzero(assignment[:b] > 0)
        boundaries = np.searchsorted(rows, np.arange(1, b))
        per_row = np.split(cols, boundaries)
        out = []
        per_row_term = isinstance(term_round, np.ndarray)
        for i, p in enumerate(problems):
            tr = int(term_round[i]) if per_row_term else term_round
            term_idx = min(tr, len(compiled[i].terms) - 1)
            term_name = compiled[i].terms[term_idx][0]
            if not has_candidates[i]:
                out.append(
                    ScheduleResult(
                        key=p.key,
                        affinity_name=term_name,
                        error="no clusters fit the placement",
                    )
                )
                continue
            if unschedulable[i]:
                out.append(
                    ScheduleResult(
                        key=p.key,
                        affinity_name=term_name,
                        error=INSUFFICIENT_ERROR,
                    )
                )
                continue
            row = assignment[i]
            placed = {names[j]: int(row[j]) for j in per_row[i]}
            feasible = (
                tuple(names[j] for j in np.flatnonzero(candidates[i]))
                if p.replicas == 0
                else ()
            )
            out.append(
                ScheduleResult(
                    key=p.key,
                    clusters=placed,
                    feasible=feasible,
                    affinity_name=term_name,
                )
            )
        return out
