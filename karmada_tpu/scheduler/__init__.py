"""Batched TPU scheduler (ref: pkg/scheduler)."""

from .core import BindingProblem, ScheduleResult, TensorScheduler  # noqa: F401
from .snapshot import (  # noqa: F401
    ClusterSnapshot,
    CompiledPlacement,
    compile_affinity,
    compile_placement,
    strategy_code,
)
