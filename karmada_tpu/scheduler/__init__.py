"""Batched TPU scheduler (ref: pkg/scheduler)."""

from .core import BindingProblem, ScheduleResult, TensorScheduler  # noqa: F401
from .quota import (  # noqa: F401
    QUOTA_EXCEEDED_ERROR,
    QUOTA_EXCEEDED_REASON,
    QuotaSnapshot,
    build_quota_snapshot,
)
from .snapshot import (  # noqa: F401
    ClusterSnapshot,
    CompiledPlacement,
    compile_affinity,
    compile_placement,
    strategy_code,
)
