"""Trace-signature manifest + AOT prewarming: kill the cold start.

The fleet engine already ledgers every XLA trace signature it dispatches
(``FleetTable._mark_trace`` — the ``new_trace_last_pass`` warm-loop
contract). This module makes that ledger DURABLE and REPLAYABLE:

- ``TraceManifest`` persists, for every fresh trace, the kernel name, the
  ledger key, the exact input shapes/dtypes, and the static-argument
  tuple — everything needed to re-lower and re-compile that trace in a
  process that has never scheduled anything.
- ``replay()`` walks the manifest and runs each record ONCE on
  zero-filled dummy inputs — no engine, no real data — which traces,
  compiles (a persistent-cache hit when a prior process seeded it), and
  leaves the jit DISPATCH cache hot, so the first real dispatch is a
  straight cache hit. AOT ``lower().compile()`` alone is not enough: it
  populates the compile caches but the first dispatch still re-traces
  and re-loads on the serving path (measured at ~1.5× a steady wave). A
  record whose kernel rejects zeros falls back to exactly that AOT
  compile. Everything happens OFF the serving path.
- ``warmup()`` is the boot-phase entry (the ``karmadactl-tpu warmup``
  verb, the localup/solver ``--warmup-manifest`` boot stage, and the
  opt-in fleet-rebuild background thread all land here).

Shape-bucket canonicalization: the engine's static caps are already
quantized (pow2 chunk/slot caps, quarter-octave entry caps, M/D-quantum
wire caps), so a fleet of a given size maps to a small, stable signature
set. ``replay(expand=True)`` additionally compiles the NEXT bucket of
each tuned cap (entry/meta/delta), so a churn burst that grows a cap
mid-storm lands on an already-compiled bucket instead of minting a fresh
compile on the critical path. Grown specs carry no ledger key — the
signature genuinely was not observed, so ``new_trace_last_pass`` still
reports it honestly; only the compile is prepaid.

Shrink buckets (the compaction/rebucket family) expand too: a settle
train's demand collapses toward the cap FLOORS (sustained-shrink
policy), so for each observed record the predecessor bucket and the
floor bucket of each tuned cap are synthesized WITH their derived
ledger keys — the key is a pure function of the record's key and the
substituted cap element, so seeding it after a successful compile is
honest (the compile genuinely happened; the first settle dispatch is a
dispatch-cache hit). Without these, a restored 1M-shape engine minted a
fresh multi-second solve trace mid-settle (BENCH_r05 pass 5).

Restore contract: after ``replay()`` ran in this process, an engine
constructed with the same manifest seeds its fleet ledger from the
manifest keys, so its FIRST pass over a covered fleet shape reports
``new_trace=False`` — warm loops (and HA failovers) skip straight to the
timed window. Seeding without replay would be a lie (the compile would
still run at first dispatch), so it is gated on the replay having
actually happened (``TraceManifest.warmed``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

#: per manifest path, the record canons replay() COMPILED in this
#: process — the honesty gate for ledger seeding (see module docstring).
#: Per-record, not per-path: a partial warm (stale record vs new build,
#: transient backend error) must seed only the keys whose compile
#: actually succeeded, or the first pass claims new_trace=False while a
#: compile still runs on the serving path.
_WARMED: dict[str, set[str]] = {}
#: per manifest path, the ledger keys replay() proved compiled — the
#: observed records' keys plus the DERIVED shrink-bucket keys (which
#: have no manifest record to recover a key from, hence key set rather
#: than canon set).
_WARMED_KEYS: dict[str, set] = {}
_WARM_LOCK = threading.Lock()

_SCHEMA_VERSION = 1

#: kernels worth persisting: the solve-family traces dominate compile
#: cost; tiny utility kernels (row scatter, meta gather) stay ledger-only.
#: This is the jax-free NAME mirror of fleet.FLEET_KERNELS —
#: TraceManifest._load filters on it without importing the engine;
#: _jit_registry asserts the two stay in lockstep (and graftlint IR004
#: machine-checks it in tier-1). Values are the ``row_coupled``
#: delta-safety declarations — the jax-free mirror of each kernel's own
#: ``row_coupled`` attribute, checked for agreement (and proven against
#: the traced jaxprs) by graftlint IR006.
_KERNELS = {
    "fleet_solve": True,
    "fleet_pass": True,
    "fleet_entries": True,
    "fleet_bits": False,
    "quota_admit": True,
    "quota_cluster_caps": False,
    "explain_pass": False,
    "preempt_select": True,
}


def _jit_registry() -> dict:
    from . import fleet

    registry = dict(fleet.FLEET_KERNELS)
    assert set(registry) == set(_KERNELS), (sorted(registry), sorted(_KERNELS))
    return registry


def _retuple(v):
    """JSON round-trip inverse: lists back to tuples, recursively (ledger
    keys and the ``fast`` static are tuples; JSON stores them as lists)."""
    if isinstance(v, list):
        return tuple(_retuple(x) for x in v)
    return v


def _canon(record: dict) -> str:
    """Content identity of a record (dedup key): kernel + shapes +
    statics. The ledger key is derived from those, so it is excluded —
    an expanded spec (key=None) must dedup against an observed record
    with the same compile inputs."""
    return json.dumps(
        [record["kernel"], record["in_shapes"], record["statics"]],
        sort_keys=True,
    )


class TraceManifest:
    """File-backed ledger of compile-ready trace records.

    One instance per path; safe to share across engines in a process.
    Recording never raises into the scheduler (best-effort persistence);
    writes are atomic (tmp + rename) so a crashed writer cannot corrupt
    the manifest a future boot restores from."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.records: list[dict] = []
        self._seen: set[str] = set()
        self._lock = threading.Lock()
        self._load()

    @property
    def warmed(self) -> bool:
        """True when ``replay()`` completed for this path in this
        process (possibly a partial warm — see ``warmed_keys``)."""
        return self.path in _WARMED

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                data = json.load(f)
            records = data.get("records", [])
        except (OSError, ValueError):
            return
        # under the lock like every other records/_seen mutation: _load
        # also runs via restore-time re-instantiation while engine threads
        # may hold the same manifest object (one instance per path)
        with self._lock:
            for r in records:
                if r.get("kernel") in _KERNELS and "in_shapes" in r:
                    c = _canon(r)
                    if c not in self._seen:
                        self._seen.add(c)
                        self.records.append(r)

    # called-with-lock-held helper (the *_locked convention): load() and
    # record() hold self._lock around it, so the self.records read is
    # serialized with every writer  # graftlint: disable=GL011
    def _save(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        payload = {
            "version": _SCHEMA_VERSION,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
            "records": self.records,
        }
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=None, separators=(",", ":"))
        os.replace(tmp, self.path)

    def record(self, kernel: str, key, arrays, statics: dict) -> None:
        """Persist one fresh trace: ``key`` is the fleet ledger tuple (or
        None for synthesized bucket specs), ``arrays`` the positional
        kernel inputs in dispatch order, ``statics`` the static kwargs.
        No-op for already-known records."""
        rec = {
            "kernel": kernel,
            "key": key if key is None else list(_listify(key)),
            "in_shapes": [
                [list(int(d) for d in a.shape), str(a.dtype)]
                for a in arrays
            ],
            "statics": {k: _listify(v) for k, v in statics.items()},
        }
        c = _canon(rec)
        with self._lock:
            if c in self._seen:
                return
            self._seen.add(c)
            self.records.append(rec)
            try:
                self._save()
            except OSError:
                pass  # persistence is best-effort; the ledger still holds

    def annotate_memory(self, rec_canon: str, memory: dict) -> None:
        """Attach a compiled record's XLA ``memory_analysis()`` footprint
        (temp/output/argument/generated-code bytes) to the matching
        manifest record — the durable half of the device-memory ledger
        (ISSUE 12 b): a future boot can read the compile-time memory
        bill without recompiling. ``memory`` excludes itself from record
        identity (``_canon`` keys on kernel/shapes/statics only), so
        annotation never forks a record. Best-effort persistence, like
        ``record``."""
        with self._lock:
            for r in self.records:
                if _canon(r) == rec_canon:
                    if r.get("memory") == memory:
                        return
                    r["memory"] = memory
                    try:
                        self._save()
                    except OSError:
                        pass  # the in-memory annotation still holds
                    return

    def keys(self) -> set:
        """The observed ledger keys, as tuples (seeding form)."""
        with self._lock:
            records = list(self.records)
        return {
            _retuple(r["key"])
            for r in records
            if r.get("key") is not None
        }

    def warmed_keys(self) -> set:
        """The ledger keys ``replay()`` proved compiled in this process —
        the only keys an engine may seed its new-trace ledger from:
        observed records' keys plus derived shrink-bucket keys. Empty
        before replay; excludes records whose compile failed (their
        trace would still run at first dispatch)."""
        ok = _WARMED.get(self.path)
        if not ok:
            return set()
        with self._lock:
            records = list(self.records)
        keys = {
            _retuple(r["key"])
            for r in records
            if r.get("key") is not None and _canon(r) in ok
        }
        keys.update(_WARMED_KEYS.get(self.path, set()))
        return keys


def _listify(v):
    if isinstance(v, tuple):
        return [_listify(x) for x in v]
    return v


def _statics_from_json(statics: dict) -> dict:
    """Inverse of record(): lists back to tuples (``fast``), everything
    else verbatim. A meshed record's ``mesh`` static is its canonical
    SHAPE tuple (parallel.mesh.mesh_shape) — kept in shape form here so
    content signatures round-trip byte-identically (the IR004 canon);
    ``replay()`` materializes a live Mesh over the booting process's
    devices just before compiling (parallel.mesh.materialize_mesh_statics
    — a backend that cannot host the recorded shape fails that record,
    which keeps it out of ``warmed_keys`` and off the seeded ledger)."""
    return {k: _retuple(v) for k, v in statics.items()}


def _cap_prev(cap: int) -> Optional[int]:
    """Largest quantized entry cap strictly below ``cap`` (None at the
    1024 floor) — the bucket a sustained shrink lands on next. Bisects
    against ``_cap_round`` (monotone, rounds up) so the result tracks
    the engine's quantization policy verbatim."""
    from .fleet import _cap_round

    if cap <= 1024:
        return None
    lo, hi = 1, cap - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if _cap_round(mid) < cap:
            lo = mid
        else:
            hi = mid - 1
    return _cap_round(lo)


#: kernel -> {static name: index of that cap in the record's ledger key}
#: (fleet.l_key / _e_key / a_key layouts). Shrink-bucket derivation
#: substitutes the cap element of an OBSERVED key; the sanity check in
#: expand_records (key[idx] == statics[cap]) keeps a layout drift from
#: ever seeding a wrong key.
_KEY_CAP_INDEX = {
    "fleet_solve": {"e_cap": 8},
    "fleet_entries": {"e_cap": 6},
    "fleet_pass": {"m_cap": 10, "d_cap": 11},
}


def _derived(r: dict, updates: dict) -> Optional[dict]:
    """A synthesized record: ``r`` with the cap statics in ``updates``
    substituted and the ledger key re-derived by element substitution.
    None when the observed key does not match the declared layout."""
    idx_map = _KEY_CAP_INDEX.get(r["kernel"], {})
    key = list(r["key"]) if r.get("key") is not None else None
    statics = dict(r["statics"])
    for name, cap in updates.items():
        if key is not None:
            i = idx_map.get(name)
            if i is None or i >= len(key) or key[i] != statics.get(name):
                key = None  # layout drift: compile-only, never seed
            else:
                key[i] = cap
        statics[name] = cap
    return {
        "kernel": r["kernel"],
        "key": key,
        "in_shapes": r["in_shapes"],
        "statics": statics,
    }


def expand_records(records: list[dict]) -> list[dict]:
    """Shape-bucket expansion: for each observed record, synthesize the
    NEXT bucket of each tuned wire cap (so mid-storm cap growth lands on
    a prepaid compile) and the PREDECESSOR + FLOOR buckets (so a settle
    train's sustained shrink does too). Grown specs have key=None (the
    signature was never dispatched; the ledger must stay honest); shrink
    specs carry their derived key — see the module docstring."""
    from .fleet import D_FLOOR, D_ROUND, M_ROUND, _cap_round, d_round

    out: list[dict] = []
    seen = {_canon(r) for r in records}

    def _emit(rec: dict) -> None:
        c = _canon(rec)
        if c not in seen:
            seen.add(c)
            out.append(rec)

    for r in records:
        statics = dict(r["statics"])
        grown: list[dict] = []
        shrunk: list[dict] = []
        if r["kernel"] in ("fleet_solve", "fleet_entries"):
            e_cap = statics.get("e_cap")
            if isinstance(e_cap, int):
                grown.append({**statics, "e_cap": _cap_round(e_cap + 1)})
                prev = _cap_prev(e_cap)
                if prev is not None:
                    shrunk.append({"e_cap": prev})
                    if prev > 1024:
                        shrunk.append({"e_cap": 1024})
        elif r["kernel"] == "fleet_pass":
            m_cap = statics.get("m_cap")
            d_cap = statics.get("d_cap", 0)
            if isinstance(m_cap, int):
                # the engine's m_round: 4096 floor, then M_ROUND
                # multiples, clamped to the padded row count (the rows
                # input, position 5) — rounding the cap's successor lands
                # on the bucket the engine would actually tune to next
                # (adding a raw quantum to the 4096 floor does not)
                n_pad = r["in_shapes"][5][0][0]
                nxt = (
                    -(-(m_cap + 1) // M_ROUND) * M_ROUND
                    if m_cap + 1 > 4096
                    else 4096
                )
                nxt = min(nxt, n_pad)
                if nxt > m_cap:
                    grown.append({**statics, "m_cap": nxt})
            if isinstance(d_cap, int) and d_cap > 0:
                # same successor-rounding for the delta cap (D_FLOOR,
                # then D_ROUND multiples)
                grown.append({**statics, "d_cap": d_round(d_cap + 1)})
            # shrink: the settle train tunes each cap down its own
            # sustain vote, so cover the single-step predecessors and
            # the joint floor state the train terminates in
            m_floor = (
                min(4096, r["in_shapes"][5][0][0])
                if isinstance(m_cap, int)
                else None
            )
            m_prev = None
            if isinstance(m_cap, int) and m_cap > m_floor:
                q = (m_cap - 1) // M_ROUND * M_ROUND
                m_prev = q if q > m_floor else m_floor
            d_prev = None
            if isinstance(d_cap, int) and d_cap > D_FLOOR:
                q = (d_cap - 1) // D_ROUND * D_ROUND
                d_prev = q if q > D_FLOOR else D_FLOOR
            if m_prev is not None:
                shrunk.append({"m_cap": m_prev})
            if d_prev is not None:
                shrunk.append({"d_cap": d_prev})
            floors = {}
            if m_prev is not None:
                floors["m_cap"] = m_floor
            if d_prev is not None:
                floors["d_cap"] = D_FLOOR
            if floors:
                shrunk.append(floors)
        for st in grown:
            _emit(
                {
                    "kernel": r["kernel"],
                    "key": None,
                    "in_shapes": r["in_shapes"],
                    "statics": st,
                }
            )
        for updates in shrunk:
            d = _derived(r, updates)
            if d is not None:
                _emit(d)
    return out


def replay(manifest: TraceManifest, *, expand: bool = True) -> dict:
    """AOT-compile every manifest record (plus expanded buckets) on the
    current backend. Returns stats; per-record failures are counted, not
    raised — a manifest written by an older build must degrade to a
    partial warm, never block boot."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    registry = _jit_registry()
    records = list(manifest.records)
    specs = records + (expand_records(records) if expand else [])
    # dedup expanded specs against observed ones
    seen: set[str] = set()
    todo = []
    for r in specs:
        c = _canon(r)
        if c not in seen:
            seen.add(c)
            todo.append(r)
    compiled = failed = 0
    ok_canons: set[str] = set()
    ok_keys: set = set()
    errors: list[str] = []
    # kernel -> {temp/output/argument/generated_code bytes}: the MAX
    # footprint across this replay's records per kernel family — what an
    # operator budgets HBM against (karmada_tpu_kernel_memory_bytes)
    memory_by_kernel: dict[str, dict] = {}
    t0 = time.perf_counter()
    for r in todo:
        fn = registry.get(r["kernel"])
        if fn is None:
            failed += 1
            continue
        try:
            shapes = [
                (tuple(shape), np.dtype(dtype))
                for shape, dtype in r["in_shapes"]
            ]
            statics = _statics_from_json(r["statics"])
            # a meshed record carries its mesh as the canonical shape;
            # build the live mesh over THIS process's devices (raises —
            # counting the record failed — when the backend cannot host
            # it, so an 8-chip record can never fake-warm a 1-chip boot)
            from ..parallel.mesh import materialize_mesh_statics

            statics = materialize_mesh_statics(statics)
            aot = None
            try:
                # one dummy-data execution: trace + compile (persistent-
                # cache hit when seeded) + run, leaving the jit dispatch
                # cache hot — the first REAL dispatch then skips tracing
                # and cache-loading entirely
                args = [jnp.zeros(s, d) for s, d in shapes]
                jax.block_until_ready(fn(*args, **statics))
                del args
            except Exception:  # noqa: BLE001 — zeros tripped the kernel
                # fall back to AOT compile: the caches still fill, only
                # the first dispatch re-traces (off the compile cliff).
                # Kept for the memory hook below — never re-lowered.
                aot = fn.lower(
                    *(jax.ShapeDtypeStruct(s, d) for s, d in shapes),
                    **statics,
                ).compile()
            compiled += 1
            ok_canons.add(_canon(r))
            if r.get("key") is not None:
                # proved-compiled ledger key (observed or derived
                # shrink bucket) — the seeding surface of warmed_keys()
                ok_keys.add(_retuple(r["key"]))
            # device-memory footprint (ISSUE 12 b), best-effort: an
            # already-annotated record reuses its stored footprint —
            # zero extra lowerings on every boot after the first; a
            # fresh record pays ONE extra lowering (the compile itself
            # is a cache hit behind the execution above / the persistent
            # cache warmup enables at threshold 0).
            try:
                mem = r.get("memory")
                if mem is None:
                    if aot is None:
                        aot = fn.lower(
                            *(
                                jax.ShapeDtypeStruct(s, d)
                                for s, d in shapes
                            ),
                            **statics,
                        ).compile()
                    ma = aot.memory_analysis()
                    if ma is not None:
                        mem = {
                            "temp_bytes": int(ma.temp_size_in_bytes),
                            "output_bytes": int(ma.output_size_in_bytes),
                            "argument_bytes": int(
                                ma.argument_size_in_bytes
                            ),
                            "generated_code_bytes": int(
                                ma.generated_code_size_in_bytes
                            ),
                        }
                        if r.get("key") is not None:
                            manifest.annotate_memory(_canon(r), mem)
                if mem:
                    slot = memory_by_kernel.setdefault(r["kernel"], {})
                    for kind, v in mem.items():
                        slot[kind] = max(slot.get(kind, 0), int(v))
            except Exception:  # noqa: BLE001 — footprint is telemetry
                pass
        except Exception as e:  # noqa: BLE001 — partial warm beats no boot
            failed += 1
            if len(errors) < 5:
                errors.append(f"{r['kernel']}: {e!r}")
    stats = {
        "records": len(records),
        "specs": len(todo),
        "compiled": compiled,
        "failed": failed,
        "seconds": round(time.perf_counter() - t0, 3),
    }
    if errors:
        stats["errors"] = errors
    if memory_by_kernel:
        stats["memory_bytes"] = {
            k: dict(sorted(v.items()))
            for k, v in sorted(memory_by_kernel.items())
        }
        from ..utils.metrics import kernel_memory_bytes

        for kernel, mem in memory_by_kernel.items():
            for kind, v in mem.items():
                kernel_memory_bytes.set(
                    v, kernel=kernel, kind=kind.removesuffix("_bytes"),
                )
    # compile-lifecycle metric hook (ISSUE 6 b): off-serving-path prewarm
    # compiles show on /metrics beside the serving-path compile counter,
    # so an operator can see a boot's compile bill vs the storm's
    from ..utils.metrics import kernel_prewarmed

    if compiled:
        kernel_prewarmed.inc(compiled, result="compiled")
    if failed:
        kernel_prewarmed.inc(failed, result="failed")
    with _WARM_LOCK:
        _WARMED.setdefault(manifest.path, set()).update(ok_canons)
        _WARMED_KEYS.setdefault(manifest.path, set()).update(ok_keys)
    return stats


def warmup(
    manifest_path: Optional[str] = None, *, expand: bool = True
) -> dict:
    """Boot-phase prewarm: enable the persistent cache with a zero
    persistence threshold (every warmed trace must survive the process),
    load the manifest, and replay it. The entry point behind the
    ``karmadactl-tpu warmup`` verb and the localup/solver
    ``--warmup-manifest`` boot stage."""
    from ..utils import compilecache

    path = manifest_path or compilecache.default_manifest_path()
    if not path:
        return {"records": 0, "specs": 0, "compiled": 0, "failed": 0,
                "seconds": 0.0, "manifest": "", "cache_dir": ""}
    cache_dir = compilecache.enable(min_compile_secs=0.0)
    manifest = TraceManifest(path)
    stats = replay(manifest, expand=expand)
    stats["manifest"] = manifest.path
    stats["cache_dir"] = cache_dir
    # the boot's scheduling-mesh identity rides the warmup stats so the
    # operator (and the orchestrator scraping the JSON line) can tell a
    # single-chip from an 8-chip plane before any engine is built
    from ..parallel.mesh import mesh_shape, resolve_mesh

    try:
        stats["mesh"] = mesh_shape(resolve_mesh(None))
    except Exception as exc:  # noqa: BLE001 — a misconfigured mesh env
        # fails loudly at ENGINE construction; warmup only reports
        stats["mesh"] = f"error: {exc}"
    return stats


def resolve_boot_manifest(flag: Optional[str]) -> str:
    """The ``--warmup-manifest`` resolution rule shared by the solver
    sidecar and the localup serve/replica boot phases: a flag left unset
    (None) falls back to ``$KARMADA_TPU_TRACE_MANIFEST``; an EXPLICIT
    ``""`` opts out even with the env var set. Returns the manifest path
    ("" = disabled)."""
    if flag is not None:
        return flag
    from ..utils.compilecache import MANIFEST_ENV

    return os.environ.get(MANIFEST_ENV, "")


def resolve_manifest(spec) -> Optional[TraceManifest]:
    """Normalize an engine's ``trace_manifest`` argument: a TraceManifest
    passes through, a path string wraps, None falls back to the env
    default (``KARMADA_TPU_TRACE_MANIFEST``; unset/empty = disabled —
    engines never write a manifest the operator didn't ask for)."""
    if isinstance(spec, TraceManifest):
        return spec
    if isinstance(spec, str):
        return TraceManifest(spec) if spec else None
    if spec is None:
        from ..utils.compilecache import MANIFEST_ENV

        path = os.environ.get(MANIFEST_ENV, "")
        return TraceManifest(path) if path else None
    raise TypeError(f"trace_manifest: expected TraceManifest, str or None, "
                    f"got {type(spec).__name__}")


_REBUILD_WARMED: set[str] = set()


def prewarm_on_rebuild(manifest: Optional[TraceManifest]) -> None:
    """Opt-in background prewarm when a fleet table is (re)built: replay
    the manifest on a daemon thread so the rebuilt table's upcoming
    shapes compile OFF the serving path. Enabled by
    ``KARMADA_TPU_PREWARM_ON_REBUILD=1``; once per manifest per
    process."""
    if manifest is None:
        return
    if os.environ.get("KARMADA_TPU_PREWARM_ON_REBUILD") not in ("1", "true"):
        return
    with _WARM_LOCK:
        if manifest.path in _REBUILD_WARMED:
            return
        _REBUILD_WARMED.add(manifest.path)

    def _bg() -> None:
        try:
            replay(manifest)
        except Exception:  # noqa: BLE001 — warmers never take the plane down
            logging.getLogger("karmada_tpu").exception(
                "background prewarm of %s failed; serving path will "
                "compile on first dispatch instead", manifest.path
            )

    threading.Thread(
        target=_bg, name="fleet-prewarm", daemon=True
    ).start()
