"""Topology group selection: the region-DFS of spread constraints.

Faithful re-execution of pkg/scheduler/core/spreadconstraint/
{select_groups.go, select_clusters_by_region.go, group_clusters.go}: feasible
group combinatorics are small (regions per fleet, not clusters), so this
bounded search stays on host while scoring inputs (availability, locality
scores) come from the batched device kernels (SURVEY.md section 7: "keep
bounded search on host, tensorize scoring only").

Semantics mirrored:
- group score (group_clusters.go:138-330): Duplicated counts clusters whose
  availability covers the full replica count; Divided walks the score-ordered
  clusters until both cluster-min-groups and ceil(replicas/minGroups) are
  covered; 1000x weighting makes capacity dominate score averages.
- selectGroups DFS (select_groups.go:102-224): combinations of regions whose
  total cluster count reaches the cluster min-groups, path length within
  [minGroups, maxGroups]; ties broken by weight desc, value desc, discovery
  id; subpaths preferred over superpaths.
- region assembly (select_clusters_by_region.go:28-70): best cluster per
  chosen region, remainder filled by (score desc, avail desc) up to the
  cluster max-groups (0 max-groups quirk preserved: region-only constraints
  select exactly one cluster per region).
- zone/provider-only constraints are unsupported in the reference
  (select_clusters.go:58 "just support cluster and region") -> FitError here
  too.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Mapping, Optional

import numpy as np

from ..api.policy import SpreadConstraint
from .snapshot import ClusterSnapshot

WEIGHT_UNIT = 1000  # group_clusters.go:134


def calc_group_score(
    members: list[int],  # cluster indices in global (score, avail) order
    score: np.ndarray,
    credited: np.ndarray,
    duplicated: bool,
    replicas: int,
    group_min_groups: int,
    cluster_min_groups: int,
) -> int:
    """group_clusters.go:138-330."""
    if duplicated:
        valid = [j for j in members if int(credited[j]) >= replicas]
        sum_valid_score = sum(int(score[j]) for j in valid)
        n = len(valid)
        return n * WEIGHT_UNIT + (sum_valid_score // n if n else 0)

    target = math.ceil(replicas / max(group_min_groups, 1))
    cmg = max(cluster_min_groups, group_min_groups)
    sum_avail = 0
    sum_score = 0
    valid = 0
    for j in members:
        sum_avail += int(credited[j])
        sum_score += int(score[j])
        valid += 1
        if valid >= cmg and sum_avail >= target:
            break
    if sum_avail < target:
        return sum_avail * WEIGHT_UNIT + sum_score // max(len(members), 1)
    return target * WEIGHT_UNIT + sum_score // max(valid, 1)


@dataclass
class _Group:
    name: str
    value: int  # number of clusters
    weight: int  # group score


@dataclass
class _Path:
    groups: list[_Group] = field(default_factory=list)
    id: int = 0


def _find_feasible_paths(
    groups: list[_Group], min_c: int, max_c: int, target: int
) -> list[tuple[list[_Group], int, int, int]]:
    """select_groups.go:146-190. Returns (sorted groups, weight, value, id)."""
    groups = sorted(groups, key=lambda g: (g.value, -g.weight, g.name))
    paths: list[tuple[list[_Group], int, int, int]] = []
    stack: list[_Group] = []
    counter = [0]

    def dfs(total: int, begin: int) -> None:
        if total >= target and min_c <= len(stack) <= max_c:
            counter[0] += 1
            chosen = sorted(stack, key=lambda g: (-g.weight, g.name))
            paths.append(
                (
                    chosen,
                    sum(g.weight for g in chosen),
                    sum(g.value for g in chosen),
                    counter[0],
                )
            )
            return
        if len(stack) >= max_c:
            return
        for i in range(begin, len(groups)):
            stack.append(groups[i])
            dfs(total + groups[i].value, i + 1)
            if len(groups) == min_c:
                # select_groups.go:180-182: break without popping — every
                # ancestor frame breaks on the same condition, so the dirty
                # stack is never observed
                return
            stack.pop()

    dfs(0, 0)
    return paths


def _prioritize_paths(
    paths: list[tuple[list[_Group], int, int, int]]
) -> list[_Group]:
    """select_groups.go:192-224: weight desc, value desc, id asc; then prefer
    the shortest matching sub-path."""
    paths = sorted(paths, key=lambda p: (-p[1], -p[2], p[3]))
    final = paths[0]
    for cand in paths[1:]:
        fg, cg = final[0], cand[0]
        if len(cg) < len(fg) and all(
            fg[i].name == g.name for i, g in enumerate(cg)
        ):
            final = cand
    return final[0]


def select_groups(
    groups: list[_Group], min_c: int, max_c: int, target: int
) -> list[_Group]:
    if not groups:
        return []
    if max_c <= 0:
        max_c = len(groups)
    paths = _find_feasible_paths(groups, min_c, max_c, target)
    if not paths:
        return []
    return _prioritize_paths(paths)


def select_by_topology_groups(
    snap: ClusterSnapshot,
    by_field: Mapping[str, SpreadConstraint],
    order: np.ndarray,  # feasible clusters in (score desc, avail desc) order
    score: np.ndarray,
    credited: np.ndarray,
    need: int,
    duplicated: bool,
    replicas: int,
) -> Optional[np.ndarray]:
    """selectBestClustersByRegion (select_clusters_by_region.go:28-70).
    Returns selected cluster indices or None (FitError)."""
    if "region" not in by_field:
        # zone/provider without region: unsupported upstream -> FitError
        return None
    region_sc = by_field["region"]
    cluster_sc = by_field.get("cluster", SpreadConstraint(min_groups=0, max_groups=0))

    regions: dict[str, list[int]] = {}
    for j in order:
        if int(snap.region_ids[j]) == 0:
            continue
        # real region names: group-name tiebreaks sort lexicographically
        regions.setdefault(snap.clusters[j].spec.region, []).append(int(j))

    if len(regions) < max(region_sc.min_groups, 1):
        return None

    groups = [
        _Group(
            name=name,
            value=len(members),
            weight=calc_group_score(
                members,
                score,
                credited,
                duplicated,
                replicas,
                region_sc.min_groups,
                cluster_sc.min_groups,
            ),
        )
        for name, members in regions.items()
    ]
    chosen = select_groups(
        groups, region_sc.min_groups, region_sc.max_groups, cluster_sc.min_groups
    )
    if not chosen:
        return None

    selected: list[int] = []
    candidates: list[int] = []
    for g in chosen:
        members = regions[g.name]
        selected.append(members[0])  # best cluster per region
        candidates.extend(members[1:])
    need_cnt = len(selected) + len(candidates)
    if need_cnt > cluster_sc.max_groups:
        need_cnt = cluster_sc.max_groups
    rest = need_cnt - len(selected)
    if rest > 0:
        candidates.sort(key=lambda j: (-int(score[j]), -int(credited[j]), j))
        selected.extend(candidates[:rest])
    return np.asarray(selected, np.int64)
