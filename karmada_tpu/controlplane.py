"""ControlPlane: the whole system wired together in one process.

The analogue of hack/local-up-karmada.sh + the cmd/ binaries: a store (the
apiserver role), the reconciler fleet, the tensor scheduler, estimators and
member clients — composed for in-process operation. Tests and the demo drive
it deterministically with ``settle()``; a real deployment runs the same
controllers against remote stores/members.

Usage:
    cp = ControlPlane()
    cp.join_cluster(new_cluster("member1"), member_state)
    cp.store.apply(template); cp.store.apply(policy)
    cp.settle()          # -> works applied into member clusters
"""

from __future__ import annotations

from typing import Optional, Sequence

from .api.cluster import Cluster
from .controllers import (
    ApplicationFailoverController,
    BindingController,
    BindingStatusController,
    ClusterController,
    ClusterStatusController,
    DependenciesDistributor,
    Descheduler,
    ExecutionController,
    FederatedResourceQuotaController,
    GracefulEvictionController,
    NamespaceSyncController,
    ResourceDetector,
    SchedulerController,
    TaintManager,
    WorkloadRebalancerController,
    WorkStatusController,
)
from .estimator import AccurateEstimator, EstimatorRegistry, NodeSnapshot
from .interpreter import default_interpreter
from .utils import Runtime, Store
from .utils.member import MemberCluster, MemberClientRegistry


class ControlPlane:
    def __init__(
        self,
        *,
        enable_descheduler: bool = False,
        # ISSUE 14: the continuous drift-rebalance tier (bounded-
        # disruption re-placement off a per-tick dry solve). Off by
        # default like the estimator descheduler — benches and scarcity
        # deployments opt in.
        enable_drift_rebalancer: bool = False,
        enable_accurate_estimator: bool = False,
        # disabled by default like the reference (controllermanager.go:213-214)
        enable_member_hpa_sync: bool = False,
        eviction_timeout: float = 600.0,
        clock=None,
        # Pull-cluster lease staleness threshold (ClusterLeaseDuration
        # analogue); process-level harnesses shorten it so agent-death
        # failover is observable in wall-clock test time
        lease_grace_seconds: float = None,
        # --plugins enable/disable list + out-of-tree filter plugins
        # (cmd/scheduler/app/options/options.go:130-165 analogue)
        disabled_scheduler_plugins=(),
        scheduler_filter_plugins=(),
        # out-of-process solver sidecar (karmada_tpu.solver.RemoteSolver):
        # routes Score/Assign over gRPC instead of the in-proc engine
        solver=None,
        # external admission (webhook.server.RemoteAdmission hooks): every
        # store write round-trips a TLS webhook process instead of the
        # in-proc chain (cmd/webhook deployment shape)
        admission_override=None,
        delete_admission_override=None,
        # HA replica mode: run the controller fleet over an EXTERNAL store
        # (a bus ReplicaStoreFacade) — reads hit the local mirror, writes
        # round-trip the primary which owns admission. Two planes over one
        # store + Lease leader election = the reference's --leader-elect
        # active-standby shape for controller-manager/scheduler.
        store=None,
    ) -> None:
        import time as _time

        self.clock = clock or _time.time
        from .webhook import default_admission_chain

        self.admission = default_admission_chain()
        if store is not None:
            self.store = store
        else:
            self.store = Store(
                admission=admission_override or self.admission.admit,
                delete_admission=(
                    delete_admission_override or self.admission.admit_delete
                ),
            )
        self.runtime = Runtime()
        self.members = MemberClientRegistry()
        self.interpreter = default_interpreter()
        self.estimators = EstimatorRegistry()

        from .controllers.propagation import WorkIndex

        self.detector = ResourceDetector(self.store, self.runtime, self.interpreter)
        # one shared Work index (informer-indexer analogue) serves the
        # binding, work-status and binding-status controllers
        self.work_index = WorkIndex(self.store)
        self.binding_controller = BindingController(
            self.store, self.runtime, self.interpreter,
            work_index=self.work_index,
        )
        self.execution_controller = ExecutionController(
            self.store, self.runtime, self.members, self.interpreter
        )
        self.work_status_controller = WorkStatusController(
            self.store, self.runtime, self.members, self.interpreter,
            work_index=self.work_index,
        )
        self.binding_status_controller = BindingStatusController(
            self.store, self.runtime, self.detector,
            work_index=self.work_index,
        )
        status_kw = (
            {"lease_grace_seconds": lease_grace_seconds}
            if lease_grace_seconds is not None
            else {}
        )
        self.cluster_status_controller = ClusterStatusController(
            self.store, self.runtime, self.members, clock=self.clock,
            **status_kw,
        )
        self.cluster_controller = ClusterController(self.store, self.runtime)
        self.taint_manager = TaintManager(self.store, self.runtime, clock=self.clock)
        self.graceful_eviction = GracefulEvictionController(
            self.store, self.runtime, timeout_seconds=eviction_timeout,
            clock=self.clock,
        )
        self.app_failover = ApplicationFailoverController(
            self.store, self.runtime, clock=self.clock
        )
        extra = []
        self._accurate_enabled = enable_accurate_estimator
        # node snapshots track member state (the estimator server's informer
        # refresh); rebuilt each settle pass. No-op while accurate estimators
        # are disabled so the addon toggle works after construction.
        self.runtime.add_ticker(self._refresh_estimators)
        self.scheduler = SchedulerController(
            self.store,
            self.runtime,
            extra_estimators=extra,
            disabled_plugins=disabled_scheduler_plugins,
            custom_filters=scheduler_filter_plugins,
            clock=self.clock,
            solver=solver,
            estimator_registry=self.estimators,
        )
        self.descheduler = (
            Descheduler(self.store, self.runtime, self.members, clock=self.clock)
            if enable_descheduler
            else None
        )
        if enable_drift_rebalancer:
            from .controllers.rebalance import ContinuousDescheduler

            self.drift_rebalancer = ContinuousDescheduler(
                self.store, self.runtime, self.scheduler, clock=self.clock
            )
        else:
            self.drift_rebalancer = None
        self.dependencies_distributor = DependenciesDistributor(
            self.store, self.runtime, self.interpreter
        )
        self.namespace_sync = NamespaceSyncController(self.store, self.runtime)
        self.workload_rebalancer = WorkloadRebalancerController(
            self.store, self.runtime, clock=self.clock
        )
        self.frq_controller = FederatedResourceQuotaController(
            self.store, self.runtime, self.members
        )
        from .controllers.autoscaling import (
            CronFederatedHPAController,
            FederatedHPAController,
        )

        self.federated_hpa = FederatedHPAController(
            self.store, self.runtime, self.members, clock=self.clock
        )
        self.cron_federated_hpa = CronFederatedHPAController(
            self.store, self.runtime, clock=self.clock
        )
        from .controllers.mcs import (
            MultiClusterServiceController,
            ServiceExportController,
        )

        self.service_export = ServiceExportController(
            self.store, self.runtime, self.members
        )
        self.multicluster_service = MultiClusterServiceController(
            self.store, self.runtime, self.members
        )
        from .controllers.mci import MultiClusterIngressController

        self.multicluster_ingress = MultiClusterIngressController(
            self.store, self.runtime, self.members
        )
        from .controllers.remedy import RemedyController
        from .metricsadapter import MetricsAdapter
        from .search import Proxy, SearchController

        self.remedy_controller = RemedyController(self.store, self.runtime)
        self.search = SearchController(self.store, self.runtime, self.members)
        self.proxy = Proxy(self.store, self.members, self.search.cache)
        self.metrics_adapter = MetricsAdapter(self.members)
        # the HPA controller consumes the SAME adapter facade (one cache/
        # state surface), not a private duplicate over the registry
        self.federated_hpa._metrics_adapter = self.metrics_adapter
        from .controllers.hpa_sync import (
            DeploymentReplicasSyncer,
            HpaScaleTargetMarker,
            UnifiedAuthController,
        )
        from .interpreter.declarative import CustomizationConfigManager

        if enable_member_hpa_sync:
            self.hpa_marker = HpaScaleTargetMarker(self.store, self.runtime)
            self.replicas_syncer = DeploymentReplicasSyncer(
                self.store, self.runtime, self.members
            )
        else:
            self.hpa_marker = None
            self.replicas_syncer = None
        self.unified_auth = UnifiedAuthController(self.store, self.runtime)
        self.interpreter_config = CustomizationConfigManager(
            self.store, self.runtime, self.interpreter
        )
        from .interpreter.webhook import WebhookConfigManager

        self.interpreter_webhooks = WebhookConfigManager(
            self.store, self.runtime, self.interpreter
        )
        self.agents: dict[str, object] = {}
        from .utils.register import RegistrationAuthority

        # token issuance + CSR approval + cert rotation for pull-mode agents
        # (pkg/karmadactl/register, agent-CSR-approving controller,
        # pkg/controllers/certificate/)
        self.authority = RegistrationAuthority(clock=self.clock)
        self.runtime.add_ticker(self._rotate_certificates)
        # per-member coredns-failure detectors (deployed explicitly via
        # add_sn_detector, like the reference's example binary)
        self.sn_detectors: dict[str, object] = {}

    # -- cluster lifecycle (karmadactl join/unjoin analogue) ---------------

    def join_cluster(
        self,
        cluster: Cluster,
        member: Optional[MemberCluster] = None,
        *,
        remote_agent: bool = False,
    ):
        """Register a member. Push mode: the control plane owns the client
        (karmadactl join); Pull mode: a KarmadaAgent runs "inside" the member
        and drives the work application itself (karmadactl register).
        ``remote_agent`` marks a Pull member whose agent runs OUT of process
        (python -m karmada_tpu.bus.agent over the store bus) — the plane
        registers only the inventory shell and never constructs a local
        agent; the real member state lives in the agent's process."""
        member = member or MemberCluster(cluster.name)
        self.members.register(member)
        if cluster.spec.sync_mode == "Pull" and not remote_agent:
            from .controllers.remedy import KarmadaAgent

            self.agents = getattr(self, "agents", {})
            self.agents[cluster.name] = KarmadaAgent(
                self.store, self.runtime, member, self.interpreter,
                clock=self.clock,
            )
        self.work_status_controller.watch_member(member)
        if self._accurate_enabled:
            self._register_estimator(cluster.name, member)
        self.store.apply(cluster)
        return member

    def unjoin_cluster(self, name: str) -> None:
        self.members.deregister(name)
        self.estimators.deregister(name)
        det = self.sn_detectors.pop(name, None)
        if det is not None:
            det.active = False
        self.store.delete("Cluster", name)
        # re-point the scheduler's estimator fan-out at the surviving
        # members — a stale batch estimator keeps the old cluster-column
        # layout and breaks the min-merge shape on the next reconcile
        if self._accurate_enabled:
            names = sorted(self.members.names())
            self.scheduler.extra_estimators = (
                [self.estimators.make_batch_estimator(names)] if names else []
            )

    # -- optional components (karmadactl addons analogue) ------------------

    def _register_estimator(self, cluster_name: str, member) -> None:
        snap_dims = ["cpu", "memory", "pods", "ephemeral-storage"]
        est = AccurateEstimator(cluster_name, NodeSnapshot(member.nodes, snap_dims))
        self.estimators.register(est)
        names = sorted(self.members.names())
        self.scheduler.extra_estimators = [self.estimators.make_batch_estimator(names)]

    def enable_accurate_estimators(self) -> None:
        """addons enable karmada-scheduler-estimator: deploy one estimator
        per member and point the scheduler's fan-out at them."""
        if self._accurate_enabled:
            return
        self._accurate_enabled = True
        for name in sorted(self.members.names()):
            self._register_estimator(name, self.members.get(name))

    def disable_accurate_estimators(self) -> None:
        if not self._accurate_enabled:
            return
        self._accurate_enabled = False
        for name in list(self.members.names()):
            self.estimators.deregister(name)
        self.scheduler.extra_estimators = []

    def add_sn_detector(self, cluster_name: str, probe=None):
        """Deploy the service-name-resolution detector into one member
        (cmd/service-name-resolution-detector-example)."""
        from .controllers.remedy import ServiceNameResolutionDetector

        member = self.members.get(cluster_name)
        if member is None:
            raise KeyError(f"unknown cluster {cluster_name}")
        prev = self.sn_detectors.get(cluster_name)
        if prev is not None:
            prev.active = False
        det = ServiceNameResolutionDetector(
            self.store, self.runtime, member, probe=probe
        )
        self.sn_detectors[cluster_name] = det
        return det

    def _rotate_certificates(self) -> None:
        """cert-rotation controller sweep over registered agent certs."""
        for cluster_name in list(self.authority.certificates):
            self.authority.rotate_if_needed(cluster_name)

    def _refresh_estimators(self) -> None:
        if not self._accurate_enabled:
            return
        import numpy as np

        snap_dims = ["cpu", "memory", "pods", "ephemeral-storage"]
        for name in self.members.names():
            member = self.members.get(name)
            est = self.estimators.get(name)
            if member is None or est is None:
                continue
            new = NodeSnapshot(member.nodes, snap_dims)
            old = est.snapshot
            # generation gate (EstimatorRegistry delta refresh): a fresh
            # NodeSnapshot always stamps a NEW generation, so carry the old
            # one forward when the packed capacities provably did not move
            # — the memoized estimates stay valid and the registry's
            # refresh pass skips this cluster. The packed array is a copy
            # made at build time, so comparing old vs new detects drift
            # even though both snapshots reference the same NodeState
            # objects.
            if old is not None and np.array_equal(old.available, new.available):
                new.generation = old.generation
            est.snapshot = new
            est.unschedulable = member.count_unschedulable(self.clock())

    # -- driving -----------------------------------------------------------

    def settle(self, max_steps: int = 100_000) -> int:
        """Run all reconcilers to a fixed point (deterministic e2e driver)."""
        total = 0
        for _ in range(16):  # tickers can cascade new work
            steps = self.runtime.run_until_settled(max_steps)
            total += steps
            if self.runtime.pending() == 0 and steps == 0:
                break
        return total
